"""Jaxpr abstract interpreter: ``maelstrom lint --ranges``.

The analysis stack can say what the tick *reads* (lane liveness, pass
6) and what it *costs* (IR/cost gate, passes 4-5), but not what its
values *can be*: CON204's counter-overflow check is a per-leaf
heuristic over hand-picked counters, the 2^20-tick horizon cap in
``make_sim_config`` was hand-derived, and nothing proved the composed
scatters in the models' apply loops never race on duplicate indices —
the classic silent-nondeterminism hazard on accelerator scatter units.
This pass is the missing third pillar: a forward **interval abstract
interpretation** of the traced fused tick (the same
``cost_model.trace_tick`` jaxpr the other passes share through the
trace cache), with per-leaf / per-*lane* int32 ranges — the message
pool's lane axis is resolved through the lane-liveness tagger, so the
DTICK deadline lane, the small TYPE enum lane, and the model's payload
lanes each carry their own range instead of one conflated join.

Per model x carry layout the analyzer:

- seeds every carry leaf from an abstract walk of the ``init_carry``
  jaxpr (no device, no concrete state);
- iterates the tick transfer to a fixed point, classifying each leaf
  element as *stable* or as a *counter* with a measured per-tick
  growth bound ``g``;
- widens counters **affinely in the horizon** ``T`` (``hi(T) = hi_fp +
  g * T``) instead of to infinity, then re-applies the tick transfer
  at the horizon state to verify the growth bound still holds there
  (leaves that fail — super-linear recurrences — widen to dtype-full
  and void their proof, ABS704);
- walks the tick once more at the horizon state recording every int32
  arithmetic site whose infinite-precision result escapes int32
  (ABS701), every gather/scatter whose resolved index range is
  provably outside its operand axis under a clamping mode (ABS703),
  and every non-commutative scatter whose index rows can alias
  (ABS702);
- binary-searches the largest power-of-two horizon with a clean walk —
  the entry's **proven** ``max_safe_horizon_log2`` — and, on failure,
  the minimal overflowing ``T``.

Rules (ABS7xx):

=======  ========================  ========  ==============================
rule     name                      severity  what it flags
=======  ========================  ========  ==============================
ABS700   range-manifest-updated    info      ``--update-ranges`` rewrote
                                             the manifest
ABS701   int32-overflow            error     an int32 value (an arithmetic
                                             site in the tick, or a carry
                                             counter extrapolated to the
                                             horizon) provably escapes
                                             int32 within the configured
                                             horizon — with the offending
                                             leaf/eqn and the minimal T
                                             that overflows
ABS702   scatter-write-race        error     a non-commutative scatter
                                             (overwrite mode) whose index
                                             rows can alias within one
                                             tick — XLA applies duplicate
                                             updates in unspecified order,
                                             so the result is silently
                                             nondeterministic
ABS703   oob-index                 error     a gather/scatter/dynamic-
                                             slice index range provably
                                             outside the operand axis
                                             under a clamping mode — jit
                                             clamps instead of raising,
                                             so the access silently reads/
                                             writes the wrong element
ABS704   range-unresolvable        warning   a carry leaf's growth could
                                             not be bounded (super-linear
                                             recurrence, unmodeled
                                             primitive, while_loop) — the
                                             leaf widened to dtype-full
                                             and the overflow verdict for
                                             it is vacuous (mirror of
                                             LNE605's widening)
ABS705   range-manifest-drift      error     the proven ranges differ from
                                             the checked-in manifest entry
                                             (warning + a re-record hint
                                             when the manifest was
                                             recorded under a different
                                             jax version —
                                             ``cost_model.toolchain_note``)
ABS706   range-manifest-missing    error     a registered model x layout
                                             has no manifest entry
ABS707   range-manifest-stale      warning   a manifest entry matches no
                                             registered model
ABS708   range-analysis-failure    error     ``get_model`` or the range
                                             analysis itself raised
=======  ========================  ========  ==============================

Soundness caveats (documented in doc/lint.md pass 7): interval
transfer functions over-approximate values, so a *clean* verdict is a
proof only up to the affine-widening assumption — the per-tick growth
``g`` measured at the abstract fixed point is assumed maximal, which
holds for the additive bounded-increment counters this runtime uses
(interval addition's growth is state-independent) and is re-checked by
one transfer application at the horizon state; leaves that fail the
re-check widen and are reported unproven rather than proven-safe.
Threefry/RNG primitives are opaque (full uint32 range, never an
overflow — wraparound there is intended), and uint32 arithmetic is
exempt from ABS701 (defined wraparound).
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import (Any, Dict, List, Optional, Sequence, Set, Tuple)

import numpy as np

from . import cost_model
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "ranges"

DEFAULT_RANGE_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "range_manifest.json")

INT32_MIN = -(1 << 31)
INT32_MAX = (1 << 31) - 1

# the largest power-of-two horizon the default analysis probes: one
# clean walk at 2^PROBE_LOG2 proves every smaller horizon (bounds are
# monotone in T). Past ~2^24 the netsim age-rank encoding and the
# cumulative fleet counters genuinely overflow, so probing higher only
# buys binary-search work on entries that can never pass.
PROBE_LOG2 = 24

# the production horizon make_sim_config enforces (netsim delivery-
# priority encoding) — headroom bits are quoted at this horizon
PRODUCTION_LOG2 = 20

# commutative scatter combiners: duplicate indices are deterministic
# for integer arithmetic, so only overwrite-mode scatters can race
_COMMUTATIVE_SCATTERS = frozenset(
    {"scatter-add", "scatter-mul", "scatter-min", "scatter-max",
     "scatter-and", "scatter-or", "scatter-xor"})

# RNG / bit-plumbing primitives whose outputs are deliberately the full
# dtype range: opaque, never an overflow (threefry wraparound is the
# point), never a widening note
_OPAQUE_PRIMS = frozenset(
    {"threefry2x32", "random_bits", "random_seed", "random_wrap",
     "random_unwrap", "random_fold_in", "random_split", "random_clone",
     "random_gamma", "bitcast_convert_type"})

Itv = Tuple[float, float]       # (lo, hi); python ints for int dtypes


def _itv_join(a: Optional[Itv], b: Optional[Itv]) -> Optional[Itv]:
    if a is None:
        return b
    if b is None:
        return a
    return (min(a[0], b[0]), max(a[1], b[1]))


def _dtype_itv(dtype) -> Optional[Itv]:
    """The full range of a dtype — the TOP element for tracked kinds,
    None (untracked) for floats and exotics."""
    kind = getattr(dtype, "kind", None)
    if kind == "b":
        return (0, 1)
    if kind in ("i", "u"):
        info = np.iinfo(dtype)
        return (int(info.min), int(info.max))
    return None


def _aval(v):
    return getattr(v, "aval", None)


def _shape(v) -> Tuple[int, ...]:
    return tuple(getattr(_aval(v), "shape", ()))


def _dtype(v):
    return getattr(_aval(v), "dtype", None)


def _is_var(v) -> bool:
    return not hasattr(v, "val")


@dataclass
class Val:
    """Abstract value of one array: a whole-array interval, plus an
    optional per-lane interval vector when the array is tagged with the
    wire-format lane axis (lane coordinate -> interval)."""
    itv: Optional[Itv]
    lanes: Optional[Tuple[Optional[Itv], ...]] = None

    def whole(self) -> Optional[Itv]:
        if self.lanes is not None:
            out: Optional[Itv] = None
            for li in self.lanes:
                out = _itv_join(out, li)
            return out if out is not None else self.itv
        return self.itv


def _val_join(a: Val, b: Val) -> Val:
    if a.lanes is not None and b.lanes is not None \
            and len(a.lanes) == len(b.lanes):
        return Val(None, tuple(_itv_join(x, y)
                               for x, y in zip(a.lanes, b.lanes)))
    return Val(_itv_join(a.whole(), b.whole()))


def _val_eq(a: Val, b: Val) -> bool:
    return a.whole() == b.whole() and a.lanes == b.lanes


def _const_val(arr, lane_axis: Optional[int], n_lanes: int) -> Val:
    """Exact Val of a concrete array (a jaxpr const or literal)."""
    try:
        a = np.asarray(arr)
    except Exception:
        return Val(None)
    if a.dtype.kind == "f":
        # float constants bound the latency-sampling chain (-mean *
        # log(u)); non-finite values stay untracked
        if a.size == 0 or not np.isfinite(a).all():
            return Val(None) if a.size else Val((0, 0))
        return Val((float(a.min()), float(a.max())))
    if a.dtype.kind not in "iub":
        return Val(None)
    if a.size == 0:
        return Val((0, 0))
    if lane_axis is not None and a.ndim > lane_axis \
            and a.shape[lane_axis] == n_lanes:
        moved = np.moveaxis(a, lane_axis, 0).reshape(n_lanes, -1)
        return Val(None, tuple((int(r.min()), int(r.max()))
                               for r in moved))
    return Val((int(a.min()), int(a.max())))


def _sub_closed(eqn):
    out = []
    for k, v in eqn.params.items():
        for sub in (v if isinstance(v, (list, tuple)) else (v,)):
            if hasattr(sub, "eqns") or hasattr(getattr(sub, "jaxpr", None),
                                               "eqns"):
                out.append((k, sub))
    return out


def _inner_jaxpr(sub):
    return sub.jaxpr if hasattr(sub, "jaxpr") else sub


# --- the interpreter -------------------------------------------------------


class _Interp:
    """One forward interval walk over a traced tick jaxpr.

    ``tagger`` is the lane-liveness ``_Analyzer`` (already folded and
    tagged) — it supplies the lane-axis map (which axis of which var is
    message-lane-shaped) and the constant folds used to resolve
    gather/scatter index columns exactly. ``None`` disables per-lane
    tracking (the init-carry walk needs none)."""

    def __init__(self, tagger, n_lanes: int, phase_of=None):
        self.tagger = tagger
        self.L = n_lanes
        self.notes: List[str] = []
        self.record = False
        self.overflow_sites: List[Dict[str, Any]] = []
        self.oob_sites: List[Dict[str, Any]] = []
        self.race_sites: List[Dict[str, Any]] = []
        self.scatter_census: Dict[str, int] = {}
        self._phase_ctx: Optional[str] = None
        self._phase_of = phase_of or cost_model._phase_of

    # -- plumbing --

    def note(self, msg: str):
        if msg not in self.notes:
            self.notes.append(msg)

    def _lane_axis(self, v) -> Optional[int]:
        if self.tagger is None or not _is_var(v):
            return None
        t = self.tagger._tag(v)
        if t is None:
            return None
        shp = _shape(v)
        if t < len(shp) and shp[t] == self.L:
            return t
        return None

    def _cval(self, v):
        if hasattr(v, "val"):
            try:
                return np.asarray(v.val)
            except Exception:
                return None
        if self.tagger is not None:
            return self.tagger.consts.get(v)
        return None

    def _get(self, env, v) -> Val:
        if hasattr(v, "val"):
            return _const_val(v.val, None, self.L)
        got = env.get(v)
        if got is not None:
            return got
        cv = self._cval(v)
        if cv is not None:
            return _const_val(cv, self._lane_axis(v), self.L)
        return Val(_dtype_itv(_dtype(v)))

    def _top(self, v) -> Val:
        return Val(_dtype_itv(_dtype(v)))

    def _mk(self, out_var, itv: Optional[Itv],
            lanes: Optional[Tuple] = None) -> Val:
        """Clamp a computed Val to the output's dtype range and attach
        lanes only when the output is lane-tagged."""
        top = _dtype_itv(_dtype(out_var))
        if top is None:
            # float outputs: no dtype clamp, but keep the bounds (the
            # latency-sampling chain rides through here)
            if lanes is not None:
                joined: Optional[Itv] = None
                for li in lanes:
                    joined = _itv_join(joined, li)
                itv = _itv_join(itv, joined)
            return Val(itv)

        def cl(i):
            if i is None:
                return top
            return (max(top[0], min(i[0], top[1])),
                    max(top[0], min(i[1], top[1])))
        if lanes is not None and self._lane_axis(out_var) is not None:
            return Val(None, tuple(cl(i) for i in lanes))
        if lanes is not None:
            joined: Optional[Itv] = None
            for li in lanes:
                joined = _itv_join(joined, li)
            itv = _itv_join(itv, joined)
        return Val(cl(itv))

    def _phase(self, eqn) -> str:
        return self._phase_ctx if self._phase_ctx is not None \
            else self._phase_of(eqn)

    def _check_ovf(self, eqn, lo, hi, in_itvs) -> Itv:
        """Record an ABS701 site when an int32 arithmetic result
        escapes int32 — only when every operand was itself strictly
        inside int32 (an already-saturated operand means the overflow
        was created, and reported, upstream), and at least one operand
        is runtime state (an all-constant wrap — e.g. the dead top
        square in a pow-by-squaring lowering — is a lowering artifact,
        not a horizon-reachable overflow)."""
        dt = _dtype(eqn.outvars[0])
        if getattr(dt, "kind", None) == "i" and np.dtype(dt).itemsize == 4 \
                and (lo < INT32_MIN or hi > INT32_MAX):
            clean_ins = all(
                i is not None and i[0] > INT32_MIN and i[1] < INT32_MAX
                for i in in_itvs)
            runtime_in = any(self._cval(v) is None
                             for v in eqn.invars)
            if self.record and clean_ins and runtime_in:
                self.overflow_sites.append({
                    "kind": "eqn", "prim": eqn.primitive.name,
                    "phase": self._phase(eqn),
                    "lo": int(lo), "hi": int(hi)})
        return (lo, hi)

    # -- the walk --

    def call(self, jaxpr, invals: Sequence[Val],
             consts: Sequence[Any] = ()) -> List[Val]:
        env: Dict[Any, Val] = {}
        for cv, cval in zip(getattr(jaxpr, "constvars", ()), consts):
            env[cv] = _const_val(cval, self._lane_axis(cv), self.L)
        for v, val in zip(jaxpr.invars, invals):
            env[v] = val
        self._walk(jaxpr, env)
        return [self._get(env, v) for v in jaxpr.outvars]

    def _walk(self, jaxpr, env):
        outer = self._phase_ctx
        for eqn in jaxpr.eqns:
            self._phase_ctx = outer if outer is not None \
                else self._phase_of(eqn)
            try:
                outs = self._eval_eqn(eqn, env)
            except Exception as e:  # a transfer bug must degrade, not die
                self.note(f"transfer for '{eqn.primitive.name}' raised "
                          f"{type(e).__name__} — widened to dtype-full")
                outs = [self._top(o) for o in eqn.outvars]
            for o, val in zip(eqn.outvars, outs):
                if _is_var(o) and type(o).__name__ != "DropVar":
                    env[o] = val
        self._phase_ctx = outer

    # -- per-primitive transfer --

    def _eval_eqn(self, eqn, env) -> List[Val]:
        name = eqn.primitive.name
        ins = [self._get(env, v) for v in eqn.invars]

        if name in ("add", "sub", "mul", "max", "min", "div", "rem"):
            return [self._binop(eqn, name, ins)]
        if name == "select_n":
            return [self._select_n(eqn, ins)]
        if name == "clamp":
            return [self._clamp(eqn, ins)]
        if name in ("neg", "abs", "sign", "not", "integer_pow",
                    "exp", "log", "sqrt", "rsqrt", "logistic", "tanh",
                    "erf", "floor", "ceil", "round", "square",
                    "is_finite", "population_count", "clz",
                    "stop_gradient", "copy", "real", "imag"):
            return [self._unop(eqn, name, ins[0])]
        if name in ("eq", "ne", "lt", "le", "gt", "ge"):
            return [self._mk(eqn.outvars[0], (0, 1))]
        if name in ("and", "or", "xor"):
            return [self._bitwise(eqn, name, ins)]
        if name in ("shift_left", "shift_right_logical",
                    "shift_right_arithmetic"):
            return [self._shift(eqn, name, ins)]
        if name == "convert_element_type":
            return [self._convert(eqn, ins[0])]
        if name in _OPAQUE_PRIMS:
            return [self._bitcast(eqn, ins[0]) if
                    name == "bitcast_convert_type" else self._top(o)
                    for o in eqn.outvars]
        if name in ("broadcast_in_dim", "reshape", "squeeze",
                    "transpose", "rev", "expand_dims"):
            return [self._shapeop(eqn, ins[0])]
        if name == "iota":
            return [self._iota(eqn)]
        if name == "concatenate":
            return [self._concat(eqn, ins)]
        if name == "slice":
            return [self._slice(eqn, ins[0])]
        if name == "pad":
            return [self._mk(eqn.outvars[0],
                             _itv_join(ins[0].whole(), ins[1].whole()),
                             ins[0].lanes)]
        if name in ("reduce_sum", "reduce_max", "reduce_min",
                    "reduce_or", "reduce_and", "reduce_prod"):
            return [self._reduce(eqn, name, ins[0])]
        if name in ("argmax", "argmin"):
            axes = tuple(int(a) for a in eqn.params.get("axes", ()))
            n = 1
            for a in axes:
                n *= _shape(eqn.invars[0])[a]
            return [self._mk(eqn.outvars[0], (0, max(0, n - 1)))]
        if name in ("cumsum", "cumlogsumexp", "cummax", "cummin",
                    "cumprod"):
            return [self._cumop(eqn, name, ins[0])]
        if name == "sort":
            return [Val(v.whole(), v.lanes if
                        self._lane_axis(o) is not None else None)
                    for v, o in zip(ins, eqn.outvars)]
        if name == "top_k":
            k_axis = _shape(eqn.invars[0])[-1]
            return [Val(ins[0].whole()),
                    self._mk(eqn.outvars[1], (0, max(0, k_axis - 1)))]
        if name == "gather":
            return [self._gather(eqn, ins, env)]
        if name.startswith("scatter"):
            return [self._scatter(eqn, name, ins, env)]
        if name == "dynamic_slice":
            return [self._dynamic_slice(eqn, ins)]
        if name == "dynamic_update_slice":
            return [self._dus(eqn, ins)]
        if name == "pjit" or name in ("closed_call", "core_call",
                                      "custom_jvp_call",
                                      "custom_vjp_call", "remat",
                                      "checkpoint"):
            return self._call_like(eqn, ins)
        if name == "scan":
            return self._scan(eqn, ins)
        if name == "cond":
            return self._cond(eqn, ins)
        if name == "while":
            return self._while(eqn, ins)
        if name in ("nextafter", "pow", "atan2", "uniform"):
            return [Val(None)]
        # unmodeled: dtype-full, noted once per primitive (the ABS704
        # mirror of LNE605's conservative widening)
        if any(getattr(_dtype(o), "kind", None) in ("i", "u")
               for o in eqn.outvars):
            self.note(f"unmodeled primitive '{name}' — outputs widened "
                      f"to dtype-full")
        return [self._top(o) for o in eqn.outvars]

    # elementwise helpers ---------------------------------------------------

    def _aligned_lanes(self, eqn, ins) -> Optional[List[Tuple]]:
        """Per-lane operand vectors when the op can run lane-wise: every
        operand is either lane-tagged (same coordinates) or a whole
        value broadcast across lanes."""
        if self._lane_axis(eqn.outvars[0]) is None:
            return None
        if not any(v.lanes is not None for v in ins):
            return None
        cols = []
        for v in ins:
            if v.lanes is not None:
                cols.append(v.lanes)
            else:
                cols.append((v.whole(),) * self.L)
        return [tuple(c[i] for c in cols) for i in range(self.L)]

    def _binop_itv(self, name, a: Optional[Itv], b: Optional[Itv],
                   eqn, record=True) -> Optional[Itv]:
        if a is None or b is None:
            return None
        (al, ah), (bl, bh) = a, b
        if name == "add":
            lo, hi = al + bl, ah + bh
        elif name == "sub":
            lo, hi = al - bh, ah - bl
        elif name == "mul":
            cs = (al * bl, al * bh, ah * bl, ah * bh)
            lo, hi = min(cs), max(cs)
        elif name == "max":
            lo, hi = max(al, bl), max(ah, bh)
        elif name == "min":
            lo, hi = min(al, bl), min(ah, bh)
        elif name == "rem":
            # sign follows the dividend; |r| < |divisor| and <= |dividend|
            m = max(abs(bl), abs(bh))
            m = max(0, m - 1) if isinstance(m, int) else m
            m = min(m, max(abs(al), abs(ah)))
            lo = 0 if al >= 0 else -m
            hi = 0 if ah <= 0 else m
        elif name == "div":
            dt = _dtype(eqn.outvars[0])
            if getattr(dt, "kind", None) in ("i", "u"):
                m = max(abs(al), abs(ah))
                lo = 0 if al >= 0 and bl >= 0 else -m
                hi = m
            else:
                return None
        else:
            return None
        if record:
            lo, hi = self._check_ovf(eqn, lo, hi, [a, b])
        return (lo, hi)

    def _binop(self, eqn, name, ins) -> Val:
        lanes_in = self._aligned_lanes(eqn, ins)
        if lanes_in is not None:
            lanes = tuple(self._binop_itv(name, a, b, eqn)
                          for a, b in lanes_in)
            return self._mk(eqn.outvars[0], None, lanes)
        return self._mk(eqn.outvars[0],
                        self._binop_itv(name, ins[0].whole(),
                                        ins[1].whole(), eqn))

    def _select_n(self, eqn, ins) -> Val:
        cases = ins[1:]
        lanes_in = self._aligned_lanes(eqn, [ins[0]] + list(cases))
        if lanes_in is not None:
            lanes = []
            for row in lanes_in:
                out: Optional[Itv] = None
                for c in row[1:]:
                    out = _itv_join(out, c)
                lanes.append(out)
            return self._mk(eqn.outvars[0], None, tuple(lanes))
        out: Optional[Itv] = None
        for c in cases:
            w = c.whole()
            if w is None:
                return Val(_dtype_itv(_dtype(eqn.outvars[0])))
            out = _itv_join(out, w)
        return self._mk(eqn.outvars[0], out)

    def _clamp(self, eqn, ins) -> Val:
        lo_v, x, hi_v = ins

        def one(lo_i, x_i, hi_i):
            if x_i is None or lo_i is None or hi_i is None:
                if lo_i is not None and hi_i is not None:
                    return (lo_i[0], hi_i[1])
                return None
            return (min(max(x_i[0], lo_i[0]), hi_i[0]),
                    min(max(x_i[1], lo_i[1]), hi_i[1]))
        lanes_in = self._aligned_lanes(eqn, ins)
        if lanes_in is not None:
            return self._mk(eqn.outvars[0], None,
                            tuple(one(a, b, c) for a, b, c in lanes_in))
        return self._mk(eqn.outvars[0],
                        one(lo_v.whole(), x.whole(), hi_v.whole()))

    def _unop(self, eqn, name, v: Val) -> Val:
        def one(i: Optional[Itv]) -> Optional[Itv]:
            if i is None:
                if name in ("sign",):
                    return (-1, 1)
                if name in ("logistic", "is_finite"):
                    return (0, 1)
                if name == "tanh":
                    return (-1, 1)
                if name == "erf":
                    return (-1, 1)
                if name in ("population_count", "clz"):
                    return (0, 64)
                return None
            lo, hi = i
            try:
                if name == "neg":
                    out = (-hi, -lo)
                elif name == "abs":
                    out = (0 if lo <= 0 <= hi else min(abs(lo), abs(hi)),
                           max(abs(lo), abs(hi)))
                elif name == "sign":
                    out = (-1 if lo < 0 else (0 if lo == 0 else 1),
                           1 if hi > 0 else (0 if hi == 0 else -1))
                    out = (min(out), max(out))
                elif name == "not":
                    dt = _dtype(eqn.outvars[0])
                    out = (0, 1) if getattr(dt, "kind", "") == "b" \
                        else (-hi - 1, -lo - 1)
                elif name == "integer_pow":
                    p = int(eqn.params["y"])
                    cs = [lo ** p, hi ** p] + ([0] if lo <= 0 <= hi
                                               else [])
                    out = (min(cs), max(cs))
                    out = self._check_ovf(eqn, out[0], out[1], [i])
                elif name == "exp":
                    out = (math.exp(min(lo, 700)), math.exp(min(hi, 700)))
                elif name == "log":
                    if lo <= 0:
                        return None
                    out = (math.log(lo), math.log(hi))
                elif name in ("sqrt",):
                    if lo < 0:
                        return None
                    out = (math.sqrt(lo), math.sqrt(hi))
                elif name == "rsqrt":
                    if lo <= 0:
                        return None
                    out = (1.0 / math.sqrt(hi), 1.0 / math.sqrt(lo))
                elif name in ("logistic", "is_finite"):
                    out = (0, 1)
                elif name in ("tanh", "erf"):
                    out = (-1, 1)
                elif name == "floor":
                    out = (math.floor(lo), math.floor(hi))
                elif name == "ceil":
                    out = (math.ceil(lo), math.ceil(hi))
                elif name == "round":
                    out = (round(lo), round(hi))
                elif name == "square":
                    cs = [lo * lo, hi * hi] + ([0] if lo <= 0 <= hi
                                               else [])
                    out = (min(cs), max(cs))
                    out = self._check_ovf(eqn, out[0], out[1], [i])
                elif name in ("population_count", "clz"):
                    out = (0, 64)
                elif name in ("stop_gradient", "copy", "real", "imag"):
                    out = i
                else:
                    return None
            except (OverflowError, ValueError):
                return None
            return out
        if v.lanes is not None and self._lane_axis(eqn.outvars[0]) \
                is not None:
            return self._mk(eqn.outvars[0], None,
                            tuple(one(i) for i in v.lanes))
        return self._mk(eqn.outvars[0], one(v.whole()))

    def _bitwise(self, eqn, name, ins) -> Val:
        def one(a: Optional[Itv], b: Optional[Itv]) -> Optional[Itv]:
            dt = _dtype(eqn.outvars[0])
            if getattr(dt, "kind", "") == "b":
                return (0, 1)
            if a is None or b is None:
                return None
            if name == "and" and (a[0] >= 0 or b[0] >= 0):
                # masking with a nonneg operand bounds the result by it
                # whatever the other side's sign (x & 1 stays [0, 1])
                hi = min(x[1] for x in (a, b) if x[0] >= 0)
                return (0, hi)
            if a[0] < 0 or b[0] < 0:
                # sign bits involved: bitwise results stay within the
                # magnitude envelope of the operands (two's complement)
                m = max(abs(a[0]), abs(a[1]), abs(b[0]), abs(b[1]), 1)
                bits = int(m).bit_length()
                return (-(1 << bits), (1 << bits) - 1)
            if name == "and":
                return (0, min(a[1], b[1]))
            # or/xor: bounded by the next power of two covering both;
            # or additionally dominates both operands (nonneg), which
            # keeps jax.random.uniform's mantissa|0x3f800000 pattern
            # recognizable for the bitcast-to-[1,2) transfer
            bits = max(int(a[1]).bit_length(), int(b[1]).bit_length())
            lo = max(a[0], b[0]) if name == "or" else 0
            return (lo, (1 << bits) - 1)
        lanes_in = self._aligned_lanes(eqn, ins)
        if lanes_in is not None:
            return self._mk(eqn.outvars[0], None,
                            tuple(one(a, b) for a, b in lanes_in))
        return self._mk(eqn.outvars[0],
                        one(ins[0].whole(), ins[1].whole()))

    def _shift(self, eqn, name, ins) -> Val:
        a, s = ins[0].whole(), ins[1].whole()
        if a is None or s is None:
            return self._top(eqn.outvars[0])
        # out-of-range shift amounts are undefined in XLA; clamping the
        # abstract amount to the defined window keeps e.g. the raft
        # vote bitmask (1 << src with a joined-lane src) bounded
        sl, sh = max(0, int(s[0])), int(min(max(s[1], 0), 63))
        if name == "shift_left":
            if a[0] < 0:
                return self._top(eqn.outvars[0])
            # shift_left is bit plumbing, not arithmetic: `1 << bit`
            # deliberately reaches the sign bit in the bitset idiom
            # (crdt.py _set_bit), so a shift past the dtype is the
            # defined wrap, never an ABS701 — the result just widens
            lo, hi = int(a[0]) << sl, int(a[1]) << sh
            top = _dtype_itv(_dtype(eqn.outvars[0]))
            if top is not None and hi > top[1]:
                return Val(top)
            return self._mk(eqn.outvars[0], (lo, hi))
        if a[0] < 0:
            if name == "shift_right_arithmetic":
                return self._mk(eqn.outvars[0],
                                (int(a[0]) >> sl, int(a[1]) >> sl))
            return self._top(eqn.outvars[0])   # logical shift of negative
        return self._mk(eqn.outvars[0],
                        (int(a[0]) >> sh, int(a[1]) >> sl))

    def _convert(self, eqn, v: Val) -> Val:
        src = _dtype(eqn.invars[0])
        dst = _dtype(eqn.outvars[0])
        dst_top = _dtype_itv(dst)

        def one(i: Optional[Itv]) -> Optional[Itv]:
            if i is None:
                return dst_top
            if dst_top is None:        # float destination: values pass
                return i
            lo, hi = i
            if getattr(src, "kind", "") == "f":
                if not (math.isfinite(lo) and math.isfinite(hi)):
                    return dst_top
                lo, hi = math.floor(lo), math.floor(hi)
            if getattr(dst, "kind", "") == "b":
                return (0, 1)
            if lo < dst_top[0] or hi > dst_top[1]:
                return dst_top       # wrapping conversion: full range
            return (int(lo), int(hi)) if getattr(dst, "kind", "") in \
                ("i", "u") else (lo, hi)
        if v.lanes is not None and self._lane_axis(eqn.outvars[0]) \
                is not None:
            return Val(None, tuple(one(i) for i in v.lanes))
        return Val(one(v.whole()))

    def _bitcast(self, eqn, v: Val) -> Val:
        """The one bitcast pattern worth modeling: mantissa bits OR'd
        with 0x3f800000 viewed as float32 — jax.random.uniform's
        [1, 2) construction. Everything else is opaque."""
        src, dst = _dtype(eqn.invars[0]), _dtype(eqn.outvars[0])
        w = v.whole()
        if getattr(dst, "kind", "") == "f" and \
                getattr(src, "kind", "") == "u" and w is not None \
                and 0x3F800000 <= w[0] and w[1] <= 0x3FFFFFFF:
            return Val((1.0, 2.0))
        return self._top(eqn.outvars[0])

    # structure -------------------------------------------------------------

    def _shapeop(self, eqn, v: Val) -> Val:
        if self._lane_axis(eqn.outvars[0]) is not None \
                and v.lanes is not None:
            return Val(None, v.lanes)
        if self._lane_axis(eqn.outvars[0]) is not None and \
                v.itv is not None:
            return Val(None, (v.itv,) * self.L)
        return Val(v.whole())

    def _iota(self, eqn) -> Val:
        shape = eqn.params["shape"]
        dim = int(eqn.params["dimension"])
        n = int(shape[dim])
        out = eqn.outvars[0]
        if self._lane_axis(out) == dim:
            return Val(None, tuple((i, i) for i in range(self.L)))
        return self._mk(out, (0, max(0, n - 1)))

    def _concat(self, eqn, ins) -> Val:
        out = eqn.outvars[0]
        axis = int(eqn.params["dimension"])
        la = self._lane_axis(out)
        if la == axis:
            # a row built lane-wise from pieces: splice per-lane vals
            lanes: List[Optional[Itv]] = []
            for v, piece in zip(ins, eqn.invars):
                size = _shape(piece)[axis]
                if v.lanes is not None and len(v.lanes) == size:
                    lanes.extend(v.lanes)
                else:
                    lanes.extend([v.whole()] * size)
            if len(lanes) == self.L:
                return Val(None, tuple(lanes))
        if la is not None:
            pieces = [v.lanes if v.lanes is not None
                      else (v.whole(),) * self.L for v in ins]
            return Val(None, tuple(
                _itv_join_many([p[i] for p in pieces])
                for i in range(self.L)))
        w: Optional[Itv] = None
        for v in ins:
            iv = v.whole()
            if iv is None:
                return Val(None)
            w = _itv_join(w, iv)
        return self._mk(out, w)

    def _slice(self, eqn, v: Val) -> Val:
        la_in = self._lane_axis(eqn.invars[0])
        out = eqn.outvars[0]
        if la_in is not None and v.lanes is not None:
            start = eqn.params["start_indices"][la_in]
            limit = eqn.params["limit_indices"][la_in]
            stride = (eqn.params["strides"] or
                      (1,) * len(_shape(eqn.invars[0])))[la_in]
            sel = v.lanes[start:limit:stride]
            if len(sel) == self.L and self._lane_axis(out) is not None:
                return Val(None, tuple(sel))
            return Val(_itv_join_many(list(sel)))
        return Val(v.whole(), v.lanes if
                   self._lane_axis(out) is not None else None)

    def _reduce(self, eqn, name, v: Val) -> Val:
        axes = tuple(int(a) for a in eqn.params.get("axes", ()))
        in_shape = _shape(eqn.invars[0])
        n = 1
        for a in axes:
            n *= int(in_shape[a])
        la = self._lane_axis(eqn.invars[0])
        if name == "reduce_sum":
            if la is not None and la in axes and v.lanes is not None:
                # summing the lane axis: per-lane bounds add exactly
                rest = n // self.L if self.L else n
                lo = sum((i[0] if i else INT32_MIN) for i in v.lanes)
                hi = sum((i[1] if i else INT32_MAX) for i in v.lanes)
                lo, hi = lo * max(1, rest), hi * max(1, rest)
            else:
                w = v.whole()
                if w is None:
                    return self._top(eqn.outvars[0])
                lo, hi = n * w[0] if w[0] < 0 else w[0] * n, n * w[1] \
                    if w[1] > 0 else w[1] * n
                lo, hi = min(lo, w[0] * n), max(hi, w[1] * n)
            lo, hi = self._check_ovf(eqn, lo, hi, [v.whole()])
            return self._mk(eqn.outvars[0], (lo, hi))
        if name == "reduce_prod":
            return self._top(eqn.outvars[0])
        # max/min/or/and keep the value envelope
        w = v.whole()
        if name in ("reduce_or", "reduce_and"):
            dt = _dtype(eqn.outvars[0])
            if getattr(dt, "kind", "") == "b":
                return self._mk(eqn.outvars[0], (0, 1))
        return self._mk(eqn.outvars[0], w)

    def _cumop(self, eqn, name, v: Val) -> Val:
        w = v.whole()
        if w is None:
            return self._top(eqn.outvars[0])
        if name == "cumsum":
            axis = int(eqn.params["axis"])
            n = int(_shape(eqn.invars[0])[axis])
            lo = min(w[0], w[0] * n)
            hi = max(w[1], w[1] * n)
            lo, hi = self._check_ovf(eqn, lo, hi, [w])
            return self._mk(eqn.outvars[0], (lo, hi))
        if name in ("cummax", "cummin"):
            return self._mk(eqn.outvars[0], w)
        return self._top(eqn.outvars[0])

    # gather / scatter / dynamic slicing ------------------------------------

    def _mode_name(self, eqn) -> str:
        return str(eqn.params.get("mode", "")).lower()

    def _record_oob(self, eqn, axis_size, lo, hi, what):
        if self.record:
            self.oob_sites.append({
                "prim": eqn.primitive.name, "phase": self._phase(eqn),
                "what": what, "axis_size": int(axis_size),
                "lo": int(lo), "hi": int(hi)})

    def _gather(self, eqn, ins, env) -> Val:
        operand, idx = ins[0], ins[1]
        dnums = eqn.params["dimension_numbers"]
        slice_sizes = tuple(int(s) for s in eqn.params["slice_sizes"])
        in_shape = _shape(eqn.invars[0])
        mode = self._mode_name(eqn)
        fill = "fill" in mode or "drop" in mode
        # ABS703: a clamping-mode gather whose index range is provably
        # outside the operand axis — resolve columns exactly when the
        # index array folded, else use the whole-index interval
        start_map = tuple(int(d) for d in dnums.start_index_map)
        arr = self._cval(eqn.invars[1])
        iw = idx.whole()
        if not fill:
            for col, d in enumerate(start_map):
                limit = in_shape[d] - slice_sizes[d]
                if arr is not None and arr.ndim >= 1 \
                        and arr.shape[-1] == len(start_map):
                    c = arr.reshape(-1, len(start_map))[:, col]
                    clo, chi = int(c.min()), int(c.max())
                elif iw is not None:
                    clo, chi = int(iw[0]), int(iw[1])
                else:
                    continue
                if clo > limit or chi < 0:
                    self._record_oob(eqn, in_shape[d], clo, chi,
                                     f"gather axis {d}")
        la = self._lane_axis(eqn.invars[0])
        out_la = self._lane_axis(eqn.outvars[0])
        if la is not None and operand.lanes is not None:
            if slice_sizes[la] == in_shape[la] and la not in \
                    set(int(d) for d in
                        getattr(dnums, "collapsed_slice_dims", ())):
                lanes = operand.lanes
                if fill:
                    lanes = tuple(_itv_join(i, (0, 0)) for i in lanes)
                if out_la is not None:
                    return Val(None, lanes)
                return Val(_itv_join_many(list(lanes)))
            if la in start_map:
                # lane-indexed gather: join the reachable lanes
                col = start_map.index(la)
                vals = None
                if arr is not None and arr.ndim >= 1 and \
                        arr.shape[-1] == len(start_map):
                    c = arr.reshape(-1, len(start_map))[:, col]
                    vals = range(max(0, int(c.min())),
                                 min(self.L, int(c.max()) + 1))
                elif iw is not None:
                    vals = range(max(0, int(iw[0])),
                                 min(self.L, int(iw[1]) + 1))
                w = slice_sizes[la]
                if vals is not None:
                    out: Optional[Itv] = None
                    for vstart in vals:
                        for lane in range(vstart,
                                          min(self.L, vstart + w)):
                            out = _itv_join(out, operand.lanes[lane])
                    if fill:
                        out = _itv_join(out, (0, 0))
                    return self._mk(eqn.outvars[0], out)
        w = operand.whole()
        if fill:
            w = _itv_join(w, (0, 0))
        return self._mk(eqn.outvars[0], w, (
            operand.lanes if out_la is not None else None))

    def _scatter_rows(self, eqn) -> int:
        """Index rows per batch slice: >1 means several updates can
        target the same operand element within one scatter."""
        dn = eqn.params["dimension_numbers"]
        idx_shape = _shape(eqn.invars[1])
        bdims = set(int(d) for d in
                    getattr(dn, "scatter_indices_batching_dims", ()))
        rows = 1
        for a, d in enumerate(idx_shape[:-1] if idx_shape else ()):
            if a not in bdims:
                rows *= int(d)
        return rows

    def _scatter_race(self, eqn, idx_val: Val):
        """ABS702: can this overwrite-mode scatter's index rows alias?
        Proof obligations, cheapest first: a single row per batch is
        trivially race-free; folded constant indices are checked for
        duplicates exactly; otherwise the pigeonhole on the resolved
        index-space cardinality applies; an unresolvable multi-row
        overwrite scatter is reported — "can alias" is exactly the
        hazard."""
        rows = self._scatter_rows(eqn)
        if rows <= 1:
            return
        dn = eqn.params["dimension_numbers"]
        sdims = tuple(int(d) for d in dn.scatter_dims_to_operand_dims)
        arr = self._cval(eqn.invars[1])
        if arr is not None and arr.ndim >= 1 and sdims and \
                arr.shape[-1] == len(sdims):
            bdims = tuple(int(d) for d in
                          getattr(dn, "scatter_indices_batching_dims",
                                  ()))
            moved = np.moveaxis(arr, bdims,
                                tuple(range(len(bdims)))) \
                if bdims else arr[None]
            flat = moved.reshape(np.prod(moved.shape[:len(bdims)] or
                                         (1,), dtype=int) if bdims
                                 else 1, -1, len(sdims))
            for batch in flat:
                uniq = {tuple(int(x) for x in row) for row in batch}
                if len(uniq) < len(batch):
                    if self.record:
                        self.race_sites.append({
                            "prim": eqn.primitive.name,
                            "phase": self._phase(eqn),
                            "why": "constant index rows contain "
                                   "duplicates", "rows": rows})
                    return
            return                       # constants proven distinct
        # pigeonhole on the abstract index space
        iw = idx_val.whole()
        in_shape = _shape(eqn.invars[0])
        if iw is not None:
            card = 1
            for d in sdims:
                lo = max(int(iw[0]), 0)
                hi = min(int(iw[1]), in_shape[d] - 1)
                card *= max(0, hi - lo + 1)
            if card < rows:
                if self.record:
                    self.race_sites.append({
                        "prim": eqn.primitive.name,
                        "phase": self._phase(eqn),
                        "why": f"pigeonhole: {rows} update rows over "
                               f"{card} reachable index tuples",
                        "rows": rows})
                return
        if self.record:
            self.race_sites.append({
                "prim": eqn.primitive.name, "phase": self._phase(eqn),
                "why": "index rows unresolvable — aliasing cannot be "
                       "ruled out", "rows": rows})

    def _scatter(self, eqn, name, ins, env) -> Val:
        operand, idx, updates = ins[0], ins[1], ins[2]
        dn = eqn.params["dimension_numbers"]
        in_shape = _shape(eqn.invars[0])
        mode = self._mode_name(eqn)
        if self.record:
            ph = self._phase(eqn)
            self.scatter_census[ph] = self.scatter_census.get(ph, 0) + 1
        if name == "scatter" and name not in _COMMUTATIVE_SCATTERS:
            self._scatter_race(eqn, idx)
        # ABS703 on clamping-mode scatters (drop-mode discards OOB)
        if "clip" in mode:
            iw = idx.whole()
            sdims = tuple(int(d) for d in dn.scatter_dims_to_operand_dims)
            arr = self._cval(eqn.invars[1])
            for col, d in enumerate(sdims):
                if arr is not None and arr.ndim >= 1 and \
                        arr.shape[-1] == len(sdims):
                    c = arr.reshape(-1, len(sdims))[:, col]
                    clo, chi = int(c.min()), int(c.max())
                elif iw is not None:
                    clo, chi = int(iw[0]), int(iw[1])
                else:
                    continue
                if clo > in_shape[d] - 1 or chi < 0:
                    self._record_oob(eqn, in_shape[d], clo, chi,
                                     f"scatter axis {d}")
        # value transfer
        if name == "scatter-add":
            rows = self._scatter_rows(eqn)
            ow, uw = operand.whole(), updates.whole()
            if ow is None or uw is None:
                return self._top(eqn.outvars[0])
            lo = ow[0] + rows * min(0, uw[0])
            hi = ow[1] + rows * max(0, uw[1])
            lo, hi = self._check_ovf(eqn, lo, hi, [ow, uw])
            return self._mk(eqn.outvars[0], (lo, hi), operand.lanes)
        la = self._lane_axis(eqn.invars[0])
        if la is not None and operand.lanes is not None:
            window_map = _scatter_window_map(dn, len(in_shape))
            sdims = tuple(int(d) for d in dn.scatter_dims_to_operand_dims)
            written: Optional[Set[int]] = None
            if la in window_map:
                up_shape = _shape(eqn.invars[2])
                w = up_shape[window_map[la]] \
                    if window_map[la] < len(up_shape) else self.L
                if w == in_shape[la]:
                    written = set(range(self.L))
                elif la in sdims:
                    # a partial lane window POSITIONED by an index
                    # column (jnp's .at[slice] / dynamic_update_slice
                    # lowerings): resolve the start(s) and write
                    # exactly the covered lanes — the gossip body
                    # write lands on its declared lanes instead of
                    # smearing the whole row
                    arr = self._cval(eqn.invars[1])
                    if arr is not None and arr.ndim >= 1 and sdims \
                            and arr.shape[-1] == len(sdims):
                        c = arr.reshape(-1, len(sdims))[
                            :, sdims.index(la)]
                        written = set()
                        for v in np.unique(c):
                            start = max(0, min(int(v), self.L - w))
                            written.update(range(start, start + w))
                else:
                    written = set(range(min(self.L, w)))
            elif la in sdims:
                arr = self._cval(eqn.invars[1])
                if arr is not None and arr.ndim >= 1 and sdims and \
                        arr.shape[-1] == len(sdims):
                    c = arr.reshape(-1, len(sdims))[:, sdims.index(la)]
                    written = {int(x) for x in np.unique(c)
                               if 0 <= int(x) < self.L}
            if written is None:
                written = set(range(self.L))
            uw = updates.whole()
            ul = updates.lanes
            lanes = []
            for i, cur in enumerate(operand.lanes):
                if i in written:
                    upd = ul[i] if ul is not None and \
                        len(ul) == self.L else uw
                    lanes.append(_itv_join(cur, upd))
                else:
                    lanes.append(cur)
            if self._lane_axis(eqn.outvars[0]) is not None:
                return Val(None, tuple(lanes))
            return Val(_itv_join_many(lanes))
        return self._mk(eqn.outvars[0],
                        _itv_join(operand.whole(), updates.whole()),
                        operand.lanes)

    def _dynamic_slice(self, eqn, ins) -> Val:
        operand = ins[0]
        in_shape = _shape(eqn.invars[0])
        out_shape = _shape(eqn.outvars[0])
        # ABS703: dynamic_slice always clamps its start
        for a, sv in enumerate(eqn.invars[1:]):
            limit = in_shape[a] - out_shape[a]
            w = self._get({}, sv).whole() if not _is_var(sv) else \
                ins[1 + a].whole()
            if w is not None and (w[0] > limit or w[1] < 0) \
                    and in_shape[a] != out_shape[a]:
                self._record_oob(eqn, in_shape[a], int(w[0]), int(w[1]),
                                 f"dynamic_slice axis {a}")
        la = self._lane_axis(eqn.invars[0])
        if la is not None and operand.lanes is not None:
            if out_shape[la] == in_shape[la]:
                if self._lane_axis(eqn.outvars[0]) is not None:
                    return Val(None, operand.lanes)
                return Val(_itv_join_many(list(operand.lanes)))
            sw = ins[1 + la].whole()
            if sw is not None:
                lo = max(0, int(sw[0]))
                hi = min(self.L - out_shape[la], int(sw[1]))
                sel = [operand.lanes[i]
                       for s in range(lo, hi + 1)
                       for i in range(s, s + out_shape[la])]
                if sel:
                    return self._mk(eqn.outvars[0],
                                    _itv_join_many(sel))
            return self._mk(eqn.outvars[0],
                            _itv_join_many(list(operand.lanes)))
        return self._mk(eqn.outvars[0], operand.whole(),
                        operand.lanes)

    def _dus(self, eqn, ins) -> Val:
        operand, update = ins[0], ins[1]
        in_shape = _shape(eqn.invars[0])
        up_shape = _shape(eqn.invars[1])
        for a, sv in enumerate(eqn.invars[2:]):
            limit = in_shape[a] - up_shape[a]
            w = ins[2 + a].whole()
            if w is not None and (w[0] > limit or w[1] < 0) \
                    and in_shape[a] != up_shape[a]:
                self._record_oob(eqn, in_shape[a], int(w[0]), int(w[1]),
                                 f"dynamic_update_slice axis {a}")
        la = self._lane_axis(eqn.invars[0])
        if la is not None and operand.lanes is not None:
            written: Set[int] = set(range(self.L))
            if up_shape[la] != in_shape[la]:
                sw = ins[2 + la].whole()
                if sw is not None:
                    lo = max(0, min(int(sw[0]), self.L - up_shape[la]))
                    hi = max(0, min(int(sw[1]), self.L - up_shape[la]))
                    written = {i for s in range(lo, hi + 1)
                               for i in range(s, s + up_shape[la])}
            ul = update.lanes
            uw = update.whole()
            lanes = []
            for i, cur in enumerate(operand.lanes):
                if i in written:
                    # weak update: join (must-overwrite would need a
                    # single resolved start; join is always sound)
                    upd = ul[i] if ul is not None and \
                        len(ul) == self.L and \
                        up_shape[la] == in_shape[la] else uw
                    lanes.append(_itv_join(cur, upd))
                else:
                    lanes.append(cur)
            if self._lane_axis(eqn.outvars[0]) is not None:
                return Val(None, tuple(lanes))
            return Val(_itv_join_many(lanes))
        return self._mk(eqn.outvars[0],
                        _itv_join(operand.whole(), update.whole()),
                        operand.lanes)

    # control flow ----------------------------------------------------------

    def _call_like(self, eqn, ins) -> List[Val]:
        subs = _sub_closed(eqn)
        for _, sub in subs:
            inner = _inner_jaxpr(sub)
            if len(inner.invars) == len(eqn.invars) and \
                    len(inner.outvars) == len(eqn.outvars):
                return self.call(inner, ins,
                                 getattr(sub, "consts", ()))
        if any(getattr(_dtype(o), "kind", None) in ("i", "u")
               for o in eqn.outvars):
            self.note(f"call-like primitive "
                      f"'{eqn.primitive.name}' with mismatched inner "
                      f"arity — outputs widened")
        return [self._top(o) for o in eqn.outvars]

    def _cond(self, eqn, ins) -> List[Val]:
        branches = [(s, _inner_jaxpr(s)) for _, s in _sub_closed(eqn)]
        fit = [(s, b) for s, b in branches
               if len(b.invars) == len(eqn.invars) - 1
               and len(b.outvars) == len(eqn.outvars)]
        if not fit or len(fit) != len(branches):
            return [self._top(o) for o in eqn.outvars]
        outs: Optional[List[Val]] = None
        for s, b in fit:
            bouts = self.call(b, ins[1:], getattr(s, "consts", ()))
            outs = bouts if outs is None else \
                [_val_join(a, x) for a, x in zip(outs, bouts)]
        return outs or [self._top(o) for o in eqn.outvars]

    def _while(self, eqn, ins) -> List[Val]:
        # no whiles in honest ticks (JXP404 polices them); outputs
        # widen to dtype-full and the model's proof degrades (ABS704)
        if any(getattr(_dtype(o), "kind", None) in ("i", "u")
               for o in eqn.outvars):
            self.note("a while_loop crosses the tick — its outputs "
                      "widened to dtype-full")
        return [self._top(o) for o in eqn.outvars]

    def _scan(self, eqn, ins) -> List[Val]:
        nc = int(eqn.params["num_consts"])
        ncar = int(eqn.params["num_carry"])
        length = int(eqn.params.get("length", 1))
        subs = _sub_closed(eqn)
        if not subs:
            return [self._top(o) for o in eqn.outvars]
        sub = subs[0][1]
        inner = _inner_jaxpr(sub)
        consts_v = ins[:nc]
        carry_v = list(ins[nc:nc + ncar])
        xs_v = []
        for k, xv in enumerate(ins[nc + ncar:]):
            bv = inner.invars[nc + ncar + k]
            keep_lanes = xv.lanes is not None and \
                self._lane_axis(bv) is not None
            xs_v.append(Val(xv.whole(),
                            xv.lanes if keep_lanes else None))

        def apply(cvals: List[Val], rec: bool) -> List[Val]:
            saved = self.record
            self.record = rec and saved
            try:
                return self.call(inner, list(consts_v) + cvals + xs_v,
                                 getattr(sub, "consts", ()))
            finally:
                self.record = saved

        final_carry, unstable = self._loop_fixpoint(
            apply, carry_v, length,
            [inner.invars[nc + k] for k in range(ncar)])
        outs = apply(final_carry, True)
        out_carry = [_val_join(c, o)
                     for c, o in zip(final_carry, outs[:ncar])]
        ys = outs[ncar:]
        # ys lanes survive only when the stacked outer var is tagged
        result = []
        for k, c in enumerate(out_carry):
            result.append(c)
        for k, y in enumerate(ys):
            ov = eqn.outvars[ncar + k]
            keep = y.lanes is not None and \
                self._lane_axis(ov) is not None
            result.append(Val(y.whole(), y.lanes if keep else None))
        return result

    # the shared loop widener ------------------------------------------------

    def _loop_fixpoint(self, apply, seed_vals: List[Val], length: int,
                       carry_vars=None, pad: bool = False,
                       iters: int = 5) -> Tuple[List[Val], List[int]]:
        """Iterate ``apply`` (one abstract loop body) joining into the
        carry; on non-convergence, extrapolate each element's per-trip
        growth affinely by ``length`` and re-verify the growth bound at
        the widened state. Returns (final carry, indices of leaves
        whose growth could not be bounded)."""
        hist = [list(seed_vals)]
        cur = list(seed_vals)
        stable = False
        # enough join iterations for COUPLED rates to reach steady
        # state (raft's term adopts pool lanes that adopt terms: the
        # common rate only emerges once the feedback cycle saturates —
        # measuring on the transient under-estimates it and the
        # verification below would churn)
        for _ in range(iters):
            outs = apply(cur, False)
            new = [_val_join(c, o) for c, o in zip(cur, outs)]
            if all(_val_eq(a, b) for a, b in zip(new, cur)):
                stable = True
                break
            hist.append(new)
            cur = new
        if stable:
            self._fp_base, self._fp_rates = cur, [None] * len(cur)
            return cur, []
        g_prev = [_growth(a, b) for a, b in zip(hist[-3], hist[-2])]
        g_fin = [_growth(a, b) for a, b in zip(hist[-2], hist[-1])]
        if len(hist) >= 4:
            g_old = [_growth(a, b) for a, b in zip(hist[-4], hist[-3])]
            g_prev = [_growth_max(a, b) for a, b in zip(g_old, g_prev)]
        # extrapolate with the larger of the last two growth rates,
        # then VERIFY: one more application at the widened state must
        # not grow faster than the assumed rate. Coupled counters
        # (raft's term adopts the pool's term lane, which carries
        # client values growing at the op-mint rate) measure different
        # transient rates, so the repair loop raises a failing leaf's
        # rate to the growth actually observed at the horizon state and
        # re-extrapolates — rates converge to the coupled system's
        # common rate in a few rounds. A genuinely super-linear
        # recurrence keeps outrunning every assumed rate (its observed
        # step scales with the horizon) and widens to dtype-full.
        g_cur = [_growth_max(a, b) for a, b in zip(g_prev, g_fin)]
        unstable: List[int] = []
        threshold_mode: Set[int] = set()
        widened = []
        for i, (v, gp, gf) in enumerate(zip(cur, g_prev, g_fin)):
            if _growth_accel(gp, gf):
                # the leaf's base-iteration growth is still
                # accelerating (the pn gossip max-merge tripling toward
                # its clamp): an affine extrapolation of the transient
                # rate would be sound but hopelessly loose (it blows
                # the N-way sum past int32 at modest horizons). Climb
                # thresholds from the base instead — SELECTIVELY, only
                # the accelerating lanes; steady lanes (the pool's
                # DTICK deadline) keep their affine extrapolation. The
                # climb finds the chain's clamp fixpoint, and the exit
                # hands back the true post-clamp drift as the rate.
                threshold_mode.add(i)
                widened.append(_mixed_init(v, gp, gf, g_cur[i],
                                           length))
            else:
                widened.append(_extrapolate(v, g_cur[i], length))
        for rnd in range(24):
            outs = apply(widened, False)
            ok = True
            for i, (w, o) in enumerate(zip(widened, outs)):
                if i in unstable:
                    continue
                if _growth_within(w, o, g_cur[i]):
                    continue
                ok = False
                if os.environ.get("ABSINT_DEBUG"):
                    nm = (carry_vars[i] if carry_vars and
                          i < len(carry_vars) else i)
                    print(f"[absint] rnd{rnd} fail #{i} ({nm}) "
                          f"w={w.whole()} o={o.whole()} "
                          f"thr={i in threshold_mode} "
                          f"g={_rate_size(g_cur[i])}")
                if _step_saturated(o):
                    if rnd < 2 and i not in threshold_mode:
                        # the affine extrapolation overshot the rail —
                        # the transient rate was garbage (a geometric
                        # chain heading for a clamp). Restart the leaf
                        # as a threshold climb from its iteration base;
                        # if it saturates AGAIN the overflow is real.
                        threshold_mode.add(i)
                        widened[i] = _threshold_widen(cur[i])
                    else:
                        # the observed step hit the int32 rail past
                        # the redirect window: at THIS horizon the
                        # leaf overflows — mark it past the rail so
                        # the caller's leaf-overflow check reports it
                        # (a smaller probe decides whether the growth
                        # was linear-but-large or super-linear)
                        widened[i] = _overflowed_like(w)
                    continue
                step = _step_size(w, o)
                if i in threshold_mode:
                    if step <= 256:
                        # the geometric phase ended (the chain's clamp
                        # was reached — pn's counter_abs_max): the
                        # small steady residual IS the asymptotic
                        # rate. REPLACE the meaningless transient rate
                        # and extrapolate affinely from here.
                        threshold_mode.discard(i)
                        g_cur[i] = _growth(w, o)
                        widened[i] = _extrapolate(_val_join(w, o),
                                                  g_cur[i], length)
                    else:
                        # classic widening-to-thresholds: jump to the
                        # next power of two past the observed output —
                        # only on the lanes that actually failed
                        widened[i] = _threshold_sel(w, o, g_cur[i])
                elif rnd < 3:
                    # settling constant offsets (a window buffer
                    # filling, a lane one tick behind its source):
                    # plain join absorbs them without touching the rate
                    widened[i] = _val_join(w, o)
                else:
                    # steady residual: the coupled system's common
                    # rate is higher than this leaf's measured one —
                    # adopt the OBSERVED step as the rate (replace,
                    # not max: a stale transient rate must not keep
                    # inflating the bound once the chain settles) and
                    # re-extrapolate from the joined state
                    g_cur[i] = _growth_max(_growth(w, o),
                                           _growth(w, o))
                    widened[i] = _extrapolate(_val_join(w, o),
                                              g_cur[i], length)
            if ok:
                break
        else:
            outs = apply(widened, False)
            for i, (w, o, g) in enumerate(zip(widened, outs, g_cur)):
                if i in unstable:
                    continue
                if not _growth_within(w, o, g):
                    widened[i] = Val(None)
                    unstable.append(i)
        # stash the pre-extrapolation base and the verified rates so
        # the caller can re-extrapolate the SAME proof to a smaller
        # horizon without re-iterating (rates are monotone in t)
        self._fp_base, self._fp_rates = cur, g_cur
        # pay the verification slack into the claimed bounds — at the
        # TOP (tick) level only: inner-scan carries are re-verified by
        # the tick-level loop anyway, and padding them once per outer
        # iteration would compound the slack geometrically
        if pad:
            for i in range(len(widened)):
                if i not in unstable:
                    widened[i] = _pad(widened[i], g_cur[i])
        return widened, sorted(unstable)


def _itv_join_many(itvs: List[Optional[Itv]]) -> Optional[Itv]:
    out: Optional[Itv] = None
    for i in itvs:
        if i is None:
            return None
        out = _itv_join(out, i)
    return out


def _growth(a: Val, b: Val):
    """Per-element (hi-growth, lo-growth) from one loop iteration —
    per-lane vectors when both sides carry lanes."""
    def one(x: Optional[Itv], y: Optional[Itv]):
        if x is None or y is None:
            return None
        return (max(0, y[1] - x[1]), max(0, x[0] - y[0]))
    if a.lanes is not None and b.lanes is not None \
            and len(a.lanes) == len(b.lanes):
        return [one(x, y) for x, y in zip(a.lanes, b.lanes)]
    return one(a.whole(), b.whole())


def _growth_max(gp, gl):
    """Elementwise max of two growth measurements."""
    def one(p, l):
        if p is None or l is None:
            return None
        return (max(p[0], l[0]), max(p[1], l[1]))
    if isinstance(gl, list) or isinstance(gp, list):
        n = len(gl) if isinstance(gl, list) else len(gp)
        gp = gp if isinstance(gp, list) else [gp] * n
        gl = gl if isinstance(gl, list) else [gl] * n
        return [one(p, l) for p, l in zip(gp, gl)]
    return one(gp, gl)


def _extrapolate(v: Val, g, n: int) -> Val:
    def one(i: Optional[Itv], gi) -> Optional[Itv]:
        if i is None:
            return None
        if gi is None or gi == (0, 0):
            return i
        return (i[0] - gi[1] * n, i[1] + gi[0] * n)
    if v.lanes is not None and isinstance(g, list) \
            and len(g) == len(v.lanes):
        return Val(None, tuple(one(i, gi)
                               for i, gi in zip(v.lanes, g)))
    return Val(one(v.whole(), g if not isinstance(g, list) else None))


def _growth_accel(gp, gl) -> bool:
    """True when the later growth measurement materially exceeds the
    earlier one — the leaf is still accelerating across the base
    iterations and its transient rate must not be extrapolated."""
    def one(p, l):
        if l is None:
            return False
        if p is None:
            return max(l) > 256
        return l[0] > 1.5 * p[0] + 256 or l[1] > 1.5 * p[1] + 256
    if isinstance(gl, list) or isinstance(gp, list):
        n = len(gl) if isinstance(gl, list) else len(gp)
        gp = gp if isinstance(gp, list) else [gp] * n
        gl = gl if isinstance(gl, list) else [gl] * n
        return any(one(p, l) for p, l in zip(gp, gl))
    return one(gp, gl)


def _rate_size(g) -> float:
    if isinstance(g, list):
        return max((max(gi) for gi in g if gi is not None), default=0)
    return max(g) if g is not None else 0


def _step_size(w: Val, o: Val) -> float:
    """Scalar magnitude of one verification residual (max over
    lanes/sides) — the accelerating-vs-steady discriminator."""
    g = _growth(w, o)
    if isinstance(g, list):
        return max((max(gi) for gi in g if gi is not None), default=0)
    return max(g) if g is not None else 0


def _threshold_sel(w: Val, o: Val, g) -> Val:
    """Per-lane selective threshold widening: lanes still within the
    slack allowance keep their joined value; failing lanes jump to the
    next power-of-two threshold past the observed output."""
    def one(wi: Optional[Itv], oi: Optional[Itv], gi) -> Optional[Itv]:
        if wi is None or oi is None:
            return _itv_join(wi, oi)
        sh, sl = _slack(gi)
        if oi[1] <= wi[1] + sh and oi[0] >= wi[0] - sl:
            return _itv_join(wi, oi)
        return _threshold_itv(_itv_join(wi, oi))
    if w.lanes is not None and o.lanes is not None \
            and len(w.lanes) == len(o.lanes):
        gl = g if isinstance(g, list) else [g] * len(w.lanes)
        return Val(None, tuple(one(wi, oi, gi) for wi, oi, gi in
                               zip(w.lanes, o.lanes, gl)))
    return _threshold_widen(_val_join(w, o))


def _mixed_init(v: Val, gp, gf, g, length: int) -> Val:
    """Initial widening for an accelerating leaf: per lane, jump the
    accelerating lanes to a threshold and extrapolate the steady
    ones."""
    if v.lanes is None or not isinstance(gf, list):
        return _threshold_widen(v)
    gpl = gp if isinstance(gp, list) else [gp] * len(v.lanes)
    gcl = g if isinstance(g, list) else [g] * len(v.lanes)
    lanes = []
    for vi, gpi, gfi, gci in zip(v.lanes, gpl, gf, gcl):
        if _growth_accel(gpi, gfi):
            lanes.append(_threshold_itv(vi))
        else:
            ex = _extrapolate(Val(vi), gci, length)
            lanes.append(ex.whole())
    return Val(None, tuple(lanes))


def _threshold_itv(i: Optional[Itv]) -> Optional[Itv]:
    if i is None:
        return None
    hi = int(max(i[1], 1))
    lo = int(min(i[0], 0))
    return (-(1 << abs(lo).bit_length()) if lo < 0 else lo,
            1 << hi.bit_length())


def _threshold_widen(v: Val) -> Val:
    """Jump a bound outward to the next power-of-two threshold (one
    doubling past the observed value) so geometric chains reach their
    stabilizing clamp in logarithmically many repair rounds."""
    if v.lanes is not None:
        return Val(None, tuple(_threshold_itv(i) for i in v.lanes))
    return Val(_threshold_itv(v.whole()))


def _overflowed_like(w: Val) -> Val:
    """A bound one past the int32 rails — the explicit 'this leaf
    overflows at this horizon' marker the leaf-overflow check reads
    (and _growth_within trivially accepts, ending the repair churn)."""
    over = (INT32_MIN - 1, INT32_MAX + 1)
    if w.lanes is not None:
        return Val(None, (over,) * len(w.lanes))
    return Val(over)


def _step_saturated(o: Val) -> bool:
    """True when an observed verification step already hit the int32
    rail — the leaf is outrunning every finite rate (super-linear);
    inflating the rate further would only turn 'unprovable' into a
    bogus concrete overflow claim."""
    def one(i: Optional[Itv]) -> bool:
        return i is not None and (i[1] >= INT32_MAX or i[0] <= INT32_MIN)
    if o.lanes is not None:
        return any(one(i) for i in o.lanes)
    return one(o.whole())


# verification slack: a multi-leaf feedback cycle (term -> pool lane ->
# term) settles its cross-leaf offsets a constant at a time, so the
# re-application check allows a bounded number of growth steps plus an
# absolute floor — and _pad() charges the same allowance back into the
# final bounds, so the claimed invariant is exactly what was verified.
# Against million-tick extrapolations the allowance is noise; a super-
# linear recurrence still blows past it (its excess scales with T).
_SLACK_MUL = 32
_SLACK_ABS = 16


def _slack(gi) -> Tuple[int, int]:
    gh, glo = gi if gi is not None else (0, 0)
    return (max(_SLACK_MUL * gh, _SLACK_ABS),
            max(_SLACK_MUL * glo, _SLACK_ABS))


def _growth_within(w: Val, o: Val, g) -> bool:
    """out must stay within the slack allowance of the widened state."""
    def one(wi: Optional[Itv], oi: Optional[Itv], gi) -> bool:
        if wi is None:
            return True
        if oi is None:
            return False
        sh, sl = _slack(gi)
        return oi[1] <= wi[1] + sh and oi[0] >= wi[0] - sl
    if w.lanes is not None and o.lanes is not None \
            and isinstance(g, list) and len(g) == len(w.lanes):
        return all(one(wi, oi, gi)
                   for wi, oi, gi in zip(w.lanes, o.lanes, g))
    return one(w.whole(), o.whole(),
               g if not isinstance(g, list) else None)


def _pad(v: Val, g) -> Val:
    """Charge the verification slack into a bound (see _SLACK_MUL).
    Saturating at the int32 rails: a leaf sitting AT the rail is TOP
    (imprecision), not an overflow — only bounds that already crossed
    (the _overflowed_like marker) stay past it."""
    def one(i: Optional[Itv], gi) -> Optional[Itv]:
        if i is None:
            return None
        sh, sl = _slack(gi)
        lo, hi = i[0] - sl, i[1] + sh
        if i[1] <= INT32_MAX:
            hi = min(hi, INT32_MAX)
        if i[0] >= INT32_MIN:
            lo = max(lo, INT32_MIN)
        return (lo, hi)
    if v.lanes is not None and isinstance(g, list) \
            and len(g) == len(v.lanes):
        return Val(None, tuple(one(i, gi)
                               for i, gi in zip(v.lanes, g)))
    return Val(one(v.whole(), g if not isinstance(g, list) else None))


def _scatter_window_map(dnums, operand_rank) -> Dict[int, int]:
    inserted = set(int(d) for d in dnums.inserted_window_dims)
    batching = set(int(d) for d in
                   getattr(dnums, "operand_batching_dims", ()))
    window = tuple(int(d) for d in dnums.update_window_dims)
    amap, k = {}, 0
    for a in range(operand_rank):
        if a in inserted or a in batching:
            continue
        if k < len(window):
            amap[a] = window[k]
        k += 1
    return amap


# --- per-model analysis ----------------------------------------------------


@dataclass
class RangeReport:
    """Value-range result for ONE model x layout."""
    label: str
    probe_log2: int                     # largest horizon probed
    horizon_log2: int = PRODUCTION_LOG2  # horizon ABS701 gates on (the
                                        # probe itself when explicitly
                                        # overridden — the lint_gate
                                        # canary's synthetic budget)
    proven: bool = True                 # no unbounded leaves / notes
    max_safe_horizon_log2: int = 0      # largest 2^k with a clean walk
    min_overflow_t: Optional[int] = None
    overflow_sites: List[Dict[str, Any]] = field(default_factory=list)
    oob_sites: List[Dict[str, Any]] = field(default_factory=list)
    race_sites: List[Dict[str, Any]] = field(default_factory=list)
    scatter_census: Dict[str, int] = field(default_factory=dict)
    unproven_leaves: List[str] = field(default_factory=list)
    counters: Dict[str, int] = field(default_factory=dict)
    # leaf path -> headroom bits at the production horizon
    flake: Optional[Dict[str, int]] = None
    notes: List[str] = field(default_factory=list)

    @property
    def ovf_margin_bits(self) -> int:
        """Minimum proven counter headroom — bits to int32 max at
        ``min(2^20, 2^max_safe)``, the horizon the entry is actually
        proven (and production-capped) to. 0 = unproven. The bench.py
        metric."""
        if not self.proven:
            return 0
        return min(self.counters.values(), default=31)

    @property
    def race_status(self) -> str:
        if self.race_sites:
            return "racing"
        return "race-free" if self.proven else "unproven"

    def to_entry(self) -> Dict[str, Any]:
        """The checked-in manifest representation: the safety-relevant
        facts (proven horizon, per-counter headroom, scatter-race
        verdict) the drift gate pins."""
        entry = {
            "proven": self.proven,
            "max_safe_horizon_log2": self.max_safe_horizon_log2,
            "min_overflow_t": self.min_overflow_t,
            "scatter_race": self.race_status,
            "netsim_scatters": sum(
                n for ph, n in self.scatter_census.items()
                if ph in ("deliver", "enqueue")),
            "ovf_margin_bits": self.ovf_margin_bits,
            "counters": {k: self.counters[k]
                         for k in sorted(self.counters)},
        }
        if self.flake is not None:
            entry["flake"] = self.flake
        if self.unproven_leaves:
            entry["unproven_leaves"] = sorted(self.unproven_leaves)
        return entry


def _carry_paths(carry) -> List[str]:
    import jax
    return [jax.tree_util.keystr(kp) for kp, _ in
            jax.tree_util.tree_flatten_with_path(carry)[0]]


def _abstract_init_vals(model, sim, n_lanes: int,
                        pool_idx: int) -> List[Val]:
    """Seed intervals for every carry leaf from an abstract walk of the
    ``init_carry`` jaxpr — no concrete state is ever materialized, so
    this prices the same at 4 instances or 100k."""
    import jax
    from ..tpu.runtime import init_carry

    params = model.make_params(sim.net.n_nodes)
    closed = jax.make_jaxpr(
        lambda: init_carry(model, sim, 0, params))()
    interp = _Interp(tagger=None, n_lanes=n_lanes,
                     phase_of=lambda eqn: "init")
    outs = interp.call(closed.jaxpr, [], closed.consts)
    # the pool leaf is all-zero at init in every configuration — give
    # it an exact per-lane seed so lane precision starts tight
    vals = []
    for i, v in enumerate(outs):
        if i == pool_idx:
            vals.append(Val(None, ((0, 0),) * n_lanes))
        else:
            vals.append(Val(v.whole()))
    return vals


def analyze_model(model, node_count: int, layout: str = "lead",
                  label: Optional[str] = None, sim=None,
                  traced=None, trace_cache=None,
                  probe_log2: Optional[int] = None) -> RangeReport:
    """Run the interval analysis for one model x layout. ``sim``
    overrides the shared audit config (bench.py passes its own);
    ``traced`` (a ``cost_model.trace_tick`` triple) and ``trace_cache``
    follow the lanes-pass conventions so the combined gate traces each
    model x layout once. ``probe_log2`` raises/lowers the largest
    horizon probed (the lint_gate canary probes 2^31 to plant a
    synthetic overflow budget every cumulative counter trips)."""
    from .lane_liveness import _Analyzer, _pool_lane_axis

    if sim is not None:
        layout = sim.layout
        trace_cache = None
    label = label or f"{getattr(model, 'name', type(model).__name__)}" \
                     f"/{layout}"
    if sim is None:
        sim = cost_model.audit_sim(model, node_count, layout)
    closed, carry, out_shapes = traced or cost_model.trace_tick(
        model, sim, cache=trace_cache)
    n_lanes = sim.net.lanes
    probe = PROBE_LOG2 if probe_log2 is None else int(probe_log2)

    import jax
    paths = _carry_paths(carry)
    n_carry = len(paths)
    pool_idx = paths.index(".pool")
    lane_axis = _pool_lane_axis(layout,
                                jax.tree_util.tree_leaves(carry)
                                [pool_idx].shape, n_lanes)
    tagger = _Analyzer(closed, n_lanes, {pool_idx: lane_axis})
    tagger.fold_consts()
    tagger.infer_tags()

    interp = _Interp(tagger, n_lanes)
    init_vals = _abstract_init_vals(model, sim, n_lanes, pool_idx)
    # lane-tag the pool seed only if the traced invar really is tagged
    if interp._lane_axis(closed.jaxpr.invars[pool_idx]) is None:
        init_vals[pool_idx] = Val((0, 0))

    def fixpoint(T: int):
        t_val = Val((0, T - 1))

        def apply(carry_vals, rec: bool) -> List[Val]:
            interp.record = rec
            outs = interp.call(closed.jaxpr,
                               list(carry_vals) + [t_val],
                               closed.consts)
            interp.record = False
            return outs[:n_carry]
        final, unstable = interp._loop_fixpoint(
            apply, list(init_vals), T, pad=True, iters=8)
        return final, unstable, t_val, interp._fp_base, \
            interp._fp_rates

    def probe_walk(final, t_val) -> Tuple[List[Dict], List[Dict],
                                          List[Dict]]:
        interp.overflow_sites, interp.oob_sites, interp.race_sites = \
            [], [], []
        interp.scatter_census = {}
        interp.record = True
        interp.call(closed.jaxpr, list(final) + [t_val], closed.consts)
        interp.record = False
        return (list(interp.overflow_sites), list(interp.oob_sites),
                list(interp.race_sites))

    # one fixed point at the probe horizon; growth rates measured there
    # over-approximate every smaller horizon (monotone transfer), so
    # smaller probes reuse the same extrapolation base
    final, unstable, t_top, base, rates = fixpoint(1 << probe)

    # leaf-level overflow: an extrapolated carry counter escaping int32
    leaf_over: List[Tuple[str, Itv]] = []
    for i, v in enumerate(final):
        if i in unstable:
            continue
        w = v.whole()
        leaf_dt = getattr(jax.tree_util.tree_leaves(carry)[i],
                          "dtype", None)
        if w is not None and str(leaf_dt) == "int32" and \
                (w[0] < INT32_MIN or w[1] > INT32_MAX):
            leaf_over.append((paths[i], w))

    over, oob, races = probe_walk(final, t_top)
    census = dict(interp.scatter_census)
    # oob/races are horizon-independent verdicts (reported as ABS703/
    # ABS702 on their own); only OVERFLOW drives the horizon search
    clean = not (over or leaf_over)

    report = RangeReport(
        label=label, probe_log2=probe,
        # an explicitly-requested probe IS the configured horizon the
        # overflow verdict gates on; the default probe gates on the
        # production cap (real models prove past it with headroom)
        horizon_log2=(PRODUCTION_LOG2 if probe_log2 is None
                      else probe))
    report.scatter_census = census
    report.race_sites = races
    report.oob_sites = oob
    report.notes = list(interp.notes)
    report.unproven_leaves = [paths[i] for i in unstable]
    report.proven = not unstable and not interp.notes

    if clean:
        report.max_safe_horizon_log2 = probe
    else:
        # binary-search the largest clean power-of-two horizon; bounds
        # are monotone in T so one fixpoint per candidate suffices
        lo_k, hi_k = -1, probe
        while hi_k - lo_k > 1:
            mid = (lo_k + hi_k) // 2
            f_mid, uns_mid, t_mid, _, _ = fixpoint(1 << mid)
            o_mid, _, _ = probe_walk(f_mid, t_mid)
            l_mid = _leaf_overflow(f_mid, uns_mid, carry)
            if o_mid or l_mid:
                hi_k = mid
            else:
                lo_k = mid
        report.max_safe_horizon_log2 = max(0, lo_k)
        report.min_overflow_t = _min_overflow_t(
            fixpoint, probe_walk, carry,
            1 << max(0, lo_k), 1 << hi_k)
        report.overflow_sites = over + [
            {"kind": "leaf", "leaf": p, "lo": int(w[0]), "hi": int(w[1])}
            for p, w in leaf_over]

    # per-counter headroom at the production horizon — re-extrapolate
    # the probe fixpoint's verified base/rates to the smaller horizon
    # (rates are monotone in t, so this is the same proof, cheaper)
    t_prod = 1 << min(PRODUCTION_LOG2, report.max_safe_horizon_log2)
    uns_prod = unstable
    f_prod = [v if g is None else _pad(_extrapolate(v, g, t_prod), g)
              for v, g in zip(base, rates)]
    leaves = jax.tree_util.tree_leaves(carry)
    for i, (v0, vT) in enumerate(zip(init_vals, f_prod)):
        if i in uns_prod:
            continue
        w0, wT = v0.whole(), vT.whole()
        if w0 is None or wT is None or \
                str(getattr(leaves[i], "dtype", "")) != "int32":
            continue
        if wT[1] >= INT32_MAX or wT[0] <= INT32_MIN:
            # rails-saturated = TOP by design (the g-set seen bitmask
            # deliberately uses the sign bit): no headroom CLAIM — a
            # counter that genuinely reached the rails would have made
            # the probe walk dirty instead
            continue
        if wT[1] > w0[1] or wT[0] < w0[0]:     # a counter: it moved
            m = max(abs(int(wT[0])), abs(int(wT[1])), 1)
            report.counters[paths[i]] = max(0, 31 - m.bit_length())
    # the declared flake-id split, proven not hand-waved (the ROADMAP
    # accepted-debt item): the node-state counter's proven ceiling vs
    # the field width CON204 audits
    bits = getattr(model, "flake_counter_bits", None)
    if bits is not None:
        peak = 0
        for i, p in enumerate(paths):
            if p.startswith(".node_state") and i not in uns_prod:
                w = f_prod[i].whole()
                if w is not None:
                    peak = max(peak, int(w[1]))
        report.flake = {
            "bits": int(bits),
            "proven_counter_max": int(peak),
            "fits": bool(peak < (1 << bits)),
        }
        if not report.flake["fits"]:
            report.overflow_sites.append({
                "kind": "flake", "leaf": ".node_state",
                "hi": int(peak), "bits": int(bits)})
    return report


def _leaf_overflow(final, unstable, carry_shapes) -> bool:
    import jax
    leaves = jax.tree_util.tree_leaves(carry_shapes)
    for i, v in enumerate(final):
        if i in unstable:
            continue
        w = v.whole()
        if w is not None and str(getattr(leaves[i], "dtype", "")) == \
                "int32" and (w[0] < INT32_MIN or w[1] > INT32_MAX):
            return True
    return False


def _min_overflow_t(fixpoint, probe_walk, carry_shapes, lo_t: int,
                    hi_t: int) -> int:
    """Binary-search the minimal horizon T (not necessarily a power of
    two) whose walk overflows — ABS701 names it."""
    while hi_t - lo_t > 1:
        mid = (lo_t + hi_t) // 2
        f, uns, t_v, _, _ = fixpoint(mid)
        o, _, _ = probe_walk(f, t_v)
        if o or _leaf_overflow(f, uns, carry_shapes):
            hi_t = mid
        else:
            lo_t = mid
    return hi_t


# --- findings --------------------------------------------------------------


def _model_path(model) -> str:
    return type(model).__module__.replace(".", os.sep) + ".py"


def _finding(rule, name, severity, path, symbol, message) -> Finding:
    return Finding(rule=rule, name=name, severity=severity,
                   pass_name=PASS_NAME, path=path, line=0,
                   symbol=symbol, message=message)


def findings_of_report(model, report: RangeReport) -> List[Finding]:
    """ABS701-ABS704 from one model's range result."""
    path = _model_path(model)
    cls = type(model).__name__
    out: List[Finding] = []

    def flag(rule, name, message, severity=SEV_ERROR):
        out.append(_finding(rule, name, severity, path, cls,
                            f"[{report.label}] {message}"))

    horizon = min(report.horizon_log2, report.probe_log2)
    if report.max_safe_horizon_log2 < horizon:
        for site in report.overflow_sites[:4]:
            if site.get("kind") == "leaf":
                where = f"carry leaf {site['leaf']} reaches " \
                        f"[{site['lo']}, {site['hi']}]"
            elif site.get("kind") == "flake":
                where = f"flake counter {site['leaf']} provably " \
                        f"reaches {site['hi']} > 2^{site['bits']} " \
                        f"(the declared id-space split) — ids collide"
            else:
                where = f"'{site['prim']}' in the {site['phase']} " \
                        f"phase reaches [{site['lo']}, {site['hi']}]"
            flag("ABS701", "int32-overflow",
                 f"int32 overflow reachable within the 2^{horizon}-tick "
                 f"horizon: {where}; minimal overflowing T = "
                 f"{report.min_overflow_t} (proven safe only to "
                 f"2^{report.max_safe_horizon_log2})")
    elif report.flake is not None and not report.flake["fits"]:
        flag("ABS701", "int32-overflow",
             f"flake counter provably reaches "
             f"{report.flake['proven_counter_max']} within the "
             f"2^{horizon}-tick horizon but the declared split is "
             f"{report.flake['bits']} bits — ids from different nodes "
             f"collide; widen flake_counter_bits (and prove the fix "
             f"with --update-ranges)")
    for site in report.race_sites[:4]:
        flag("ABS702", "scatter-write-race",
             f"non-commutative scatter in the {site['phase']} phase: "
             f"{site['rows']} update rows, {site['why']} — XLA applies "
             f"duplicate overwrite updates in unspecified order, so "
             f"the tick is silently nondeterministic; make the update "
             f"commutative (scatter-add/min/max), sequentialize the "
             f"writes, or prove the indices distinct")
    for site in report.oob_sites[:4]:
        flag("ABS703", "oob-index",
             f"provably out-of-bounds {site['what']} in the "
             f"{site['phase']} phase: index range [{site['lo']}, "
             f"{site['hi']}] vs axis size {site['axis_size']} — under "
             f"jit the access silently clamps (LNE604's column-exact "
             f"check, upgraded to full range reasoning)")
    if not report.proven:
        why = report.unproven_leaves[:3] or report.notes[:2]
        flag("ABS704", "range-unresolvable",
             f"value ranges could not be fully bounded — "
             f"{'; '.join(str(w) for w in why)}; the overflow verdict "
             f"for the widened leaves is vacuous (conservative "
             f"widening, the LNE605 mirror)", SEV_WARNING)
    return out


# --- manifest io + drift gate ----------------------------------------------


def load_range_manifest(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or DEFAULT_RANGE_MANIFEST
    if not os.path.exists(path):
        return {"version": 1, "entries": {}}
    with open(path) as f:
        data = json.load(f)
    data.setdefault("entries", {})
    return data


def save_range_manifest(entries: Dict[str, Dict[str, Any]],
                        path: Optional[str] = None) -> str:
    import jax
    path = path or DEFAULT_RANGE_MANIFEST
    payload = {
        "version": 1,
        "_comment": (
            "Per-model proven value-range manifest for `maelstrom lint "
            "--ranges` (doc/lint.md pass 7). Keys: <workload>/"
            "n=<nodes>/<layout>; max_safe_horizon_log2 = largest "
            "power-of-two tick horizon with a PROVEN overflow-free "
            "abstract walk (make_sim_config refuses horizons above it), "
            "counters = per-carry-leaf headroom bits to int32 max at "
            "the production horizon, scatter_race = the ABS702 "
            "determinism verdict (race-free = every non-commutative "
            "scatter's index rows proven distinct; netsim_scatters "
            "counts scatter sites in the deliver/enqueue phases — 0 "
            "certifies the composed-gather path scatter-free). "
            "Regenerate after an INTENTIONAL range change with "
            "`maelstrom lint --ranges --update-ranges`; drift fails "
            "the gate (ABS705). jax-version records the tracing "
            "toolchain: under a different jax the gate downgrades "
            "drift to a re-record warning."),
        "jax-version": jax.__version__,
        "production_horizon_log2": PRODUCTION_LOG2,
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    _MANIFEST_CACHE.clear()
    return path


def compare_manifest(live: Dict[str, RangeReport],
                     manifest: Dict[str, Any],
                     paths: Dict[str, Tuple[str, str]],
                     full_universe: bool = True,
                     errored=frozenset()) -> List[Finding]:
    """Diff live range reports against the checked-in manifest. The
    gate pins the safety-relevant facts: the proven horizon, the
    scatter-race verdict, and per-counter headroom bits."""
    entries = manifest.get("entries", {})
    note = cost_model.toolchain_note(manifest.get("jax-version"),
                                     "the range manifest",
                                     "--update-ranges")
    findings: List[Finding] = []
    for key in sorted(live):
        rep = live[key]
        path, symbol = paths[key]
        base = entries.get(key)
        if base is None:
            findings.append(_finding(
                "ABS706", "range-manifest-missing", SEV_ERROR, path,
                symbol,
                f"[{key}] no range-manifest entry — record one with "
                f"`maelstrom lint --ranges --update-ranges`"))
            continue
        drifts = []
        for field_name, got in (
                ("proven", rep.proven),
                ("max_safe_horizon_log2", rep.max_safe_horizon_log2),
                ("scatter_race", rep.race_status),
                ("netsim_scatters", sum(
                    n for ph, n in rep.scatter_census.items()
                    if ph in ("deliver", "enqueue"))),
                ("ovf_margin_bits", rep.ovf_margin_bits),
                ("counters", {k: rep.counters[k]
                              for k in sorted(rep.counters)})):
            want = base.get(field_name)
            if want is not None and want != got:
                drifts.append(f"{field_name}: live {got!r} vs manifest "
                              f"{want!r}")
        if drifts:
            findings.append(_finding(
                "ABS705", "range-manifest-drift",
                SEV_WARNING if note else SEV_ERROR, path, symbol,
                f"[{key}] proven ranges drifted from the checked-in "
                f"manifest: {'; '.join(drifts)} — a counter's proven "
                f"bound moved; if intentional, re-record with "
                f"--update-ranges and justify it in the PR"
                + (f" ({note})" if note else "")))
    if full_universe:
        for key in sorted(set(entries) - set(live) - set(errored)):
            findings.append(_finding(
                "ABS707", "range-manifest-stale", SEV_WARNING,
                "maelstrom_tpu/analysis/range_manifest.json", "",
                f"[{key}] manifest entry matches no registered "
                f"model x layout — remove or re-record it"))
    return findings


# --- the make_sim_config cross-check ---------------------------------------

_MANIFEST_CACHE: Dict[str, Dict[str, Any]] = {}


def proven_horizon_log2(model_name: str,
                        manifest_path: Optional[str] = None
                        ) -> Optional[int]:
    """The model's proven overflow-free horizon (log2) from the
    checked-in manifest — the minimum across its recorded layouts, or
    None when the model has no proven entry. ``make_sim_config``
    cross-checks its horizon refusal against this instead of the one
    global 2^20 constant (unproven entries never cap: the global
    netsim bound still applies)."""
    path = manifest_path or DEFAULT_RANGE_MANIFEST
    cached = _MANIFEST_CACHE.get(path)
    if cached is None:
        cached = load_range_manifest(path)
        _MANIFEST_CACHE[path] = cached
    best: Optional[int] = None
    for key, entry in cached.get("entries", {}).items():
        if key.split("/", 1)[0] != model_name:
            continue
        if not entry.get("proven"):
            continue
        k = entry.get("max_safe_horizon_log2")
        if k is None:
            continue
        best = int(k) if best is None else min(best, int(k))
    return best


# --- orchestration ---------------------------------------------------------


def run_range_lint(repo_root: str = ".",
                   manifest_path: Optional[str] = None,
                   update_manifest: bool = False,
                   workloads: Optional[List[Tuple[str, int]]] = None,
                   layouts: Sequence[str] = cost_model.AUDIT_LAYOUTS,
                   include_fixtures: bool = True,
                   trace_cache=None,
                   probe_log2: Optional[int] = None) -> List[Finding]:
    """The ranges pass: interval-analyze every registered model x
    layout (or a restricted list), emit ABS7xx findings, and gate
    against (or re-record) the manifest."""
    from ..models import get_model

    full = workloads is None
    specs = cost_model.cost_specs() if full else list(workloads)
    findings: List[Finding] = []
    live: Dict[str, RangeReport] = {}
    paths: Dict[str, Tuple[str, str]] = {}
    errored: Set[str] = set()

    for wl, n in specs:
        try:
            model = get_model(wl, n, "grid")
        except Exception as e:
            findings.append(_finding(
                "ABS708", "range-analysis-failure", SEV_ERROR,
                "maelstrom_tpu/models/__init__.py", "get_model",
                f"get_model({wl!r}, {n}) raised: {e!r}"))
            errored.update(cost_model.entry_key(wl, n, lay)
                           for lay in layouts)
            continue
        for layout in layouts:
            key = cost_model.entry_key(wl, n, layout)
            try:
                rep = analyze_model(model, n, layout,
                                    label=f"{wl}/n={n}/{layout}",
                                    trace_cache=trace_cache,
                                    probe_log2=probe_log2)
            except Exception as e:
                findings.append(_finding(
                    "ABS708", "range-analysis-failure", SEV_ERROR,
                    _model_path(model), type(model).__name__,
                    f"[{key}] range analysis raised "
                    f"{type(e).__name__}: {e}"))
                errored.add(key)
                continue
            findings.extend(findings_of_report(model, rep))
            live[key] = rep
            paths[key] = (_model_path(model), type(model).__name__)

    if full and include_fixtures:
        from ..models.ir_hazards import RANGE_FIXTURE_MODELS
        for kind, cls in sorted(RANGE_FIXTURE_MODELS.items()):
            model = cls()
            for layout in layouts:
                try:
                    rep = analyze_model(model, 2, layout,
                                        label=f"fixture-{kind}/{layout}")
                except Exception as e:
                    findings.append(_finding(
                        "ABS708", "range-analysis-failure", SEV_ERROR,
                        _model_path(model), type(model).__name__,
                        f"[fixture-{kind}/{layout}] range analysis "
                        f"raised {type(e).__name__}: {e}"))
                    continue
                findings.extend(findings_of_report(model, rep))

    if update_manifest:
        path = save_range_manifest(
            {k: r.to_entry() for k, r in live.items()}, manifest_path)
        findings.append(_finding(
            "ABS700", "range-manifest-updated", SEV_INFO,
            os.path.relpath(path, os.path.abspath(repo_root))
            if os.path.isabs(path) else path, "",
            f"recorded {len(live)} range-manifest entr"
            f"{'y' if len(live) == 1 else 'ies'}"))
    else:
        manifest = load_range_manifest(manifest_path)
        findings.extend(compare_manifest(live, manifest, paths,
                                         full_universe=full,
                                         errored=errored))
    return findings


# --- bench/profiler surface ------------------------------------------------


def tick_range_stats(model, sim, traced=None) -> Dict[str, int]:
    """One-call range stats for bench.py metric lines: the minimum
    proven counter headroom (bits to int32 max at the production
    horizon) of this exact configuration's tick. 0 = unproven."""
    rep = analyze_model(model, sim.net.n_nodes, sim.layout, sim=sim,
                        traced=traced)
    return {"ovf_margin_bits": rep.ovf_margin_bits}
