"""Static-analysis subsystem: the ``maelstrom lint`` passes.

Three cooperating passes keep the TPU runtime's contracts machine-
enforced (doc/lint.md has the rule catalog and workflow):

- :mod:`.trace_lint` — AST trace-hygiene lint over the traced surfaces
  (models, tick loop, delivery kernel): TRC1xx rules.
- :mod:`.contract_audit` — ``jax.eval_shape`` audit of every registered
  model's shape/dtype/lane contracts: CON2xx rules.
- :mod:`.schema_lint` — RPC registry vs wire encodings vs demo nodes:
  SCH3xx rules.

Findings are :class:`~.findings.Finding` records; the checked-in
``baseline.json`` holds the justified exceptions.
"""

from .findings import (Baseline, Finding, LintReport, SEV_ERROR,  # noqa
                       SEV_INFO, SEV_WARNING, render_text)
from .runner import ALL_PASSES, run_lint  # noqa
