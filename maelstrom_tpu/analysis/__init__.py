"""Static-analysis subsystem: the ``maelstrom lint`` passes.

Five cooperating passes keep the TPU runtime's contracts machine-
enforced (doc/lint.md has the rule catalog and workflow):

- :mod:`.trace_lint` — AST trace-hygiene lint over the traced surfaces
  (models, tick loop, delivery kernel): TRC1xx rules.
- :mod:`.contract_audit` — ``jax.eval_shape`` audit of every registered
  model's shape/dtype/lane contracts: CON2xx rules.
- :mod:`.schema_lint` — RPC registry vs wire encodings vs demo nodes:
  SCH3xx rules.
- :mod:`.ir_lint` — opt-in (``--ir``) audit of the LOWERED tick IR:
  dtype-widening leaks, host round-trips, donation aliasing on the
  compiled executors, fusion breakers, baked-in constants: JXP4xx.
- :mod:`.cost_model` + the ``--cost`` gate — per-model static tick
  cost (eqn count, est. HBM bytes, per-phase decomposition) budgeted
  against the checked-in ``cost_baseline.json``: COST5xx rules.

Findings are :class:`~.findings.Finding` records; the checked-in
``baseline.json`` holds the justified exceptions and
``cost_baseline.json`` the per-model cost budget.
"""

from .findings import (Baseline, Finding, LintReport, SEV_ERROR,  # noqa
                       SEV_INFO, SEV_WARNING, render_text)
from .runner import ALL_PASSES, EXTRA_PASSES, run_lint  # noqa
