"""IR-level hazard lint + cost gate: ``maelstrom lint --ir --cost``.

The AST lint (TRC1xx) and abstract-eval contract audit (CON2xx) police
the *Python* surface of the traced tick. This pass polices what the
tick actually **lowers to**: for every registered model and both carry
layouts it traces the fused tick (``jax.make_jaxpr`` — abstract, no
device) and audits the jaxpr; it also lowers and COMPILES the real
production dispatch steps — ``tpu/pipeline.py::make_chunk_fn`` and
``parallel/mesh.py::make_sharded_chunk_fn``, the exact callables the
executors dispatch — to verify that carry donation actually aliased on
the executable (not a re-lowered copy).

Rules (JXP4xx — hazards; COST5xx — the cost budget):

=======  =======================  ========  ===============================
rule     name                     severity  what it flags
=======  =======================  ========  ===============================
JXP400   ir-trace-failure         error     the tick failed to lower at all
JXP401   dtype-widening-leak      error     a non-int32/uint32 leaf in the
                                            scan carry (float/64-bit
                                            promotion leaks: bit-identity,
                                            x-platform replay, and donated
                                            compaction all assume integer
                                            state), or any 64-bit aval
                                            anywhere in the tick IR
JXP402   host-round-trip          error     pure_callback / io_callback /
                                            debug_callback inside the
                                            traced tick — a device->host
                                            round-trip per tick
JXP403   donation-not-aliased     error     a compiled executor declares
                                            ``donate_argnums`` on the carry
                                            but the executable did not
                                            alias every carry leaf
                                            (silently-dropped donation =
                                            2x HBM + a hidden copy)
JXP404   fusion-breaker           error/    more fusion-breaking loops
                                  warning   (whiles + non-unrolled scans)
                                            in the tick body than the
                                            model's per-entry
                                            ``fusion-breakers`` budget in
                                            ``cost_baseline.json`` (error;
                                            the fused raft family pins 0 —
                                            budget-less entries warn on
                                            explicit while_loops), or a
                                            ``broadcast_in_dim``
                                            intermediate larger than k x
                                            the carry — the patterns that
                                            break fusion and spill HBM
JXP405   baked-in-constant        warning   a constant >= 64 KiB embedded
                                            in the tick jaxpr (executable
                                            bloat + retrace trigger)
COST500  cost-baseline-updated    info      ``--update-baseline`` rewrote
                                            the baseline
COST501  cost-regression          error     eqns or est. HBM bytes/tick
                                            regressed > tolerance (10%)
                                            vs ``cost_baseline.json``
COST502  cost-baseline-missing    error     a registered model x layout
                                            has no baseline entry
COST503  cost-baseline-stale      warning   a baseline entry matches no
                                            registered model
COST504  cost-improvement         info      a model got > tolerance
                                            CHEAPER — refresh the baseline
                                            to bank the win
COST505  scope-coverage-regression error    fused-tick eqns outside every
                                            known named scope grew past
                                            the baseline unattributed-eqns
                                            budget — a refactor dropped or
                                            renamed a jax.named_scope, so
                                            device-time profiler
                                            attribution (telemetry/
                                            profiler.py) went blind there
=======  =======================  ========  ===============================

The IR-hazard fixtures (``models/ir_hazards.py``) are audited alongside
the registered models; their findings are carried as status="expected"
in ``analysis/baseline.json`` and asserted by
``tests/test_analysis_ir.py`` — the planted-bug convention of
``RaftTracedHazards``.
"""

from __future__ import annotations

import os
import re
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import cost_model
from .cost_model import CostReport
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_IR = "ir"
PASS_COST = "cost"

# the runtime's bit-identity envelope: every carry leaf must be one of
# these (the master PRNG key is uint32; everything else is int32)
ALLOWED_CARRY_DTYPES = ("int32", "uint32")
X64_DTYPES = ("int64", "uint64", "float64")

# host-round-trip primitives (JXP402)
CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback",
                       "callback", "outside_call", "host_callback_call")

# JXP404 thresholds: a broadcast intermediate larger than BOTH of these
# is flagged (the floor keeps tiny audit-config carries from making
# every legitimate [I, N, N] broadcast look oversized)
BROADCAST_CARRY_MULT = 8
BROADCAST_FLOOR_BYTES = 1 << 20          # 1 MiB

# JXP405 threshold
CONST_WARN_BYTES = 64 << 10              # 64 KiB

# donation-audit subjects: compiling is ~5 s per executable, so the
# repo-wide gate verifies the (model-independent) executor wiring on
# the cheapest model rather than re-compiling the world
DONATION_WORKLOAD = ("echo", 2)


def _model_path(model) -> str:
    return type(model).__module__.replace(".", os.sep) + ".py"


def _finding(rule, name, severity, path, symbol, message,
             pass_name=PASS_IR) -> Finding:
    return Finding(rule=rule, name=name, severity=severity,
                   pass_name=pass_name, path=path, line=0,
                   symbol=symbol, message=message)


# --- per-model hazard audit ------------------------------------------------


def audit_model_ir(model, node_count: int, layout: str = "lead",
                   label: Optional[str] = None,
                   loop_budget: Optional[int] = None,
                   trace_cache=None,
                   ) -> Tuple[List[Finding], Optional[CostReport]]:
    """Trace one model's fused tick in one layout and audit the IR.
    Returns (findings, cost report) — the report is reused by the cost
    pass so each (model, layout) is traced exactly once per run.

    ``loop_budget`` is the model's JXP404 budget: the number of
    fusion-breaking loops (while_loops + non-unrolled scans) its tick
    is ALLOWED to carry, read from the cost baseline's per-entry
    ``fusion-breakers`` field by :func:`run_ir_lint`. Exceeding it is
    an ERROR — the fused raft-family models pin 0, so a re-introduced
    per-slot scan fails the gate, while kafka's recorded loops stay
    legal without a global exemption. ``None`` (no baseline entry yet)
    falls back to the PR-5 global behavior: explicit while_loops warn,
    legacy scans pass un-counted."""
    import jax

    label = label or getattr(model, "name", type(model).__name__)
    label = f"{label}/{layout}"
    path = _model_path(model)
    cls = type(model).__name__
    findings: List[Finding] = []

    def flag(rule, name, message, severity=SEV_ERROR, symbol=cls):
        findings.append(_finding(rule, name, severity, path, symbol,
                                 f"[{label}] {message}"))

    try:
        sim = cost_model.audit_sim(model, node_count, layout)
        closed, carry, out_shapes = cost_model.trace_tick(
            model, sim, cache=trace_cache)
    except Exception as e:
        flag("JXP400", "ir-trace-failure",
             f"lowering the fused tick raised {type(e).__name__}: {e}")
        return findings, None
    report = cost_model.cost_of_jaxpr(closed, carry)
    if trace_cache is not None:
        # leave the report next to the shared trace so the lanes pass
        # skips the duplicate byte walk in the combined gate
        trace_cache[cost_model.entry_key(
            getattr(model, "name", type(model).__name__),
            node_count, layout) + "::cost"] = report

    # JXP401a: carry leaves outside the integer envelope. The traced
    # output carry (out_shapes[0]) is authoritative — it is what the
    # scan actually threads.
    carry_out = out_shapes[0]
    for kp, leaf in jax.tree_util.tree_flatten_with_path(carry_out)[0]:
        dt = str(leaf.dtype)
        if dt not in ALLOWED_CARRY_DTYPES:
            flag("JXP401", "dtype-widening-leak",
                 f"carry leaf {jax.tree_util.keystr(kp) or '<root>'} is "
                 f"{dt} — the scan carry must stay int32/uint32 "
                 f"(bit-identity, cross-platform replay, and donated "
                 f"compaction all assume integer state)")
    # JXP401b: 64-bit avals anywhere in the tick IR (an enable_x64 /
    # numpy-scalar promotion leak — silent 2x HBM and a dtype cliff on
    # TPU, which emulates int64 pairwise)
    wide = {dt: n for dt, n in _dtype_census(closed).items()
            if dt in X64_DTYPES}
    if wide:
        flag("JXP401", "dtype-widening-leak",
             f"64-bit intermediates in the tick IR: "
             f"{', '.join(f'{n}x {dt}' for dt, n in sorted(wide.items()))}"
             f" — an x64/numpy-promotion leak")

    # JXP402: host callbacks in traced code
    cbs = {p: n for p, n in report.ops.items()
           if p in CALLBACK_PRIMITIVES}
    if cbs:
        flag("JXP402", "host-round-trip",
             f"host callback primitive(s) in the tick: "
             f"{', '.join(f'{p} x{n}' for p, n in sorted(cbs.items()))}"
             f" — one device->host round-trip per tick serializes the "
             f"scan and faults the TPU tunnel at fleet scale")

    # JXP404: fusion breakers — while_loops plus scans that survive
    # lowering as XLA whiles (non-unrolled bodies); each is a fusion
    # boundary and a per-trip relaunch
    n_loops = report.loops
    if loop_budget is not None and n_loops > loop_budget:
        flag("JXP404", "fusion-breaker",
             message=f"{n_loops} fusion-breaking loop(s) in the tick "
                     f"body vs this model's budget of {loop_budget} "
                     f"(cost_baseline.json 'fusion-breakers') — a "
                     f"while/non-unrolled scan survives as an XLA "
                     f"while the backend can neither unroll nor fuse "
                     f"across; restore the fused formulation or "
                     f"re-baseline with --update-baseline and justify "
                     f"the loop")
    elif loop_budget is None and report.ops.get("while", 0):
        # budget-less entry (not yet re-baselined, or a fixture): the
        # PR-5 global behavior — explicit while_loops warn, legacy
        # scans are implicitly tolerated
        n_while = report.ops["while"]
        flag("JXP404", "fusion-breaker", severity=SEV_WARNING,
             message=f"{n_while} while_loop(s) in the tick body — XLA "
                     f"can neither unroll nor fuse across an unbounded "
                     f"trip count (scatter x"
                     f"{report.ops.get('scatter', 0)}, sort x"
                     f"{report.ops.get('sort', 0)} ride the same tick)")
    bcast_limit = max(BROADCAST_CARRY_MULT * max(report.carry_bytes, 1),
                      BROADCAST_FLOOR_BYTES)
    if report.max_broadcast_bytes > bcast_limit:
        flag("JXP404", "fusion-breaker", severity=SEV_WARNING,
             message=f"a broadcast_in_dim intermediate is "
                     f"{report.max_broadcast_bytes} B — "
                     f"{report.max_broadcast_bytes // max(report.carry_bytes, 1)}"
                     f"x the {report.carry_bytes} B carry (HBM spill "
                     f"between producer and consumers)")

    # JXP405: baked-in constants
    if report.max_const_bytes >= CONST_WARN_BYTES:
        flag("JXP405", "baked-in-constant", severity=SEV_WARNING,
             message=f"largest baked-in constant is "
                     f"{report.max_const_bytes} B "
                     f"({report.const_bytes} B total) — embedded in "
                     f"every executable and a retrace trigger; pass it "
                     f"as params instead")
    return findings, report


def _dtype_census(closed) -> Dict[str, int]:
    census: Dict[str, int] = {}

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt is not None:
                    census[str(dt)] = census.get(str(dt), 0) + 1
            for sub, _ in cost_model._sub_jaxprs(eqn):
                walk(sub)

    walk(closed.jaxpr)
    return census


# --- JXP403: donation aliasing on the COMPILED executors -------------------


def aliased_params_of(compiled_text: str) -> set:
    """Parse the HLO module header's ``input_output_alias`` config into
    the set of aliased parameter indices. The config nests braces —
    ``{ {0}: (0, {}, may-alias), ... }`` — so the block is delimited by
    brace counting, not regex."""
    marker = "input_output_alias={"
    start = compiled_text.find(marker)
    if start < 0:
        return set()
    depth, i = 1, start + len(marker)
    while i < len(compiled_text) and depth > 0:
        if compiled_text[i] == "{":
            depth += 1
        elif compiled_text[i] == "}":
            depth -= 1
        i += 1
    block = compiled_text[start + len(marker):i - 1]
    return {int(p) for p in re.findall(r"\(\s*(\d+)\s*,", block)}


def audit_donation(jit_fn, args: Sequence[Any], n_donated: int, *,
                   path: str, symbol: str, label: str,
                   static_kwargs: Optional[Dict[str, Any]] = None,
                   ) -> List[Finding]:
    """Lower + compile ``jit_fn`` (which declares ``donate_argnums`` on
    its first argument, a pytree of ``n_donated`` leaves) and verify
    the executable aliased EVERY donated leaf. XLA silently drops
    un-aliasable donations (shape/dtype mismatch between the donated
    input and any output) — the failure mode is invisible until HBM
    fills at 2x the expected footprint."""
    findings: List[Finding] = []

    def flag(message):
        findings.append(_finding(
            "JXP403", "donation-not-aliased", SEV_ERROR, path, symbol,
            f"[{label}] {message}"))

    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            compiled = jit_fn.lower(*args,
                                    **(static_kwargs or {})).compile()
        donation_warnings = [str(w.message) for w in caught
                             if "donated" in str(w.message).lower()]
    except Exception as e:
        flag(f"lower/compile of the donating executor raised "
             f"{type(e).__name__}: {e}")
        return findings
    aliased = aliased_params_of(compiled.as_text())
    missing = sorted(set(range(n_donated)) - aliased)
    if missing:
        flag(f"{len(missing)} of {n_donated} donated carry leaves were "
             f"NOT aliased by the compiled executable (flat param "
             f"indices {missing[:8]}{'...' if len(missing) > 8 else ''})"
             f" — the donation was silently dropped; every undonated "
             f"leaf doubles its HBM footprint per dispatch")
    for w in donation_warnings:
        flag(f"XLA declined donated buffers at compile time: "
             f"{w.splitlines()[0][:160]}")
    return findings


def audit_step_ir(fn, args: Sequence[Any], *, path: str, symbol: str,
                  label: str,
                  static_kwargs: Optional[Dict[str, Any]] = None,
                  ) -> List[Finding]:
    """Hazard-audit a whole EXECUTOR STEP (the chunked pipeline dispatch
    / sharded mesh body) at the jaxpr level: 64-bit leaks and host
    callbacks anywhere in the step, including the compaction/scan
    plumbing the per-model tick audit never sees."""
    import jax

    findings: List[Finding] = []

    def flag(rule, name, message):
        findings.append(_finding(rule, name, SEV_ERROR, path, symbol,
                                 f"[{label}] {message}"))

    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # tracing through a
            # donating jit: donation cannot apply under make_jaxpr
            closed = jax.make_jaxpr(
                lambda *a: fn(*a, **(static_kwargs or {})))(*args)
    except Exception as e:
        flag("JXP400", "ir-trace-failure",
             f"lowering the executor step raised "
             f"{type(e).__name__}: {e}")
        return findings
    wide = {dt: n for dt, n in _dtype_census(closed).items()
            if dt in X64_DTYPES}
    if wide:
        flag("JXP401", "dtype-widening-leak",
             f"64-bit intermediates in the executor step: "
             f"{', '.join(f'{n}x {dt}' for dt, n in sorted(wide.items()))}")
    report = cost_model.cost_of_jaxpr(closed)
    cbs = {p: n for p, n in report.ops.items()
           if p in CALLBACK_PRIMITIVES}
    if cbs:
        flag("JXP402", "host-round-trip",
             f"host callback primitive(s) in the executor step: "
             f"{', '.join(f'{p} x{n}' for p, n in sorted(cbs.items()))}")
    return findings


def _donation_args(model, sim):
    """ShapeDtypeStruct stand-ins for one chunk dispatch's arguments."""
    import jax
    import jax.numpy as jnp
    from ..tpu.runtime import init_carry

    params = model.make_params(sim.net.n_nodes)
    carry = jax.eval_shape(lambda: init_carry(model, sim, 0, params))
    sds = jax.tree.map(lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                       carry)
    return params, sds, jax.ShapeDtypeStruct((), jnp.int32)


def audit_pipeline_donation(layouts=("lead", "minor"),
                            chunk_len: int = 4,
                            step_hazards: bool = True) -> List[Finding]:
    """JXP403 over the single-device pipelined executor: compile the
    ACTUAL ``make_chunk_fn`` product (the callable run_sim_pipelined
    dispatches) and verify carry aliasing, in both carry layouts —
    plus (``step_hazards``) the jaxpr-level hazard audit of the whole
    dispatch step, compaction and scan plumbing included."""
    import jax
    from ..models import get_model
    from ..tpu import pipeline
    from ..tpu.runtime import default_instance_ids

    wl, n = DONATION_WORKLOAD
    findings: List[Finding] = []
    for layout in layouts:
        model = get_model(wl, n)
        sim = cost_model.audit_sim(model, n, layout)
        params, carry_sds, t_sds = _donation_args(model, sim)
        chunk_fn = pipeline.make_chunk_fn(
            model, sim, params, default_instance_ids(sim), 64, 1)
        kw = dict(path="maelstrom_tpu/tpu/pipeline.py",
                  symbol="make_chunk_fn", label=f"{wl}/n={n}/{layout}",
                  static_kwargs={"length": chunk_len})
        if step_hazards:
            findings.extend(audit_step_ir(chunk_fn,
                                          (carry_sds, t_sds), **kw))
        findings.extend(audit_donation(
            chunk_fn, (carry_sds, t_sds),
            len(jax.tree.leaves(carry_sds)), **kw))
    return findings


def audit_mesh_donation(chunk_len: int = 4,
                        step_hazards: bool = True) -> List[Finding]:
    """JXP403 over the sharded executor: compile the ACTUAL
    ``make_sharded_chunk_fn`` product on a 1-device mesh and verify the
    wire carry aliased through the shard_map boundary — plus
    (``step_hazards``) the jaxpr-level hazard audit of the sharded
    body."""
    import jax
    import jax.numpy as jnp
    from ..models import get_model
    from ..parallel import mesh as mesh_mod
    from ..tpu.runtime import init_carry

    wl, n = DONATION_WORKLOAD
    model = get_model(wl, n)
    sim = cost_model.audit_sim(model, n, "lead")
    params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)    # the _prepare convention
    mesh = mesh_mod.make_mesh(1)
    chunk_fn, _ = mesh_mod.make_sharded_chunk_fn(model, sim, mesh,
                                                 params)
    wire = jax.eval_shape(
        lambda p: mesh_mod._carry_to_wire(
            init_carry(model, sim, 0, p), sim), params)
    wire_sds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), wire)
    t_sds = jax.ShapeDtypeStruct((), jnp.int32)
    p_sds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params)
    kw = dict(path="maelstrom_tpu/parallel/mesh.py",
              symbol="make_sharded_chunk_fn", label=f"{wl}/n={n}/sharded",
              static_kwargs={"length": chunk_len})
    findings: List[Finding] = []
    if step_hazards:
        findings.extend(audit_step_ir(chunk_fn,
                                      (wire_sds, t_sds, p_sds), **kw))
    findings.extend(audit_donation(
        chunk_fn, (wire_sds, t_sds, p_sds),
        len(jax.tree.leaves(wire_sds)), **kw))
    return findings


# --- the cost gate ---------------------------------------------------------


def compare_costs(live: Dict[str, CostReport],
                  baseline: Dict[str, Any],
                  paths: Dict[str, Tuple[str, str]],
                  full_universe: bool = True) -> List[Finding]:
    """Diff live cost reports against the checked-in baseline.
    ``paths`` maps entry key -> (repo path, class symbol) for finding
    locations; ``full_universe`` gates stale-entry reporting (a
    restricted audit never sees every key)."""
    tol = float(baseline.get("tolerance", cost_model.DEFAULT_TOLERANCE))
    entries = baseline.get("entries", {})
    # the recorded toolchain: under a different jax version the lowered
    # graphs legitimately differ, so drift downgrades from a hard
    # COST501 failure to a self-explaining re-record warning
    note = cost_model.toolchain_note(baseline.get("jax-version"),
                                     "the cost baseline")
    findings: List[Finding] = []
    for key in sorted(live):
        rep = live[key]
        path, symbol = paths[key]
        base = entries.get(key)
        if base is None:
            findings.append(_finding(
                "COST502", "cost-baseline-missing", SEV_ERROR, path,
                symbol,
                f"[{key}] no cost-baseline entry — record one with "
                f"`maelstrom lint --cost --update-baseline`",
                pass_name=PASS_COST))
            continue
        regressions = []
        for field_name, got, want in (
                ("eqns", rep.eqns, base.get("eqns")),
                ("hbm-bytes-per-tick", rep.hbm_bytes,
                 base.get("hbm-bytes-per-tick"))):
            if want is None or want <= 0:
                continue
            if got > want * (1 + tol):
                regressions.append((field_name, got, want))
        if regressions:
            worst = _worst_phase_delta(rep.phases, base.get("phases", {}))
            detail = "; ".join(
                f"{f}: {got} vs baseline {want} "
                f"(+{(got / want - 1) * 100:.0f}%)"
                for f, got, want in regressions)
            findings.append(_finding(
                "COST501", "cost-regression",
                SEV_WARNING if note else SEV_ERROR, path, symbol,
                f"[{key}] tick cost regressed past the {tol:.0%} "
                f"budget: {detail}{worst} — make the change cheaper, "
                f"or re-baseline with --update-baseline and justify it "
                f"in the PR" + (f" ({note})" if note else ""),
                pass_name=PASS_COST))
        elif (rep.eqns < base.get("eqns", 0) * (1 - tol)
              and rep.hbm_bytes <= base.get("hbm-bytes-per-tick",
                                            rep.hbm_bytes)):
            findings.append(_finding(
                "COST504", "cost-improvement", SEV_INFO, path, symbol,
                f"[{key}] tick got cheaper: eqns {rep.eqns} vs baseline "
                f"{base['eqns']} — run --update-baseline to bank the "
                f"win", pass_name=PASS_COST))
        # the scope-coverage gate (COST505): eqns the device-time
        # profiler cannot attribute to a known named scope must not
        # grow past the recorded budget — that's how a refactor that
        # drops/renames a jax.named_scope gets caught statically.
        # Entries recorded before the column existed carry no budget
        # and are skipped (re-record with --update-baseline).
        ua_base = base.get("unattributed-eqns")
        if ua_base is not None \
                and rep.unattributed_eqns > ua_base * (1 + tol):
            renamed = (f"; unknown scope roots seen: "
                       f"{', '.join(rep.unknown_scopes)}"
                       if rep.unknown_scopes else "")
            findings.append(_finding(
                "COST505", "scope-coverage-regression",
                SEV_WARNING if note else SEV_ERROR, path, symbol,
                f"[{key}] {rep.unattributed_eqns} fused-tick eqns "
                f"outside every known named scope vs baseline budget "
                f"{ua_base} (+{rep.unattributed_eqns - ua_base}) — a "
                f"refactor likely dropped or renamed a "
                f"jax.named_scope, so device-time attribution "
                f"(telemetry/profiler.py) goes blind there{renamed}; "
                f"restore the scope or re-record with "
                f"--update-baseline and justify it in the PR"
                + (f" ({note})" if note else ""),
                pass_name=PASS_COST))
    if full_universe:
        for key in sorted(set(entries) - set(live)):
            findings.append(_finding(
                "COST503", "cost-baseline-stale", SEV_WARNING,
                "maelstrom_tpu/analysis/cost_baseline.json", "",
                f"[{key}] baseline entry matches no registered "
                f"model x layout — remove or re-record it"
                + (f" ({note})" if note else ""),
                pass_name=PASS_COST))
    return findings


def _worst_phase_delta(live_phases: Dict[str, int],
                       base_phases: Dict[str, int]) -> str:
    worst, delta = None, 0
    for ph in set(live_phases) | set(base_phases):
        d = live_phases.get(ph, 0) - base_phases.get(ph, 0)
        if d > delta:
            worst, delta = ph, d
    return f" (worst phase: {worst} +{delta} eqns)" if worst else ""


# --- orchestration ---------------------------------------------------------


def run_ir_lint(repo_root: str = ".", hazards: bool = True,
                cost: bool = False,
                cost_baseline_path: Optional[str] = None,
                update_baseline: bool = False,
                workloads: Optional[List[Tuple[str, int]]] = None,
                layouts: Sequence[str] = cost_model.AUDIT_LAYOUTS,
                include_fixtures: bool = True,
                donation: bool = True,
                trace_cache=None) -> List[Finding]:
    """Run the IR hazard pass and/or the cost gate.

    ``workloads=None`` audits the full registered universe (plus the IR
    fixtures and the compiled-donation audit); a restricted list skips
    fixtures/donation/stale reporting — pointing the analyzer at a
    model means "audit this model", not "re-audit the world".
    """
    from ..models import get_model

    full = workloads is None
    specs = cost_model.cost_specs() if full else list(workloads)
    findings: List[Finding] = []
    live: Dict[str, CostReport] = {}
    paths: Dict[str, Tuple[str, str]] = {}

    # per-model JXP404 loop budgets from the cost baseline (entries
    # recorded before the field existed give None -> the global
    # any-loop-warns fallback)
    budgets: Dict[str, Optional[int]] = {
        k: e.get("fusion-breakers")
        for k, e in cost_model.load_cost_baseline(
            cost_baseline_path).get("entries", {}).items()}

    for wl, n in specs:
        try:
            model = get_model(wl, n, "grid")
        except Exception as e:
            findings.append(_finding(
                "JXP400", "ir-trace-failure", SEV_ERROR,
                "maelstrom_tpu/models/__init__.py", "get_model",
                f"get_model({wl!r}, {n}) raised: {e!r}"))
            continue
        for layout in layouts:
            fs, report = audit_model_ir(
                model, n, layout, label=f"{wl}/n={n}",
                loop_budget=budgets.get(
                    cost_model.entry_key(wl, n, layout)),
                trace_cache=trace_cache)
            if hazards:
                findings.extend(fs)
            else:
                # a tick that no longer lowers is fatal for the cost
                # pass too: without this a cost-only run would drop the
                # broken model from `live` and misreport it as a mere
                # stale-entry warning (or, with --update-baseline,
                # silently delete its budget)
                findings.extend(f for f in fs if f.rule == "JXP400")
            if report is not None:
                key = cost_model.entry_key(wl, n, layout)
                live[key] = report
                paths[key] = (_model_path(model), type(model).__name__)

    if hazards and full and include_fixtures:
        from ..models.ir_hazards import IR_FIXTURE_MODELS
        for kind, cls in sorted(IR_FIXTURE_MODELS.items()):
            fs, _ = audit_model_ir(cls(), 2, "lead",
                                   label=f"fixture-{kind}")
            findings.extend(fs)

    if hazards and full and donation:
        findings.extend(audit_pipeline_donation())
        findings.extend(audit_mesh_donation())

    if cost:
        if update_baseline:
            path = cost_model.save_cost_baseline(
                {k: r.to_entry() for k, r in live.items()},
                cost_baseline_path)
            findings.append(_finding(
                "COST500", "cost-baseline-updated", SEV_INFO,
                os.path.relpath(path, os.path.abspath(repo_root))
                if os.path.isabs(path) else path, "",
                f"recorded {len(live)} cost-baseline entr"
                f"{'y' if len(live) == 1 else 'ies'}",
                pass_name=PASS_COST))
        else:
            baseline = cost_model.load_cost_baseline(cost_baseline_path)
            findings.extend(compare_costs(live, baseline, paths,
                                          full_universe=full))
    return findings
