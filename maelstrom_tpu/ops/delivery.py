"""Pallas delivery kernel: the batched message-exchange hot op.

Per instance the simulated network must hand every node up to ``K`` due,
unblocked messages from the ``S``-slot pool, oldest-deadline first
(netsim.deliver's contract, mirroring net.clj:223-247's priority-queue
poll + receiver-side partition drop). The XLA path does this with a
``top_k`` over an ``[NT, S]`` priority matrix per instance; this kernel
fuses the mask construction, priority computation, and K-round argmax
selection into one VMEM-resident pass over a block of instances, so the
pool is read from HBM exactly once per tick.

Correctness contract is bit-identical to :func:`..tpu.netsim.deliver`
(cross-validated in tests/test_pallas_delivery.py on the interpreter);
enable on hardware with ``MAELSTROM_TPU_PALLAS=1``.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

from ..tpu import wire


def pallas_enabled() -> bool:
    """Use the kernel in the tick loop? ``MAELSTROM_TPU_PALLAS=1`` on a
    TPU backend, or ``=interpret`` anywhere (testing; runs the Pallas
    interpreter, slow). XLA's top_k path stays the default."""
    mode = os.environ.get("MAELSTROM_TPU_PALLAS", "0")
    if mode == "interpret":
        return True
    return mode == "1" and jax.default_backend() == "tpu"


def _interpret() -> bool:
    return (os.environ.get("MAELSTROM_TPU_PALLAS") == "interpret"
            or jax.default_backend() != "tpu")


def _deliver_kernel(pool_ref, part_ref, t_ref, pool_out_ref, inbox_ref,
                    ndel_ref, ndrop_ref, *, cfg):
    """One grid step = one instance. Block shapes keep the gridded axis:
    pool [1, S, L], part [1, NT, NT], t [1, 1]; outs pool' [1, S, L],
    inbox [1, NT, K, L], ndel [1, 1], ndrop [1, 1]. All compute is
    elementwise + broadcast-reduce (VPU), no gathers, no int matmuls."""
    S = cfg.pool_slots
    NT = cfg.n_total
    K = cfg.inbox_k
    t = t_ref[0, 0]

    pool = pool_ref[0]                       # [S, L]
    valid = pool[:, wire.VALID] == 1
    due = valid & (pool[:, wire.DTICK] <= t)
    dest = pool[:, wire.DEST]
    origin = pool[:, wire.ORIGIN]

    # blocked[s] = part[dest[s], origin[s]] — gather-free via one-hots
    # (NT is small, so the [S, NT, NT] intermediate stays tiny in VMEM)
    ids = jax.lax.broadcasted_iota(jnp.int32, (S, NT), 1)
    dest_oh = dest[:, None] == ids           # [S, NT]
    orig_oh = origin[:, None] == ids         # [S, NT]
    part = part_ref[0] != 0                  # [NT, NT]
    part_rows = jnp.sum(
        jnp.where(orig_oh[:, None, :], part[None, :, :], False)
        .astype(jnp.int32), axis=2)          # [S, NT] = part[:, origin[s]]
    blocked = jnp.sum(
        jnp.where(dest_oh, part_rows, 0), axis=1) > 0   # [S]

    drop_mask = due & blocked
    deliverable = due & ~blocked

    # priority per (node, slot): oldest deadline first, slot-index
    # tie-break — identical to netsim.deliver's ranking
    slot_order = jax.lax.broadcasted_iota(jnp.int32, (S,), 0)
    age_rank = ((1 << 20) - pool[:, wire.DTICK]) * S
    base_prio = age_rank + (S - slot_order)  # [S]
    cand = deliverable[None, :] & dest_oh.T  # [NT, S]
    prio = jnp.where(cand, base_prio[None, :], 0)

    taken = jnp.zeros((S,), dtype=jnp.bool_)
    n_del = jnp.int32(0)
    # K selection rounds: per round take each node's current best slot
    for k in range(K):
        best = jnp.argmax(prio, axis=1)          # [NT]
        bestv = jnp.max(prio, axis=1)            # [NT]
        take = bestv > 0
        best_oh = (best[:, None] ==
                   jax.lax.broadcasted_iota(jnp.int32, (NT, S), 1))
        # rows[n] = pool[best[n]] via masked broadcast-reduce
        rows = jnp.sum(
            jnp.where(best_oh[:, :, None], pool[None, :, :], 0),
            axis=1)                              # [NT, L]
        inbox_ref[0, :, k, :] = jnp.where(take[:, None], rows, 0)
        # clear the taken slots from every node's priority row
        taken_now = jnp.any(take[:, None] & best_oh, axis=0)   # [S]
        prio = jnp.where(taken_now[None, :], 0, prio)
        taken = taken | taken_now
        n_del = n_del + jnp.sum(take.astype(jnp.int32))

    cleared = taken | drop_mask
    pool_out_ref[0] = jnp.where(cleared[:, None], 0, pool)
    ndel_ref[0, 0] = n_del
    ndrop_ref[0, 0] = jnp.sum(drop_mask.astype(jnp.int32))


@partial(jax.jit, static_argnames=("cfg", "interpret"))
def deliver_pallas(pool: jnp.ndarray, partitions: jnp.ndarray,
                   t: jnp.ndarray, cfg, interpret: bool = False):
    """Batched delivery for ``[I, S, L]`` pools. Same returns as
    ``vmap(netsim.deliver)``: (pool', inbox [I, NT, K, L], n_delivered
    [I], n_dropped_partition [I])."""
    from jax.experimental import pallas as pl

    I, S, L = pool.shape
    NT = cfg.n_total
    K = cfg.inbox_k
    t_arr = jnp.broadcast_to(jnp.asarray(t, jnp.int32), (I, 1))

    grid = (I,)
    out_shape = (
        jax.ShapeDtypeStruct((I, S, L), jnp.int32),
        jax.ShapeDtypeStruct((I, NT, K, L), jnp.int32),
        jax.ShapeDtypeStruct((I, 1), jnp.int32),
        jax.ShapeDtypeStruct((I, 1), jnp.int32),
    )
    pool_out, inbox, ndel, ndrop = pl.pallas_call(
        partial(_deliver_kernel, cfg=cfg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, S, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, NT, NT), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, S, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, NT, K, L), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(pool, partitions.astype(jnp.int32), t_arr)
    return pool_out, inbox, ndel[:, 0], ndrop[:, 0]
