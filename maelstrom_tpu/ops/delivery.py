"""Pallas delivery kernel: the batched message-exchange hot op.

Per instance the simulated network must hand every node up to ``K`` due,
unblocked messages from the ``S``-slot pool, oldest-deadline first
(netsim.deliver's contract, mirroring net.clj:223-247's priority-queue
poll + receiver-side partition drop). The XLA path does this with a
``top_k`` over an ``[NT, S]`` priority matrix per instance; this kernel
fuses the mask construction, priority computation, and K-round argmax
selection into one VMEM-resident pass over a block of instances, so the
pool is read from HBM exactly once per tick.

Correctness contract is bit-identical to :func:`..tpu.netsim.deliver` —
cross-validated in tests/test_pallas_delivery.py on the interpreter AND
verified bit-identical on real v5e hardware. Enable with
``MAELSTROM_TPU_PALLAS=1``.

Measured on v5e (4096 instances, S=16, K=1): ~9 ms standalone vs the
XLA path's ~5 ms in-sim — the one-instance-per-grid-step layout is
dispatch-bound, so XLA's top_k path stays the default. Making the
kernel win requires blocking instances onto the lane axis (128+
instances per grid step); until then this is the reference Pallas
implementation of the op, not the fast path.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

from ..tpu import wire


def pallas_enabled() -> bool:
    """Use the kernel in the tick loop? ``MAELSTROM_TPU_PALLAS=1`` on a
    TPU backend, or ``=interpret`` anywhere (testing; runs the Pallas
    interpreter, slow). XLA's top_k path stays the default."""
    mode = os.environ.get("MAELSTROM_TPU_PALLAS", "0")
    if mode == "interpret":
        return True
    return mode == "1" and jax.default_backend() == "tpu"


def _interpret() -> bool:
    return (os.environ.get("MAELSTROM_TPU_PALLAS") == "interpret"
            or jax.default_backend() != "tpu")


def _deliver_kernel(pool_ref, part_ref, t_ref, pool_out_ref, inbox_ref,
                    ndel_ref, ndrop_ref, *, cfg):
    """One grid step = one instance. Block shapes keep the gridded axis:
    pool [1, S, L], part [1, NT, NT], t [1, 1, 1]; outs pool' [1, S, L],
    inbox [1, NT, K, L], ndel [1, 1, 1], ndrop [1, 1, 1]. Scalars ride
    in [I, 1, 1] arrays because Mosaic requires each block's trailing
    two dims to be (8, 128)-divisible or equal to the full array dims —
    (1, 1) trailing blocks over an [I, 1] array fail to lower. All
    compute is elementwise + broadcast-reduce (VPU), no gathers, no int
    matmuls."""
    S = cfg.pool_slots
    NT = cfg.n_total
    K = cfg.inbox_k
    t = t_ref[0, 0, 0]

    # All masks are int32 0/1: Mosaic rejects several i1-vector forms
    # ("unsupported target bitwidth for truncation"), so selection is
    # mask-multiply arithmetic rather than boolean where-chains.
    pool = pool_ref[0]                       # [S, L]
    valid_i = (pool[:, wire.VALID] == 1).astype(jnp.int32)
    due_i = valid_i * (pool[:, wire.DTICK] <= t).astype(jnp.int32)
    dest = pool[:, wire.DEST]
    origin = pool[:, wire.ORIGIN]

    # blocked[s] = part[dest[s], origin[s]] — gather-free via one-hot
    # sum-products (NT is small, the [S, NT] intermediates stay tiny)
    ids = jax.lax.broadcasted_iota(jnp.int32, (S, NT), 1)
    dest_oh = (dest[:, None] == ids).astype(jnp.int32)   # [S, NT]
    orig_oh = (origin[:, None] == ids).astype(jnp.int32)  # [S, NT]
    part = part_ref[0]                       # [NT, NT] int32 0/1
    part_rows = jnp.sum(part[None, :, :] * orig_oh[:, None, :],
                        axis=2)              # [S, NT] = part[:, origin[s]]
    blocked_i = jnp.minimum(jnp.sum(part_rows * dest_oh, axis=1), 1)

    drop_i = due_i * blocked_i               # [S]
    deliverable_i = due_i * (1 - blocked_i)  # [S]

    # priority per (node, slot): oldest deadline first, slot-index
    # tie-break — identical to netsim.deliver's ranking
    slot_order = jax.lax.broadcasted_iota(jnp.int32, (S,), 0)
    age_rank = ((1 << 20) - pool[:, wire.DTICK]) * S
    base_prio = age_rank + (S - slot_order)  # [S]
    cand = deliverable_i[None, :] * dest_oh.T            # [NT, S]
    prio = cand * base_prio[None, :]

    taken_i = jnp.zeros((S,), dtype=jnp.int32)
    n_del = jnp.int32(0)
    # K selection rounds: per round take each node's current best slot.
    # No argmax (Mosaic lowers integer argmax only for f32): candidate
    # priorities are DISTINCT by construction — the slot index rides in
    # the low bits (age_rank is a multiple of S, the tie-break term is
    # in [1, S]) — so an equality mask against the row max selects
    # exactly one slot.
    for k in range(K):
        bestv = jnp.max(prio, axis=1)            # [NT]
        take_i = (bestv > 0).astype(jnp.int32)   # [NT]
        best_oh = ((prio == bestv[:, None]).astype(jnp.int32)
                   * (prio > 0).astype(jnp.int32))        # [NT, S]
        # rows[n] = pool[best[n]] via one-hot sum-product
        rows = jnp.sum(best_oh[:, :, None] * pool[None, :, :],
                       axis=1)                   # [NT, L]
        inbox_ref[0, :, k, :] = take_i[:, None] * rows
        # clear the taken slots from every node's priority row
        taken_now = jnp.minimum(
            jnp.sum(take_i[:, None] * best_oh, axis=0), 1)   # [S]
        prio = prio * (1 - taken_now)[None, :]
        taken_i = jnp.minimum(taken_i + taken_now, 1)
        n_del = n_del + jnp.sum(take_i)

    cleared_i = jnp.minimum(taken_i + drop_i, 1)
    pool_out_ref[0] = (1 - cleared_i)[:, None] * pool
    # 2D vector stores — Mosaic cannot store scalars to VMEM
    ndel_ref[0] = n_del[None, None]
    ndrop_ref[0] = jnp.sum(drop_i)[None, None]


@partial(jax.jit, static_argnames=("cfg", "interpret"))
def deliver_pallas(pool: jnp.ndarray, partitions: jnp.ndarray,
                   t: jnp.ndarray, cfg, interpret: bool = False):
    """Batched delivery for ``[I, S, L]`` pools. Same returns as
    ``vmap(netsim.deliver)``: (pool', inbox [I, NT, K, L], n_delivered
    [I], n_dropped_partition [I])."""
    from jax.experimental import pallas as pl

    I, S, L = pool.shape
    NT = cfg.n_total
    K = cfg.inbox_k
    t_arr = jnp.broadcast_to(jnp.asarray(t, jnp.int32), (I, 1, 1))

    grid = (I,)
    out_shape = (
        jax.ShapeDtypeStruct((I, S, L), jnp.int32),
        jax.ShapeDtypeStruct((I, NT, K, L), jnp.int32),
        jax.ShapeDtypeStruct((I, 1, 1), jnp.int32),
        jax.ShapeDtypeStruct((I, 1, 1), jnp.int32),
    )
    pool_out, inbox, ndel, ndrop = pl.pallas_call(
        partial(_deliver_kernel, cfg=cfg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, S, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, NT, NT), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, S, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, NT, K, L), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(pool, partitions.astype(jnp.int32), t_arr)
    return pool_out, inbox, ndel[:, 0, 0], ndrop[:, 0, 0]
