"""Hand-written TPU kernels (Pallas) for the runtime's hot ops.

The tick loop's dominant op is message delivery — a per-instance masked
top-k over the message pool (netsim.deliver). :mod:`delivery` provides a
Pallas version that keeps the whole pool block in VMEM and fuses
mask/priority/selection into one kernel, gated behind
``MAELSTROM_TPU_PALLAS=1`` (XLA's fused top_k is the default; the kernel
exists for chips/shapes where the gather/scatter lowering dominates —
SURVEY §7 step 8).
"""

from .delivery import deliver_pallas, pallas_enabled  # noqa: F401
