"""The device side of the fault-plan engine.

:class:`FaultConfig` is the compiled, hashable form of a fault plan: a
phase timeline (``untils``) plus, per phase, the crash victims, the
degraded directed edges, and the per-node clock rates — all plain
nested tuples so ``SimConfig`` stays a static jit argument. At trace
time the phases are baked into constant planes (one row per phase plus
a trailing all-healthy row) and the tick selects its row with a single
``searchsorted`` over ``t`` — the same constant-folding move the
scripted partition nemesis uses (``runtime.partition_matrix``).

Lane semantics (shared by BOTH carry layouts — the helpers here take
ONE instance's unbatched state, and the runtime vmaps them exactly like
every other tick phase, so lead/minor trajectories stay bit-identical):

- ``crash`` — victims are held in reset for the whole phase: every
  crashed tick the node row is rebuilt via ``Model.restart_row`` (from
  the snapshot slab — its durable storage — or cold from the init
  path), delivery TO the victim is blocked via the partition plane (the
  recv-side drop IS the lost inbox), and the victim's emitted rows are
  invalidated before enqueue. Messages already in flight FROM the
  victim still deliver — they are on the wire, not in the dead process.
  The snapshot slab captures ``Model.snapshot_row`` of every healthy
  node each ``snapshot_every`` ticks (1 = write-through durability: the
  slab always holds the kill-point state; larger strides model
  asynchronous persistence, where losing the tail is a legitimate
  finding, not a checker bug).
- ``links`` — per-directed-edge ``(dest, origin)`` quality: ``block``
  folds into the delivery partition plane (asymmetric partitions),
  ``delay`` adds ticks to the sampled latency at enqueue time, and
  ``loss_pm`` (per-mille) is an extra independent loss roll. Neutral
  values (0) are value-identical to the healthy path.
- ``skew`` — per-node clock rate in 64ths (64 = 1.0x): the node phase
  runs each node's timers on ``local_t = (t * rate) // 64``. Rate 64 is
  exactly ``t`` (no rounding), so a neutral skew lane is bit-identical.
- ``membership`` — a per-phase member bitmask (``spec.membership_walk``
  resolves the add/remove event dialect to absolute per-phase sets).
  NON-members are parked exactly like crashed nodes: delivery to them
  is blocked via the partition plane, their sends are invalidated
  pre-enqueue, and their row is held at ``Model.join_row`` of their
  snapshot-slab state (terms/timers frozen at the leave point — a
  parked replica is a powered-off machine, not a ranting candidate).
  The tick that turns a node's membership ON is a JOIN: the last park
  wipe already rebuilt the row through ``join_row`` with the CURRENT
  target bitmask, so the node comes back re-provisioned (slab log +
  cluster config + re-based timers — the Netherite rejoin idiom) and,
  for catchup-gated models, mute until it holds the committed prefix.
  The member bitmask also threads INTO the node step (``m_bits``), so
  Raft drives the actual config change through joint consensus
  (``models/raft_core.py``: C_old,new / C_new log entries,
  dual-quorum election and commit) rather than by administrative fiat
  — the plane is the operator's TARGET, the log is the truth.

Everything here is traced (fixed shapes, jnp only, static branches on
the config) and linted with the models (``maelstrom lint --strict``).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

NEUTRAL_RATE = 64          # skew rates are 64ths; 64 == 1.0x (exact)


class FaultConfig(NamedTuple):
    """Static, hashable fault plan (rides ``SimConfig.faults``).

    ``untils`` are the strictly-increasing phase end ticks; phase ``p``
    covers ``[untils[p-1], untils[p])`` (phase 0 starts at tick 0) and
    every tick at/after ``untils[-1]`` or ``stop_tick`` — the final
    heal window — is healthy. The per-phase lane tuples are aligned
    with ``untils``:

    - ``crash[p]``   — tuple of crashed server-node ids
    - ``links[p]``   — tuples ``(dst, src, block, delay, loss_pm)``
    - ``skew[p]``    — tuples ``(node, rate64)``
    - ``members``    — ``None`` (lane absent) or one ABSOLUTE sorted
      member tuple per phase (``spec.membership_walk`` applied the
      add/remove inheritance); the trailing heal row is everyone

    ``fuzz`` (a :class:`~.fuzz.FuzzConfig`, or ``None``) switches the
    config from ONE deterministic fleet-shared plan to per-instance
    RANDOMIZED schedules drawn on device (``faults/fuzz.py``): the
    phase tuples stay empty then, and lane presence comes from the
    distribution instead. Mutually exclusive with a phase timeline.
    """
    enabled: bool = False
    stop_tick: int = 1 << 30
    snapshot_every: int = 1
    untils: Tuple[int, ...] = ()
    crash: Tuple[Tuple[int, ...], ...] = ()
    links: Tuple[Tuple[Tuple[int, int, int, int, int], ...], ...] = ()
    skew: Tuple[Tuple[Tuple[int, int], ...], ...] = ()
    members: Optional[Tuple[Tuple[int, ...], ...]] = None
    n_nodes: int = 0              # cluster size the plan compiled for
    #                               (the membership lane's universe —
    #                               host summaries need it; 0 on the
    #                               disabled config)
    fuzz: Optional[Any] = None    # FuzzConfig (hashable NamedTuple)

    # lane presence is a STATIC property: a lane is "present" when any
    # phase lists entries for it (even value-neutral ones) — or, under
    # a fuzz distribution, when the lane is configured at all (even at
    # rate 0: the all-healthy probe keeps the machinery in the graph).
    # Only present lanes add anything to the traced tick — a default
    # FaultConfig() compiles the exact pre-fault graph.
    @property
    def has_fuzz(self) -> bool:
        return self.fuzz is not None and self.fuzz.enabled

    @property
    def has_crash(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_crash
        return self.enabled and any(len(p) for p in self.crash)

    @property
    def has_links(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_links
        return self.enabled and any(len(p) for p in self.links)

    @property
    def has_skew(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_skew
        return self.enabled and any(len(p) for p in self.skew)

    @property
    def has_members(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_membership
        return self.enabled and self.members is not None

    @property
    def active(self) -> bool:
        return (self.has_crash or self.has_links or self.has_skew
                or self.has_members)


class FaultPlanes(NamedTuple):
    """One tick's selected fault state (``None`` = lane not present,
    statically — the runtime's fault branches key on these)."""
    crash: Optional[Any] = None      # [N] bool — nodes held in reset
    block: Optional[Any] = None      # [NT, NT] bool — recv-side drops
    delay: Optional[Any] = None      # [NT, NT] int32 — extra latency
    loss_pm: Optional[Any] = None    # [NT, NT] int32 — per-mille loss
    t_nodes: Optional[Any] = None    # [N] int32 — per-node local clock
    member: Optional[Any] = None     # [N] bool — this tick's members
    member_prev: Optional[Any] = None  # [N] bool — last tick's members
    #                                  (member & ~member_prev = a JOIN
    #                                  edge; ~(member & member_prev) =
    #                                  the park-wipe mask)


NO_PLANES = FaultPlanes()


@lru_cache(maxsize=64)
def _planes_np(fx: FaultConfig, n_nodes: int, n_clients: int):
    """Bake the phase timeline into dense per-phase numpy planes
    (row ``P`` = the trailing all-healthy phase). Cached: FaultConfig
    is hashable and the planes are pure functions of it."""
    NT = n_nodes + n_clients
    P = len(fx.untils)
    crash = np.zeros((P + 1, n_nodes), dtype=bool)
    block = np.zeros((P + 1, NT, NT), dtype=bool)
    delay = np.zeros((P + 1, NT, NT), dtype=np.int32)
    loss = np.zeros((P + 1, NT, NT), dtype=np.int32)
    skew = np.full((P + 1, n_nodes), NEUTRAL_RATE, dtype=np.int32)
    member = np.ones((P + 1, n_nodes), dtype=bool)  # heal row: all in
    for p in range(P):
        if p < len(fx.crash):
            for v in fx.crash[p]:
                crash[p, v] = True
                # a dead process hears nobody — servers AND clients;
                # its own in-flight sends still deliver (origin edges
                # are NOT blocked)
                block[p, v, :] = True
        if fx.members is not None and p < len(fx.members):
            member[p, :] = False
            for v in fx.members[p]:
                member[p, v] = True
            # a parked non-member hears nobody, exactly like a crash
            # victim (its in-flight sends still deliver)
            for v in range(n_nodes):
                if not member[p, v]:
                    block[p, v, :] = True
        if p < len(fx.links):
            for dst, src, blk, d, pm in fx.links[p]:
                # duplicate entries for one directed edge MERGE (the
                # spec promises "one edge may combine delay and loss",
                # and plans often list them as separate entries) —
                # last-writer-wins would silently zero earlier fields
                if blk:
                    block[p, dst, src] = True
                delay[p, dst, src] = max(delay[p, dst, src], d)
                loss[p, dst, src] = max(loss[p, dst, src], pm)
        if p < len(fx.skew):
            for node, rate in fx.skew[p]:
                skew[p, node] = rate
    untils = np.asarray(fx.untils, dtype=np.int32)
    return untils, crash, block, delay, loss, skew, member


def tick_planes(fx: FaultConfig, cfg, t) -> FaultPlanes:
    """Select tick ``t``'s planes (traced; constants baked per phase).
    ``cfg`` is the NetConfig (static). Ticks at/after ``stop_tick``
    read the all-healthy row — the final heal window. Fuzz configs
    have no shared timeline — the runtime routes them through
    ``fuzz.schedule_planes`` per instance instead."""
    if fx.has_fuzz:
        raise ValueError("tick_planes on a fuzz config: per-instance "
                         "planes come from fuzz.schedule_planes")
    if not fx.active:
        return NO_PLANES
    import jax.numpy as jnp

    untils, crash, block, delay, loss, skew, member = _planes_np(
        fx, cfg.n_nodes, cfg.n_clients)
    P = len(fx.untils)

    def phase_of(tt):
        ph = jnp.searchsorted(jnp.asarray(untils), tt, side="right")
        return jnp.clip(jnp.where(tt < fx.stop_tick, ph, P), 0, P)

    phase = phase_of(t)
    out = {}
    if fx.has_crash:
        out["crash"] = jnp.asarray(crash)[phase]
    if fx.has_crash or _any_block(fx):
        out["block"] = jnp.asarray(block)[phase]
    if fx.has_links:
        out["delay"] = jnp.asarray(delay)[phase]
        out["loss_pm"] = jnp.asarray(loss)[phase]
    if fx.has_skew:
        out["t_nodes"] = (t * jnp.asarray(skew)[phase]) // NEUTRAL_RATE
    if fx.has_members:
        mem = jnp.asarray(member)
        out["member"] = mem[phase]
        # last tick's membership row: tick 0 reads its own phase (no
        # join edge at the start — phase 0's members are the INITIAL
        # cluster, provisioned at init, not a mid-run join)
        out["member_prev"] = mem[phase_of(t - 1)]
    return FaultPlanes(**out)


def _any_block(fx: FaultConfig) -> bool:
    return any(e[2] for p in fx.links for e in p) or fx.has_crash \
        or fx.has_members


def wipe_crashed(model, node_state, snapshots, crash_mask, t_nodes,
                 wipe_key, cfg, params):
    """Hold crashed nodes in reset: rebuild each victim's row via
    ``Model.restart_row`` (per-node restart RNG folded off
    ``wipe_key``) and select it in under the crash mask. One instance's
    unbatched state (``node_state`` leaves ``[N, ...]``); the runtime
    vmaps this over instances in both layouts."""
    import jax
    import jax.numpy as jnp

    N = cfg.n_nodes
    idx = jnp.arange(N, dtype=jnp.int32)
    nkeys = jax.vmap(lambda i: jax.random.fold_in(wipe_key, i))(idx)
    fresh = jax.vmap(
        lambda nk, ni, snap, tn: model.restart_row(N, ni, nk, params,
                                                   snap, tn))(
        nkeys, idx, snapshots, t_nodes)

    def pick(a, b):
        m = crash_mask.reshape((N,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    return jax.tree.map(pick, node_state, fresh)


def member_bits(member):
    """Fold the ``[N]`` member plane into the int32 bitmask the node
    step consumes (bit ``i`` = node ``i`` is an administrative member —
    the reconfiguration TARGET Raft's joint consensus drives toward).
    ``N <= 30`` is enforced at spec time (``spec.MAX_MEMBER_NODES``)."""
    import jax.numpy as jnp

    n = member.shape[0]
    return jnp.sum(jnp.where(member,
                             jnp.int32(1) << jnp.arange(n,
                                                        dtype=jnp.int32),
                             0)).astype(jnp.int32)


def wipe_parked(model, node_state, snapshots, park_mask, m_bits,
                t_nodes, wipe_key, cfg, params):
    """Hold non-(stable-)members parked: rebuild each parked row via
    ``Model.join_row`` (snapshot-slab recovery + the CURRENT target
    bitmask as the re-provisioned cluster config) and select it in
    under the park mask. The mask covers ``~(member & member_prev)`` —
    every non-member tick AND the join-edge tick itself, so a joining
    node's final rebuild sees the bitmask that includes it. One
    instance's unbatched state; the runtime vmaps this in both
    layouts, mirroring :func:`wipe_crashed`."""
    import jax
    import jax.numpy as jnp

    N = cfg.n_nodes
    idx = jnp.arange(N, dtype=jnp.int32)
    nkeys = jax.vmap(lambda i: jax.random.fold_in(wipe_key, i))(idx)
    fresh = jax.vmap(
        lambda nk, ni, snap, tn: model.join_row(N, ni, nk, params,
                                                snap, tn, m_bits))(
        nkeys, idx, snapshots, t_nodes)

    def pick(a, b):
        m = park_mask.reshape((N,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    return jax.tree.map(pick, node_state, fresh)


def retarget_clients(reqs, member):
    """Remap client request destinations onto the CURRENT member list
    (clients only talk to nodes that exist — the reference's client
    node-list refresh on reconfiguration). ``reqs`` is one instance's
    ``[C, L]`` request block with server dests in ``[0, N)``; the remap
    is ``members_sorted[dest % n_members]``, which is the identity when
    everyone is a member (``argsort`` of an all-False key is stable ->
    ``[0..N)``, and ``dest % N == dest``), keeping all-healthy lanes
    bit-identical."""
    import jax.numpy as jnp

    from ..tpu import wire

    order = jnp.argsort(~member).astype(jnp.int32)  # members first,
    #                                                 ascending ids
    n_m = jnp.maximum(jnp.sum(member).astype(jnp.int32), 1)
    dest = reqs[:, wire.DEST]
    return reqs.at[:, wire.DEST].set(order[dest % n_m])


def update_snapshots(model, node_state, snapshots, crash_mask, t,
                     every: int):
    """Fold the tick's end-of-tick durable state into the snapshot slab
    (one instance, leaves ``[N, ...]``). Crashed (held-in-reset) nodes
    never overwrite their slab row — the slab keeps the kill-point
    state for the restart. ``every == 1`` is write-through durability;
    larger strides snapshot on tick ``t`` with ``(t + 1) % every == 0``
    (asynchronous persistence — the tail since the last snapshot is
    genuinely lost on a crash)."""
    import jax
    import jax.numpy as jnp

    fresh = model.snapshot_row(node_state)

    def mix(s, v):
        m = crash_mask.reshape((crash_mask.shape[0],)
                               + (1,) * (v.ndim - 1))
        out = jnp.where(m, s, v)
        if every > 1:
            out = jnp.where((t + 1) % every == 0, out, s)
        return out

    return jax.tree.map(mix, snapshots, fresh)


# --- host-side reporting ---------------------------------------------------


def phase_at(fx: FaultConfig, tick: int) -> int:
    """Host-side phase index at ``tick`` (``len(untils)`` = healthy;
    the heartbeat's fault-epoch lane — the plan is deterministic, so
    the host needs no device traffic to know it)."""
    if not fx.active or tick >= fx.stop_tick:
        return len(fx.untils)
    return int(np.searchsorted(np.asarray(fx.untils, dtype=np.int64),
                               tick, side="right"))


def _members_at(fx: FaultConfig, p: int) -> Optional[set]:
    """Phase ``p``'s absolute member set (the trailing heal row — and
    any phase past the lane's tuples — is everyone), or ``None`` when
    the lane is absent."""
    if fx.members is None:
        return None
    if 0 <= p < len(fx.members):
        return set(fx.members[p])
    return set(range(fx.n_nodes))


def _membership_epoch(fx: FaultConfig, p: int) -> Optional[Dict[str, Any]]:
    """The phase's membership record: the current member set, who is
    OUT relative to the full cluster, and the join events at the phase
    start (what ``watch`` renders as ``membership +1/-2``)."""
    cur = _members_at(fx, p)
    if cur is None:
        return None
    prev = _members_at(fx, p - 1) if p > 0 else cur
    out: Dict[str, Any] = {"members": sorted(cur)}
    joined = sorted(cur - prev)
    removed = sorted(set(range(fx.n_nodes)) - cur)
    if joined:
        out["joined"] = joined
    if removed:
        out["removed"] = removed
    return out


def phase_summary(fx: FaultConfig, tick: int) -> Dict[str, Any]:
    """The heartbeat's per-chunk fault-epoch record: which phase the
    chunk ended in and which lanes it had active."""
    p = phase_at(fx, tick)
    out: Dict[str, Any] = {"phase": p, "phases": len(fx.untils)}
    if p >= len(fx.untils):
        out["healthy"] = True
        return out
    if p < len(fx.crash) and fx.crash[p]:
        out["crashed"] = sorted(fx.crash[p])
    if p < len(fx.links) and fx.links[p]:
        out["degraded-edges"] = len(fx.links[p])
    if p < len(fx.skew) and fx.skew[p]:
        out["skewed-nodes"] = len(fx.skew[p])
    mem = _membership_epoch(fx, p)
    if mem is not None:
        out["membership"] = mem
    return out


def span_summary(fx: FaultConfig, t0: int, ticks: int) -> Dict[str, Any]:
    """Fault-epoch record for a tick RANGE (a dispatched chunk): the
    union of lanes active anywhere in ``[t0, t0 + ticks)``, plus the
    phase the span ended in. Chunks are coarser than phases, so a
    point sample at the chunk end would miss short fault windows."""
    end = t0 + max(1, int(ticks)) - 1
    out: Dict[str, Any] = {"phase": phase_at(fx, end),
                           "phases": len(fx.untils)}
    crashed: set = set()
    edges = 0
    skewed = 0
    joined: set = set()
    removed: set = set()
    members_end: Optional[set] = None
    healthy = True
    for p in range(len(fx.untils)):
        lo = fx.untils[p - 1] if p else 0
        hi = min(fx.untils[p], fx.stop_tick)
        if lo >= t0 + ticks or hi <= t0:
            continue
        if p < len(fx.crash) and fx.crash[p]:
            crashed.update(fx.crash[p])
            healthy = False
        if p < len(fx.links) and fx.links[p]:
            edges = max(edges, len(fx.links[p]))
            healthy = False
        if p < len(fx.skew) and fx.skew[p]:
            skewed = max(skewed, len(fx.skew[p]))
            healthy = False
        mem = _membership_epoch(fx, p)
        if mem is not None:
            joined.update(mem.get("joined", ()))
            removed.update(mem.get("removed", ()))
            members_end = set(mem["members"])
    if members_end is not None and (joined or removed
                                    or len(members_end) < fx.n_nodes):
        # join/remove events in the span, or nodes parked through it
        healthy = False
    if healthy:
        out["healthy"] = True
        return out
    if crashed:
        out["crashed"] = sorted(crashed)
    if edges:
        out["degraded-edges"] = edges
    if skewed:
        out["skewed-nodes"] = skewed
    if members_end is not None:
        out["membership"] = {"members": sorted(members_end),
                             **({"joined": sorted(joined)}
                                if joined else {}),
                             **({"removed": sorted(removed)}
                                if removed else {})}
    return out


def plan_summary(fx: FaultConfig) -> Dict[str, Any]:
    """The run-start heartbeat record's fault block: enough to label a
    live report without re-shipping the whole plan (the repro opts
    carry the full spec)."""
    lanes = [name for name, on in (("crash-restart", fx.has_crash),
                                   ("link-degradation", fx.has_links),
                                   ("clock-skew", fx.has_skew),
                                   ("membership", fx.has_members)) if on]
    out: Dict[str, Any] = {"phases": len(fx.untils), "lanes": lanes,
                           "snapshot-every": fx.snapshot_every,
                           "stop-tick": int(fx.stop_tick)}
    if fx.has_fuzz:
        # per-instance randomized schedules: no shared phase timeline;
        # the distribution block + fleet coverage counters label the run
        from .fuzz import fuzz_summary
        out["fuzz"] = fuzz_summary(fx)
    return out
