"""The device side of the fault-plan engine.

:class:`FaultConfig` is the compiled, hashable form of a fault plan: a
phase timeline (``untils``) plus, per phase, the crash victims, the
degraded directed edges, and the per-node clock rates — all plain
nested tuples so ``SimConfig`` stays a static jit argument. At trace
time the phases are baked into constant planes (one row per phase plus
a trailing all-healthy row) and the tick selects its row with a single
``searchsorted`` over ``t`` — the same constant-folding move the
scripted partition nemesis uses (``runtime.partition_matrix``).

Lane semantics (shared by BOTH carry layouts — the helpers here take
ONE instance's unbatched state, and the runtime vmaps them exactly like
every other tick phase, so lead/minor trajectories stay bit-identical):

- ``crash`` — victims are held in reset for the whole phase: every
  crashed tick the node row is rebuilt via ``Model.restart_row`` (from
  the snapshot slab — its durable storage — or cold from the init
  path), delivery TO the victim is blocked via the partition plane (the
  recv-side drop IS the lost inbox), and the victim's emitted rows are
  invalidated before enqueue. Messages already in flight FROM the
  victim still deliver — they are on the wire, not in the dead process.
  The snapshot slab captures ``Model.snapshot_row`` of every healthy
  node each ``snapshot_every`` ticks (1 = write-through durability: the
  slab always holds the kill-point state; larger strides model
  asynchronous persistence, where losing the tail is a legitimate
  finding, not a checker bug).
- ``links`` — per-directed-edge ``(dest, origin)`` quality: ``block``
  folds into the delivery partition plane (asymmetric partitions),
  ``delay`` adds ticks to the sampled latency at enqueue time, and
  ``loss_pm`` (per-mille) is an extra independent loss roll. Neutral
  values (0) are value-identical to the healthy path.
- ``skew`` — per-node clock rate in 64ths (64 = 1.0x): the node phase
  runs each node's timers on ``local_t = (t * rate) // 64``. Rate 64 is
  exactly ``t`` (no rounding), so a neutral skew lane is bit-identical.

Everything here is traced (fixed shapes, jnp only, static branches on
the config) and linted with the models (``maelstrom lint --strict``).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

NEUTRAL_RATE = 64          # skew rates are 64ths; 64 == 1.0x (exact)


class FaultConfig(NamedTuple):
    """Static, hashable fault plan (rides ``SimConfig.faults``).

    ``untils`` are the strictly-increasing phase end ticks; phase ``p``
    covers ``[untils[p-1], untils[p])`` (phase 0 starts at tick 0) and
    every tick at/after ``untils[-1]`` or ``stop_tick`` — the final
    heal window — is healthy. The per-phase lane tuples are aligned
    with ``untils``:

    - ``crash[p]``   — tuple of crashed server-node ids
    - ``links[p]``   — tuples ``(dst, src, block, delay, loss_pm)``
    - ``skew[p]``    — tuples ``(node, rate64)``

    ``fuzz`` (a :class:`~.fuzz.FuzzConfig`, or ``None``) switches the
    config from ONE deterministic fleet-shared plan to per-instance
    RANDOMIZED schedules drawn on device (``faults/fuzz.py``): the
    phase tuples stay empty then, and lane presence comes from the
    distribution instead. Mutually exclusive with a phase timeline.
    """
    enabled: bool = False
    stop_tick: int = 1 << 30
    snapshot_every: int = 1
    untils: Tuple[int, ...] = ()
    crash: Tuple[Tuple[int, ...], ...] = ()
    links: Tuple[Tuple[Tuple[int, int, int, int, int], ...], ...] = ()
    skew: Tuple[Tuple[Tuple[int, int], ...], ...] = ()
    fuzz: Optional[Any] = None    # FuzzConfig (hashable NamedTuple)

    # lane presence is a STATIC property: a lane is "present" when any
    # phase lists entries for it (even value-neutral ones) — or, under
    # a fuzz distribution, when the lane is configured at all (even at
    # rate 0: the all-healthy probe keeps the machinery in the graph).
    # Only present lanes add anything to the traced tick — a default
    # FaultConfig() compiles the exact pre-fault graph.
    @property
    def has_fuzz(self) -> bool:
        return self.fuzz is not None and self.fuzz.enabled

    @property
    def has_crash(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_crash
        return self.enabled and any(len(p) for p in self.crash)

    @property
    def has_links(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_links
        return self.enabled and any(len(p) for p in self.links)

    @property
    def has_skew(self) -> bool:
        if self.has_fuzz:
            return self.fuzz.has_skew
        return self.enabled and any(len(p) for p in self.skew)

    @property
    def active(self) -> bool:
        return self.has_crash or self.has_links or self.has_skew


class FaultPlanes(NamedTuple):
    """One tick's selected fault state (``None`` = lane not present,
    statically — the runtime's fault branches key on these)."""
    crash: Optional[Any] = None      # [N] bool — nodes held in reset
    block: Optional[Any] = None      # [NT, NT] bool — recv-side drops
    delay: Optional[Any] = None      # [NT, NT] int32 — extra latency
    loss_pm: Optional[Any] = None    # [NT, NT] int32 — per-mille loss
    t_nodes: Optional[Any] = None    # [N] int32 — per-node local clock


NO_PLANES = FaultPlanes()


@lru_cache(maxsize=64)
def _planes_np(fx: FaultConfig, n_nodes: int, n_clients: int):
    """Bake the phase timeline into dense per-phase numpy planes
    (row ``P`` = the trailing all-healthy phase). Cached: FaultConfig
    is hashable and the planes are pure functions of it."""
    NT = n_nodes + n_clients
    P = len(fx.untils)
    crash = np.zeros((P + 1, n_nodes), dtype=bool)
    block = np.zeros((P + 1, NT, NT), dtype=bool)
    delay = np.zeros((P + 1, NT, NT), dtype=np.int32)
    loss = np.zeros((P + 1, NT, NT), dtype=np.int32)
    skew = np.full((P + 1, n_nodes), NEUTRAL_RATE, dtype=np.int32)
    for p in range(P):
        if p < len(fx.crash):
            for v in fx.crash[p]:
                crash[p, v] = True
                # a dead process hears nobody — servers AND clients;
                # its own in-flight sends still deliver (origin edges
                # are NOT blocked)
                block[p, v, :] = True
        if p < len(fx.links):
            for dst, src, blk, d, pm in fx.links[p]:
                # duplicate entries for one directed edge MERGE (the
                # spec promises "one edge may combine delay and loss",
                # and plans often list them as separate entries) —
                # last-writer-wins would silently zero earlier fields
                if blk:
                    block[p, dst, src] = True
                delay[p, dst, src] = max(delay[p, dst, src], d)
                loss[p, dst, src] = max(loss[p, dst, src], pm)
        if p < len(fx.skew):
            for node, rate in fx.skew[p]:
                skew[p, node] = rate
    untils = np.asarray(fx.untils, dtype=np.int32)
    return untils, crash, block, delay, loss, skew


def tick_planes(fx: FaultConfig, cfg, t) -> FaultPlanes:
    """Select tick ``t``'s planes (traced; constants baked per phase).
    ``cfg`` is the NetConfig (static). Ticks at/after ``stop_tick``
    read the all-healthy row — the final heal window. Fuzz configs
    have no shared timeline — the runtime routes them through
    ``fuzz.schedule_planes`` per instance instead."""
    if fx.has_fuzz:
        raise ValueError("tick_planes on a fuzz config: per-instance "
                         "planes come from fuzz.schedule_planes")
    if not fx.active:
        return NO_PLANES
    import jax.numpy as jnp

    untils, crash, block, delay, loss, skew = _planes_np(
        fx, cfg.n_nodes, cfg.n_clients)
    P = len(fx.untils)
    phase = jnp.searchsorted(jnp.asarray(untils), t, side="right")
    phase = jnp.clip(jnp.where(t < fx.stop_tick, phase, P), 0, P)
    out = {}
    if fx.has_crash:
        out["crash"] = jnp.asarray(crash)[phase]
    if fx.has_crash or _any_block(fx):
        out["block"] = jnp.asarray(block)[phase]
    if fx.has_links:
        out["delay"] = jnp.asarray(delay)[phase]
        out["loss_pm"] = jnp.asarray(loss)[phase]
    if fx.has_skew:
        out["t_nodes"] = (t * jnp.asarray(skew)[phase]) // NEUTRAL_RATE
    return FaultPlanes(**out)


def _any_block(fx: FaultConfig) -> bool:
    return any(e[2] for p in fx.links for e in p) or fx.has_crash


def wipe_crashed(model, node_state, snapshots, crash_mask, t_nodes,
                 wipe_key, cfg, params):
    """Hold crashed nodes in reset: rebuild each victim's row via
    ``Model.restart_row`` (per-node restart RNG folded off
    ``wipe_key``) and select it in under the crash mask. One instance's
    unbatched state (``node_state`` leaves ``[N, ...]``); the runtime
    vmaps this over instances in both layouts."""
    import jax
    import jax.numpy as jnp

    N = cfg.n_nodes
    idx = jnp.arange(N, dtype=jnp.int32)
    nkeys = jax.vmap(lambda i: jax.random.fold_in(wipe_key, i))(idx)
    fresh = jax.vmap(
        lambda nk, ni, snap, tn: model.restart_row(N, ni, nk, params,
                                                   snap, tn))(
        nkeys, idx, snapshots, t_nodes)

    def pick(a, b):
        m = crash_mask.reshape((N,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    return jax.tree.map(pick, node_state, fresh)


def update_snapshots(model, node_state, snapshots, crash_mask, t,
                     every: int):
    """Fold the tick's end-of-tick durable state into the snapshot slab
    (one instance, leaves ``[N, ...]``). Crashed (held-in-reset) nodes
    never overwrite their slab row — the slab keeps the kill-point
    state for the restart. ``every == 1`` is write-through durability;
    larger strides snapshot on tick ``t`` with ``(t + 1) % every == 0``
    (asynchronous persistence — the tail since the last snapshot is
    genuinely lost on a crash)."""
    import jax
    import jax.numpy as jnp

    fresh = model.snapshot_row(node_state)

    def mix(s, v):
        m = crash_mask.reshape((crash_mask.shape[0],)
                               + (1,) * (v.ndim - 1))
        out = jnp.where(m, s, v)
        if every > 1:
            out = jnp.where((t + 1) % every == 0, out, s)
        return out

    return jax.tree.map(mix, snapshots, fresh)


# --- host-side reporting ---------------------------------------------------


def phase_at(fx: FaultConfig, tick: int) -> int:
    """Host-side phase index at ``tick`` (``len(untils)`` = healthy;
    the heartbeat's fault-epoch lane — the plan is deterministic, so
    the host needs no device traffic to know it)."""
    if not fx.active or tick >= fx.stop_tick:
        return len(fx.untils)
    return int(np.searchsorted(np.asarray(fx.untils, dtype=np.int64),
                               tick, side="right"))


def phase_summary(fx: FaultConfig, tick: int) -> Dict[str, Any]:
    """The heartbeat's per-chunk fault-epoch record: which phase the
    chunk ended in and which lanes it had active."""
    p = phase_at(fx, tick)
    out: Dict[str, Any] = {"phase": p, "phases": len(fx.untils)}
    if p >= len(fx.untils):
        out["healthy"] = True
        return out
    if p < len(fx.crash) and fx.crash[p]:
        out["crashed"] = sorted(fx.crash[p])
    if p < len(fx.links) and fx.links[p]:
        out["degraded-edges"] = len(fx.links[p])
    if p < len(fx.skew) and fx.skew[p]:
        out["skewed-nodes"] = len(fx.skew[p])
    return out


def span_summary(fx: FaultConfig, t0: int, ticks: int) -> Dict[str, Any]:
    """Fault-epoch record for a tick RANGE (a dispatched chunk): the
    union of lanes active anywhere in ``[t0, t0 + ticks)``, plus the
    phase the span ended in. Chunks are coarser than phases, so a
    point sample at the chunk end would miss short fault windows."""
    end = t0 + max(1, int(ticks)) - 1
    out: Dict[str, Any] = {"phase": phase_at(fx, end),
                           "phases": len(fx.untils)}
    crashed: set = set()
    edges = 0
    skewed = 0
    healthy = True
    for p in range(len(fx.untils)):
        lo = fx.untils[p - 1] if p else 0
        hi = min(fx.untils[p], fx.stop_tick)
        if lo >= t0 + ticks or hi <= t0:
            continue
        if p < len(fx.crash) and fx.crash[p]:
            crashed.update(fx.crash[p])
            healthy = False
        if p < len(fx.links) and fx.links[p]:
            edges = max(edges, len(fx.links[p]))
            healthy = False
        if p < len(fx.skew) and fx.skew[p]:
            skewed = max(skewed, len(fx.skew[p]))
            healthy = False
    if healthy:
        out["healthy"] = True
        return out
    if crashed:
        out["crashed"] = sorted(crashed)
    if edges:
        out["degraded-edges"] = edges
    if skewed:
        out["skewed-nodes"] = skewed
    return out


def plan_summary(fx: FaultConfig) -> Dict[str, Any]:
    """The run-start heartbeat record's fault block: enough to label a
    live report without re-shipping the whole plan (the repro opts
    carry the full spec)."""
    lanes = [name for name, on in (("crash-restart", fx.has_crash),
                                   ("link-degradation", fx.has_links),
                                   ("clock-skew", fx.has_skew)) if on]
    out: Dict[str, Any] = {"phases": len(fx.untils), "lanes": lanes,
                           "snapshot-every": fx.snapshot_every,
                           "stop-tick": int(fx.stop_tick)}
    if fx.has_fuzz:
        # per-instance randomized schedules: no shared phase timeline;
        # the distribution block + fleet coverage counters label the run
        from .fuzz import fuzz_summary
        out["fuzz"] = fuzz_summary(fx)
    return out
