"""Randomized per-instance fault schedules: the device-resident fuzzer.

PR 9's fault-plan engine runs ONE deterministic, fleet-shared schedule
per sweep — every instance sees the same crashes at the same ticks, so
a 100k-instance fleet explores exactly one point of the fault space per
run. This module turns the same silicon into a fault-space search
engine: a declarative **fault distribution** (CLI ``--fault-fuzz
file.json``, campaign ``fault_fuzz`` key) compiles to a static
:class:`FuzzConfig`, and at ``init_carry`` time each instance draws its
OWN schedule on device from the dedicated schedule-RNG purpose
(``runtime._RNG_FAULTS`` = :data:`RNG_PURPOSE`, instance-stable like
``_RNG_RESTART``) — so 100k instances each run a DIFFERENT randomized
crash/link/skew schedule inside one ``lax.scan``, in both carry
layouts and through the sharded driver.

Distribution format (ranges are inclusive ``[lo, hi]``; scalars read as
``lo == hi``):

.. code-block:: json

    {"windows": [1, 3],
     "gap": [50, 200],
     "duration": [30, 120],
     "crash": {"rate": 0.8, "victims": [1, 2]},
     "links": {"rate": 0.5, "edges": [1, 4], "block": 0.3,
               "delay": [0, 40], "loss": [0.0, 0.4]},
     "skew":  {"rate": 0.3, "victims": [1, 2], "range": [0.5, 2.0]},
     "membership": {"rate": 0.4, "victims": [1, 2]},
     "snapshot_every": 1}

- ``windows`` — fault-window count per schedule; each window is a
  healthy ``gap`` followed by a ``duration``-tick fault phase (the
  heal/fault alternation of the deterministic generator, with every
  width drawn per instance).
- per-lane blocks — ``rate`` is the per-window activation probability;
  ``victims``/``edges`` the victim-count range (distinct nodes via an
  on-device permutation; directed non-self edges for links); ``delay``/
  ``loss``/``block``/``range`` the per-victim quality draws.
- ``membership`` — per window, the drawn victims are REMOVED from the
  cluster (parked like crash victims, clients re-targeted, and the
  shrunk member bitmask handed to the node step as the
  reconfiguration target — Raft drives the change through joint
  consensus) and re-added when the window ends (a join: slab
  recovery, catch-up gating). ``victims`` is capped at ``n_nodes - 1``
  so no draw can ever empty the cluster.

The drawn :class:`FaultSchedule` is a small int32/bool pytree that
RIDES THE CARRY (``Carry.fault_sched``) so checkpoint/resume and triage
replay stay bit-exact, and each tick selects its planes with the same
``searchsorted(t)`` move the deterministic engine uses
(:func:`schedule_planes`). Every draw is integer-only
(``randint``/permutation — no float thresholds), so a schedule is a
bit-stable pure function of ``(seed, instance_id)`` across backends:
:func:`reconstruct_schedule` re-draws any instance's schedule host-side
and :func:`schedule_to_plan` lowers it to a deterministic ``--fault-
plan`` dict whose compiled planes are value-identical — the foundation
of ``maelstrom shrink`` (``faults/shrink.py``).

All-healthy draws (every rate roll failing, or a ``rate: 0``
distribution) produce value-neutral planes — zero delay/loss, rate-64
clocks, no crashes — which PR 9 proved bit-identical to the fault-free
tick, so fuzzed fleets pay only the schedule-select overhead on clean
instances (``BENCH_FUZZ=0`` A/B in bench.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from .engine import NEUTRAL_RATE, FaultConfig, FaultPlanes
from .spec import (MAX_DELAY_TICKS, MAX_RATE, MIN_RATE, SpecError,
                   _get, membership_heal_phases)

# the schedule-RNG purpose tag (tpu/runtime.py aliases this as
# _RNG_FAULTS): schedule keys fold (master, RNG_PURPOSE, instance_id) —
# instance-stable, tick-independent, so a schedule is reconstructable
# from the seed alone
RNG_PURPOSE = 6

MAX_WINDOWS = 16          # schedule-size ceiling: 2*W untils must stay
                          # far inside int32 tick arithmetic


class LaneFuzz(NamedTuple):
    """One lane's slice of the distribution (all-int, hashable).

    ``victims_max == 0`` means the lane is NOT CONFIGURED (statically
    absent from the traced graph, like an empty plan lane). A
    configured lane with ``rate_pm == 0`` is present-but-neutral: the
    machinery traces, every draw is healthy — the all-healthy
    bit-identity probe."""
    rate_pm: int = 0          # per-window activation probability (per-mille)
    victims_min: int = 0      # victim count range (nodes, or directed
    victims_max: int = 0      # edges for the links lane)
    block_pm: int = 0         # links: P(edge blocked), per-mille
    delay_min: int = 0        # links: extra latency ticks
    delay_max: int = 0
    loss_pm_min: int = 0      # links: per-mille loss
    loss_pm_max: int = 0
    rate64_min: int = NEUTRAL_RATE   # skew: clock rate in 64ths
    rate64_max: int = NEUTRAL_RATE


class FuzzConfig(NamedTuple):
    """Compiled fault distribution (rides ``FaultConfig.fuzz``; plain
    ints so ``SimConfig`` stays a static, hashable jit argument)."""
    enabled: bool = False
    windows_min: int = 0
    windows_max: int = 0
    gap_min: int = 0
    gap_max: int = 0
    dur_min: int = 0
    dur_max: int = 0
    crash: LaneFuzz = LaneFuzz()
    links: LaneFuzz = LaneFuzz()
    skew: LaneFuzz = LaneFuzz()
    membership: LaneFuzz = LaneFuzz()

    @property
    def has_crash(self) -> bool:
        return self.enabled and self.crash.victims_max > 0

    @property
    def has_links(self) -> bool:
        return self.enabled and self.links.victims_max > 0

    @property
    def has_skew(self) -> bool:
        return self.enabled and self.skew.victims_max > 0

    @property
    def has_membership(self) -> bool:
        return self.enabled and self.membership.victims_max > 0


class FaultSchedule(NamedTuple):
    """One instance's drawn schedule (int32/bool leaves, unbatched; the
    runtime batches it over instances in the carry's own layout).

    ``untils`` is the interleaved heal/fault timeline cumsum: phase
    ``2w`` is window ``w``'s healthy gap, phase ``2w + 1`` the fault
    window itself, so ``searchsorted(untils, t)`` lands in a window iff
    the phase index is odd. Unconfigured lanes carry zero-size (links)
    or all-neutral (crash/skew) planes that the static presence flags
    keep out of the traced tick."""
    untils: Any          # [2W] int32 — cumulative phase boundaries
    crash: Any           # [W, N] bool — per-window crash masks
    edge_dst: Any        # [W, E] int32 — directed-edge victims
    edge_src: Any        # [W, E] int32
    edge_block: Any      # [W, E] int32 0/1
    edge_delay: Any      # [W, E] int32 extra ticks
    edge_loss_pm: Any    # [W, E] int32 per-mille
    skew: Any            # [W, N] int32 rate64 (NEUTRAL_RATE = healthy)
    mem_out: Any         # [W, N] bool — nodes REMOVED during window w
    #                      (re-added at the window end; all-False when
    #                      the membership lane is unconfigured)


def _err(msg: str) -> SpecError:
    return SpecError(f"fault fuzz: {msg}")


def _range(v, what: str, lo_bound, hi_bound, cast=int) -> Tuple:
    """Parse an inclusive ``[lo, hi]`` range (scalar = degenerate)."""
    if isinstance(v, (list, tuple)):
        if len(v) != 2:
            raise _err(f"{what} range must be [lo, hi], got {v!r}")
        lo, hi = cast(v[0]), cast(v[1])
    else:
        try:
            lo = hi = cast(v)
        except (TypeError, ValueError):
            raise _err(f"{what} {v!r} is not a number or [lo, hi]")
    if lo > hi:
        raise _err(f"{what} range [{lo}, {hi}] has lo > hi")
    if lo < lo_bound or hi > hi_bound:
        raise _err(f"{what} range [{lo}, {hi}] out of "
                   f"[{lo_bound}, {hi_bound}]")
    return lo, hi


def _rate_pm(v, what: str) -> int:
    p = float(v or 0.0)
    if not 0.0 <= p <= 1.0:
        raise _err(f"{what} rate {p} out of [0, 1]")
    return int(round(p * 1000))


def validate_fault_fuzz(dist: Dict[str, Any], n_nodes: int) -> None:
    """Raise :class:`SpecError` on a malformed distribution (compile
    calls this first; the CLI calls it directly for friendly errors)."""
    if not isinstance(dist, dict):
        raise _err(f"top level must be a dict, got "
                   f"{type(dist).__name__}")
    _range(_get(dist, "windows", 1), "windows", 1, MAX_WINDOWS)
    _range(_get(dist, "gap", [0, 0]), "gap", 0, MAX_DELAY_TICKS)
    _range(_get(dist, "duration", [1, 1]), "duration", 1,
           MAX_DELAY_TICKS)
    every = _get(dist, "snapshot_every", 1)
    if every is not None and int(every) < 1:
        raise _err(f"snapshot_every must be >= 1, got {every}")
    lanes = 0
    crash = _get(dist, "crash")
    if crash is not None:
        _rate_pm(_get(crash, "rate", 0.0), "crash")
        _range(_get(crash, "victims", 1), "crash victims", 1, n_nodes)
        lanes += 1
    links = _get(dist, "links")
    if links is not None:
        if n_nodes < 2:
            raise _err("links lane needs >= 2 server nodes")
        _rate_pm(_get(links, "rate", 0.0), "links")
        _range(_get(links, "edges", 1), "links edges", 1,
               n_nodes * (n_nodes - 1))
        _rate_pm(_get(links, "block", 0.0), "links block")
        _range(_get(links, "delay", [0, 0]), "links delay", 0,
               MAX_DELAY_TICKS)
        _range(_get(links, "loss", [0.0, 0.0]), "links loss", 0.0, 1.0,
               cast=float)
        lanes += 1
    skew = _get(dist, "skew")
    if skew is not None:
        _rate_pm(_get(skew, "rate", 0.0), "skew")
        _range(_get(skew, "victims", 1), "skew victims", 1, n_nodes)
        _range(_get(skew, "range", [1.0, 1.0]), "skew range", MIN_RATE,
               MAX_RATE, cast=float)
        lanes += 1
    mem = _get(dist, "membership")
    if mem is not None:
        if n_nodes < 2:
            raise _err("membership lane needs >= 2 server nodes "
                       "(removing the only node would empty the "
                       "cluster)")
        from .spec import MAX_MEMBER_NODES
        if n_nodes > MAX_MEMBER_NODES:
            raise _err(f"membership lane supports at most "
                       f"{MAX_MEMBER_NODES} server nodes (int32 "
                       f"member bitmask), got n_nodes={n_nodes}")
        _rate_pm(_get(mem, "rate", 0.0), "membership")
        # victims cap n_nodes - 1: no draw may ever EMPTY the cluster
        _range(_get(mem, "victims", 1), "membership victims", 1,
               n_nodes - 1)
        lanes += 1
    if lanes == 0:
        raise _err("needs at least one lane block "
                   "(crash / links / skew / membership)")


def compile_fault_fuzz(dist: Optional[Dict[str, Any]], n_nodes: int,
                       stop_tick: int,
                       snapshot_every: Optional[int] = None
                       ) -> FaultConfig:
    """Lower a distribution dict to the static :class:`FaultConfig`
    carrying a :class:`FuzzConfig` (``dist=None`` compiles the disabled
    config, exactly like ``compile_fault_plan(None, ...)``)."""
    if not dist:
        return FaultConfig()
    validate_fault_fuzz(dist, n_nodes)
    w_lo, w_hi = _range(_get(dist, "windows", 1), "windows", 1,
                        MAX_WINDOWS)
    g_lo, g_hi = _range(_get(dist, "gap", [0, 0]), "gap", 0,
                        MAX_DELAY_TICKS)
    d_lo, d_hi = _range(_get(dist, "duration", [1, 1]), "duration", 1,
                        MAX_DELAY_TICKS)
    crash = links = skew = membership = LaneFuzz()
    c = _get(dist, "crash")
    if c is not None:
        v_lo, v_hi = _range(_get(c, "victims", 1), "crash victims", 1,
                            n_nodes)
        crash = LaneFuzz(rate_pm=_rate_pm(_get(c, "rate", 0.0), "crash"),
                         victims_min=v_lo, victims_max=v_hi)
    e = _get(dist, "links")
    if e is not None:
        e_lo, e_hi = _range(_get(e, "edges", 1), "links edges", 1,
                            n_nodes * (n_nodes - 1))
        dl_lo, dl_hi = _range(_get(e, "delay", [0, 0]), "links delay",
                              0, MAX_DELAY_TICKS)
        lp_lo, lp_hi = _range(_get(e, "loss", [0.0, 0.0]), "links loss",
                              0.0, 1.0, cast=float)
        links = LaneFuzz(
            rate_pm=_rate_pm(_get(e, "rate", 0.0), "links"),
            victims_min=e_lo, victims_max=e_hi,
            block_pm=_rate_pm(_get(e, "block", 0.0), "links block"),
            delay_min=dl_lo, delay_max=dl_hi,
            loss_pm_min=int(round(lp_lo * 1000)),
            loss_pm_max=int(round(lp_hi * 1000)))
    s = _get(dist, "skew")
    if s is not None:
        v_lo, v_hi = _range(_get(s, "victims", 1), "skew victims", 1,
                            n_nodes)
        r_lo, r_hi = _range(_get(s, "range", [1.0, 1.0]), "skew range",
                            MIN_RATE, MAX_RATE, cast=float)
        skew = LaneFuzz(
            rate_pm=_rate_pm(_get(s, "rate", 0.0), "skew"),
            victims_min=v_lo, victims_max=v_hi,
            rate64_min=max(1, int(round(r_lo * NEUTRAL_RATE))),
            rate64_max=max(1, int(round(r_hi * NEUTRAL_RATE))))
    m = _get(dist, "membership")
    if m is not None:
        v_lo, v_hi = _range(_get(m, "victims", 1),
                            "membership victims", 1, n_nodes - 1)
        membership = LaneFuzz(
            rate_pm=_rate_pm(_get(m, "rate", 0.0), "membership"),
            victims_min=v_lo, victims_max=v_hi)
    plan_every = _get(dist, "snapshot_every", 1)
    every = int(snapshot_every if snapshot_every is not None
                else (1 if plan_every is None else plan_every))
    fz = FuzzConfig(enabled=True, windows_min=w_lo, windows_max=w_hi,
                    gap_min=g_lo, gap_max=g_hi, dur_min=d_lo,
                    dur_max=d_hi, crash=crash, links=links, skew=skew,
                    membership=membership)
    return FaultConfig(enabled=True, stop_tick=int(stop_tick),
                       snapshot_every=every, fuzz=fz,
                       n_nodes=int(n_nodes))


# --- the on-device schedule draw -------------------------------------------


def _fold_seq(key, n: int):
    """``[n]`` subkeys via the runtime's batched fold_in idiom."""
    import jax
    import jax.numpy as jnp
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(n, dtype=jnp.int32))


def draw_schedule(key, fx: FaultConfig, n_nodes: int) -> FaultSchedule:
    """Draw ONE instance's schedule (traced; integer draws only, so the
    result is a backend-stable pure function of ``key``). Each
    component folds its own subkey, so adding a lane to the
    distribution never perturbs another lane's draws."""
    import jax
    import jax.numpy as jnp

    fz = fx.fuzz
    N = n_nodes
    W = fz.windows_max
    E = fz.links.victims_max
    k_win, k_crash, k_links, k_skew, k_mem = (
        jax.random.fold_in(key, i) for i in (1, 2, 3, 4, 5))

    n_w = jax.random.randint(jax.random.fold_in(k_win, 0), (),
                             fz.windows_min, fz.windows_max + 1)
    gaps = jax.random.randint(jax.random.fold_in(k_win, 1), (W,),
                              fz.gap_min, fz.gap_max + 1)
    durs = jax.random.randint(jax.random.fold_in(k_win, 2), (W,),
                              fz.dur_min, fz.dur_max + 1)
    untils = jnp.cumsum(
        jnp.stack([gaps, durs], axis=1).reshape(-1)).astype(jnp.int32)
    w_live = jnp.arange(W) < n_w      # windows past the drawn count
    #                                   exist but carry no faults

    def roll(k, pm: int):
        # integer bernoulli: no float threshold, bit-stable everywhere
        return jax.random.randint(k, (), 0, 1000) < pm

    if fz.has_crash:
        def one_crash(kw):
            act = roll(jax.random.fold_in(kw, 0), fz.crash.rate_pm)
            nv = jax.random.randint(jax.random.fold_in(kw, 1), (),
                                    fz.crash.victims_min,
                                    fz.crash.victims_max + 1)
            perm = jax.random.permutation(jax.random.fold_in(kw, 2), N)
            mask = jnp.zeros((N,), bool).at[perm].set(
                jnp.arange(N) < nv)
            return mask & act
        crash = jax.vmap(one_crash)(_fold_seq(k_crash, W)) \
            & w_live[:, None]
    else:
        crash = jnp.zeros((W, N), bool)

    if fz.has_links:
        lf = fz.links

        def one_links(kw):
            act = roll(jax.random.fold_in(kw, 0), lf.rate_pm)
            ne = jax.random.randint(jax.random.fold_in(kw, 1), (),
                                    lf.victims_min, lf.victims_max + 1)
            live_e = (jnp.arange(E) < ne) & act
            dst = jax.random.randint(jax.random.fold_in(kw, 2), (E,),
                                     0, N)
            srcr = jax.random.randint(jax.random.fold_in(kw, 3), (E,),
                                      0, N - 1)
            src = srcr + (srcr >= dst)      # directed, never self
            blk = jax.random.randint(jax.random.fold_in(kw, 4), (E,),
                                     0, 1000) < lf.block_pm
            dly = jax.random.randint(jax.random.fold_in(kw, 5), (E,),
                                     lf.delay_min, lf.delay_max + 1)
            pm = jax.random.randint(jax.random.fold_in(kw, 6), (E,),
                                    lf.loss_pm_min, lf.loss_pm_max + 1)
            z = live_e.astype(jnp.int32)
            return (dst.astype(jnp.int32), src.astype(jnp.int32),
                    blk.astype(jnp.int32) * z, dly * z, pm * z)
        e_dst, e_src, e_blk, e_dly, e_pm = jax.vmap(one_links)(
            _fold_seq(k_links, W))
        zw = w_live[:, None].astype(jnp.int32)
        e_blk, e_dly, e_pm = e_blk * zw, e_dly * zw, e_pm * zw
    else:
        z = jnp.zeros((W, 0), jnp.int32)
        e_dst = e_src = e_blk = e_dly = e_pm = z

    if fz.has_skew:
        sf = fz.skew

        def one_skew(kw):
            act = roll(jax.random.fold_in(kw, 0), sf.rate_pm)
            nv = jax.random.randint(jax.random.fold_in(kw, 1), (),
                                    sf.victims_min, sf.victims_max + 1)
            perm = jax.random.permutation(jax.random.fold_in(kw, 2), N)
            victim = jnp.zeros((N,), bool).at[perm].set(
                jnp.arange(N) < nv)
            rate = jax.random.randint(jax.random.fold_in(kw, 3), (N,),
                                      sf.rate64_min, sf.rate64_max + 1)
            return jnp.where(victim & act, rate, NEUTRAL_RATE
                             ).astype(jnp.int32)
        skew = jax.vmap(one_skew)(_fold_seq(k_skew, W))
        skew = jnp.where(w_live[:, None], skew, NEUTRAL_RATE)
    else:
        skew = jnp.full((W, N), NEUTRAL_RATE, jnp.int32)

    if fz.has_membership:
        mf = fz.membership

        def one_mem(kw):
            act = roll(jax.random.fold_in(kw, 0), mf.rate_pm)
            nv = jax.random.randint(jax.random.fold_in(kw, 1), (),
                                    mf.victims_min, mf.victims_max + 1)
            perm = jax.random.permutation(jax.random.fold_in(kw, 2), N)
            mask = jnp.zeros((N,), bool).at[perm].set(
                jnp.arange(N) < nv)
            return mask & act
        mem_out = jax.vmap(one_mem)(_fold_seq(k_mem, W)) \
            & w_live[:, None]
    else:
        mem_out = jnp.zeros((W, N), bool)

    return FaultSchedule(untils=untils, crash=crash, edge_dst=e_dst,
                         edge_src=e_src, edge_block=e_blk,
                         edge_delay=e_dly, edge_loss_pm=e_pm,
                         skew=skew, mem_out=mem_out)


def schedule_planes(sched: FaultSchedule, fx: FaultConfig, cfg,
                    t) -> FaultPlanes:
    """Select tick ``t``'s planes from ONE instance's drawn schedule
    (traced; the runtime vmaps this over instances in both layouts —
    the per-instance analog of ``engine.tick_planes``). Plane merge
    semantics match ``engine._planes_np`` exactly — crashed receivers
    block whole rows, duplicate edges max-merge — so a schedule
    replayed as a deterministic plan selects value-identical planes."""
    import jax.numpy as jnp

    fz = fx.fuzz
    N = cfg.n_nodes
    NT = cfg.n_total
    W = fz.windows_max

    def window_at(tt):
        ph = jnp.searchsorted(sched.untils, tt, side="right")
        in_win = (ph % 2 == 1) & (ph < 2 * W) & (tt < fx.stop_tick)
        return jnp.clip(ph // 2, 0, W - 1), in_win

    w, in_window = window_at(t)
    out = {}
    if fz.has_crash:
        out["crash"] = sched.crash[w] & in_window
    if fz.has_membership:
        out["member"] = ~(sched.mem_out[w] & in_window)
        # last tick's membership (join-edge / park-mask source); tick 0
        # reads its own window timeline at -1 — the leading gap — so a
        # zero-gap first window parks its victims from the very start
        w_p, in_win_p = window_at(t - 1)
        out["member_prev"] = ~(sched.mem_out[w_p] & in_win_p)
    link_blocks = fz.has_links and fz.links.block_pm > 0
    if fz.has_crash or link_blocks or fz.has_membership:
        block = jnp.zeros((NT, NT), jnp.int32)
        if link_blocks:
            blk = sched.edge_block[w] * in_window.astype(jnp.int32)
            block = block.at[sched.edge_dst[w], sched.edge_src[w]].max(
                blk)
        block = block == 1
        if fz.has_crash:
            # a dead process hears nobody — servers AND clients
            crash_nt = jnp.zeros((NT,), bool).at[:N].set(out["crash"])
            block = block | crash_nt[:, None]
        if fz.has_membership:
            # a parked non-member hears nobody, exactly like a crash
            out_nt = jnp.zeros((NT,), bool).at[:N].set(~out["member"])
            block = block | out_nt[:, None]
        out["block"] = block
    if fz.has_links:
        act = in_window.astype(jnp.int32)
        dst, src = sched.edge_dst[w], sched.edge_src[w]
        out["delay"] = jnp.zeros((NT, NT), jnp.int32).at[dst, src].max(
            sched.edge_delay[w] * act)
        out["loss_pm"] = jnp.zeros((NT, NT), jnp.int32).at[
            dst, src].max(sched.edge_loss_pm[w] * act)
    if fz.has_skew:
        rate = jnp.where(in_window, sched.skew[w], NEUTRAL_RATE)
        out["t_nodes"] = (t * rate) // NEUTRAL_RATE
    return FaultPlanes(**out)


# --- host-side reconstruction (the seed -> schedule -> plan path) ----------


def reconstruct_schedule(fx: FaultConfig, n_nodes: int, seed: int,
                         instance_id: int) -> FaultSchedule:
    """Re-draw one instance's schedule host-side: the identical key
    chain ``init_carry`` uses — ``fold_in(fold_in(PRNGKey(seed),
    RNG_PURPOSE), instance_id)`` — through the identical traced draw,
    fetched to numpy. Integer draws make this bit-stable across
    backends, so a fuzz hit on a TPU fleet reconstructs exactly on a
    CPU triage box."""
    import jax

    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(int(seed)), RNG_PURPOSE),
        int(instance_id))
    sched = jax.jit(draw_schedule, static_argnums=(1, 2))(key, fx,
                                                          n_nodes)
    return FaultSchedule(*[np.asarray(x) for x in sched])


def schedule_to_plan(sched: FaultSchedule, fx: FaultConfig
                     ) -> Dict[str, Any]:
    """Lower a drawn schedule to a deterministic ``--fault-plan`` dict
    whose compiled planes are value-identical at every tick: windows
    with no drawn content merge into the healthy timeline (searchsorted
    skips them on device too), windows entirely past the final-heal
    ``stop_tick`` are dropped (healed on both paths), and all
    quantities roundtrip exactly (integer ticks, per-mille loss,
    64th-quantized skew)."""
    fz = fx.fuzz
    W = fz.windows_max
    untils = np.asarray(sched.untils).reshape(-1)
    phases: List[Dict[str, Any]] = []
    prev = 0
    pending_add: List[int] = []   # membership restores owed to the
    #                               next emitted phase (the window
    #                               ended; an unmatched trailing add is
    #                               covered by the final-heal row)
    for w in range(W):
        gap_end = int(untils[2 * w])
        win_end = int(untils[2 * w + 1])
        if gap_end >= int(fx.stop_tick) or win_end <= gap_end:
            continue
        ph: Dict[str, Any] = {}
        victims = np.nonzero(np.asarray(sched.crash[w]))[0]
        if victims.size:
            ph["crash"] = [int(v) for v in victims]
        removed = np.nonzero(np.asarray(sched.mem_out[w]))[0]
        if removed.size:
            ph["remove"] = [int(v) for v in removed]
        edges = []
        for e in range(np.asarray(sched.edge_dst).shape[1]):
            blk = int(sched.edge_block[w][e])
            dly = int(sched.edge_delay[w][e])
            pm = int(sched.edge_loss_pm[w][e])
            if not (blk or dly or pm):
                continue      # value-neutral edge: a no-op on device
            edges.append({"dst": int(sched.edge_dst[w][e]),
                          "src": int(sched.edge_src[w][e]),
                          "block": bool(blk), "delay": dly,
                          "loss": pm / 1000.0})
        if edges:
            ph["links"] = edges
        skew = {str(n): int(r) / NEUTRAL_RATE
                for n, r in enumerate(np.asarray(sched.skew[w]))
                if int(r) != NEUTRAL_RATE}
        if skew:
            ph["skew"] = skew
        # settle any owed membership rejoin FIRST: the previous
        # removal window's victims rejoin at its end tick (== the
        # start of whatever phase comes next), keeping the compiled
        # planes value-identical to the drawn schedule's timeline
        if pending_add:
            if gap_end > prev:
                phases.append({"until": gap_end,
                               "add": pending_add})
            else:
                # zero-width gap: the rejoin rides the next window
                # phase itself (membership_walk applies add, then
                # remove)
                ph["add"] = pending_add
            pending_add = []
        if not ph:
            continue          # contentless window: pure healthy time
        if gap_end > prev and (not phases
                               or int(phases[-1]["until"]) < gap_end):
            phases.append({"until": gap_end})
        phases.append({"until": win_end, **ph})
        prev = win_end
        if removed.size:
            pending_add = [int(v) for v in removed]
    if not phases:
        return {}             # an all-healthy draw IS the empty plan
    return {"snapshot_every": int(fx.snapshot_every), "phases": phases}


def reconstruct_plan(fx: FaultConfig, n_nodes: int, seed: int,
                     instance_id: int) -> Dict[str, Any]:
    """seed + instance id -> the instance's concrete schedule as a
    deterministic plan dict (``{}`` when the draw was all-healthy)."""
    return schedule_to_plan(
        reconstruct_schedule(fx, n_nodes, seed, instance_id), fx)


def plan_weight(plan: Dict[str, Any],
                n_nodes: Optional[int] = None) -> Tuple[int, int]:
    """(fault phases, total victims) of a plan dict — the shrinker's
    minimality metric and the acceptance bar's 'strictly fewer'.
    Membership REMOVALS count as victims (an explicit absolute
    ``members`` set counts once — but only when it actually removes a
    node; a restore/no-op set is a HEAL, see
    ``spec.membership_heal_phases``); rejoin ``add`` events are
    healing, not faults, and weigh nothing."""
    if not plan:
        return 0, 0
    heals = membership_heal_phases(plan, n_nodes)
    n_phases = 0
    victims = 0
    for i, ph in enumerate(plan.get("phases", ())):
        c = len(ph.get("crash") or [])
        e = len(ph.get("links") or [])
        s = len(ph.get("skew") or {})
        m = len(ph.get("remove") or []) \
            + (1 if ph.get("members") is not None
               and i not in heals else 0)
        if c or e or s or m:
            n_phases += 1
            victims += c + e + s + m
    return n_phases, victims


# --- fleet summaries (heartbeat fault-fuzz lane) ---------------------------


def fleet_windows(fx: FaultConfig, n_nodes: int, seed: int,
                  instance_ids) -> Dict[str, np.ndarray]:
    """Host-side view of the whole fleet's drawn windows: ``starts``/
    ``ends`` ``[I, W]`` (ends clipped to the final-heal ``stop_tick``)
    plus per-lane activity masks. One vmapped re-draw per run — the
    schedules are a pure function of the seed, so the heartbeat's
    fault-fuzz lane costs no mid-run device traffic."""
    import jax

    key = jax.random.fold_in(jax.random.PRNGKey(int(seed)), RNG_PURPOSE)
    ids = np.asarray(instance_ids, np.int32)

    def draw_one(i):
        return draw_schedule(jax.random.fold_in(key, i), fx, n_nodes)

    sched = jax.jit(jax.vmap(draw_one))(ids)
    untils = np.asarray(sched.untils)
    starts = untils[:, 0::2]
    ends = np.minimum(untils[:, 1::2], int(fx.stop_tick))
    crash = np.asarray(sched.crash).any(axis=-1)
    links = ((np.asarray(sched.edge_block)
              + np.asarray(sched.edge_delay)
              + np.asarray(sched.edge_loss_pm)) > 0).any(axis=-1) \
        if np.asarray(sched.edge_dst).shape[-1] else \
        np.zeros(starts.shape, bool)
    skew = (np.asarray(sched.skew) != NEUTRAL_RATE).any(axis=-1)
    membership = np.asarray(sched.mem_out).any(axis=-1)
    live = ends > starts
    return {"starts": starts, "ends": ends, "crash": crash & live,
            "links": links & live, "skew": skew & live,
            "membership": membership & live}


def span_counters(win: Dict[str, np.ndarray], t0: int,
                  ticks: int) -> Dict[str, int]:
    """The heartbeat's per-chunk fault-fuzz record: how many instances
    have a fault window overlapping ``[t0, t0 + ticks)``, per lane —
    the per-instance analog of ``engine.span_summary``."""
    t1 = int(t0) + max(1, int(ticks))
    ov = (win["starts"] < t1) & (win["ends"] > int(t0))
    out = {"schedules-active": int(
        (ov & (win["crash"] | win["links"] | win["skew"]
               | win["membership"]))
        .any(axis=1).sum())}
    for lane in ("crash", "links", "skew", "membership"):
        out[lane] = int((ov & win[lane]).any(axis=1).sum())
    return out


def fleet_coverage(win: Dict[str, np.ndarray]) -> Dict[str, int]:
    """Schedule-space coverage counters for the run-start heartbeat
    record: distinct schedules drawn and total fault windows per lane
    (the 'how much of the space did this sweep visit' label)."""
    sig = np.concatenate(
        [win["starts"], win["ends"],
         win["crash"].astype(np.int32), win["links"].astype(np.int32),
         win["skew"].astype(np.int32),
         win["membership"].astype(np.int32)], axis=1)
    return {
        "instances": int(sig.shape[0]),
        "distinct-schedules": int(np.unique(sig, axis=0).shape[0]),
        "crash-windows": int(win["crash"].sum()),
        "link-windows": int(win["links"].sum()),
        "skew-windows": int(win["skew"].sum()),
        "membership-windows": int(win["membership"].sum()),
    }


def fuzz_summary(fx: FaultConfig) -> Dict[str, Any]:
    """The run-start record's distribution block (static; coverage
    counters ride separately via :func:`fleet_coverage`)."""
    fz = fx.fuzz
    lanes = [name for name, on in (("crash-restart", fz.has_crash),
                                   ("link-degradation", fz.has_links),
                                   ("clock-skew", fz.has_skew),
                                   ("membership", fz.has_membership))
             if on]
    return {"lanes": lanes,
            "windows": [fz.windows_min, fz.windows_max],
            "gap": [fz.gap_min, fz.gap_max],
            "duration": [fz.dur_min, fz.dur_max],
            "snapshot-every": int(fx.snapshot_every),
            "stop-tick": int(fx.stop_tick)}
