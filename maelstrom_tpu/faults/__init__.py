"""Device-resident fault-plan engine: the composable nemesis vocabulary.

The reference nemesis composes partition grudges on an interval
(``nemesis.clj``; ``maelstrom_tpu/nemesis.py`` is its host-side port,
kept as the reference-parity oracle). The TPU runtime's partition
nemesis (``tpu/runtime.py::partition_matrix``) went device-resident but
spoke partitions ONLY. This package closes ROADMAP item 4's second
half: faults beyond partitions, each a lane of one fixed-shape **fault
plan** the tick scan indexes by ``t``:

- **crash-restart** — a crash mask holds victim nodes in reset: the
  carry row is wiped back to the restart state (recovered from a
  per-node device-held snapshot slab — Netherite's crash-restart-with-
  recovery idiom — or cold-booted when the model keeps no durable
  state), delivery to the victim is blocked (its in-flight inbox is
  dropped by the existing recv-side partition drop), and its own sends
  are suppressed for the duration of the phase.
- **link degradation** — the single ``[n, n]`` partition plane
  generalizes to per-directed-edge quality: block (asymmetric
  partitions), extra latency (slow links), and elevated loss, applied
  at enqueue/delivery time per ``(dest, origin)`` edge.
- **clock/timeout skew** — a per-node tick-rate multiplier drives each
  node's LOCAL clock (``local_t = t * rate / 64``); election and
  heartbeat timers run on local time, so Raft's timeout assumptions are
  actually stressed while the network keeps global time.
- **membership** — per-phase node add/remove events (inheriting
  ``members``/``add``/``remove`` dialect): non-members are parked like
  crash victims, joins re-boot through ``Model.join_row`` from the
  snapshot slab, clients re-target the member set, and the target
  bitmask threads into the node step so Raft runs the change through
  JOINT CONSENSUS (``models/raft_core.py``: C_old,new / C_new log
  entries, dual-quorum election and commit) — where real consensus
  implementations historically break, and where the two newest
  planted bugs live (``RaftSingleQuorumReconfig``,
  ``RaftVotesBeforeCatchup``).

The plan is compiled from a declarative :class:`FaultSpec`-shaped dict
(``doc/guide/10-faults.md``) into a hashable :class:`FaultConfig` that
rides ``SimConfig`` as static trace-time configuration; per-tick state
is selected by ``searchsorted`` over the phase boundaries, exactly like
the scripted partition nemesis. An all-healthy plan is bit-identical to
a fault-free run in both carry layouts (``tests/test_faults.py``), and
every lane is proven by a planted-bug model whose anomaly the existing
checker/triage pipeline catches (``models/raft_buggy.py``:
``RaftForgetsSnapshot``, ``RaftFixedTimeout``).

Beyond the one deterministic fleet-shared plan, the **fuzzer**
(:mod:`.fuzz`) samples a fault DISTRIBUTION into a DIFFERENT
randomized schedule per instance, drawn on device from the dedicated
schedule-RNG lane and riding the carry — and :mod:`.shrink`
delta-debugs any failing drawn schedule back into a minimal
deterministic plan (``maelstrom shrink``), keeping the plan dialect
the single repro currency.
"""

from .engine import (FaultConfig, FaultPlanes, NO_PLANES,  # noqa: F401
                     member_bits, phase_summary, retarget_clients,
                     tick_planes, update_snapshots, wipe_crashed,
                     wipe_parked)
from .spec import (FAULT_KINDS, SpecError, compile_fault_plan,  # noqa: F401
                   generate_fault_plan, membership_walk,
                   validate_fault_plan)
from .fuzz import (FuzzConfig, compile_fault_fuzz,  # noqa: F401
                   validate_fault_fuzz)
