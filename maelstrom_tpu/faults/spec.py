"""Declarative fault plans: parse, validate, generate, compile.

A **fault plan** is a JSON-able dict (CLI ``--fault-plan plan.json``,
or inline as the ``fault_plan`` opt in a campaign spec item) naming a
phase timeline and, per phase, which fault lanes are active:

.. code-block:: json

    {"snapshot_every": 1,
     "phases": [
       {"until": 300, "members": [0, 1, 2]},
       {"until": 360, "crash": [0, 1]},
       {"until": 600, "links": [
          {"dst": 1, "src": 0, "block": true},
          {"dst": 0, "src": 1, "delay": 25},
          {"dst": 0, "src": 2, "loss": 0.25}]},
       {"until": 700, "add": [3, 4]},
       {"until": 900, "skew": {"0": 2.0, "2": 0.75}}
     ]}

- ``until`` — phase end tick (strictly increasing; phase 0 starts at
  tick 0). Ticks past the last phase — and past the run's final-heal
  ``stop_tick`` — are healthy.
- ``crash`` — server node ids held crashed for the phase (state wiped
  to the restart row every crashed tick, inbox dropped, sends
  suppressed; recovery semantics live in ``Model.restart_row``).
- ``links`` — directed ``(dst, src)`` edge qualities: ``block`` (bool),
  ``delay`` (extra ticks), ``loss`` (probability 0..1, stored
  per-mille). One edge may combine delay and loss.
- ``skew`` — ``{node: rate}`` clock-rate multipliers (0.125..8.0,
  quantized to 64ths; 1.0 is exactly neutral).
- **membership** — ``members`` (the absolute server member set from
  this phase on), or ``add``/``remove`` (events relative to the
  previous phase's set). Unlike the other lanes, membership INHERITS:
  a phase without a membership key keeps the previous set, the cluster
  starts at all ``n_nodes`` unless phase 0 says otherwise, and the
  trailing heal (past the last phase or ``stop_tick``) restores
  everyone. Non-members are parked like crashed nodes (recv dropped,
  sends suppressed, state held at the snapshot slab's leave-point
  row); a node whose membership turns ON re-boots through
  ``Model.join_row`` (slab recovery + re-provisioned cluster config —
  the Netherite rejoin idiom), and the current member bitmask threads
  into the node step so reconfiguration-aware protocols (Raft joint
  consensus, ``models/raft_core.py``) can run the change through their
  log. A plan may never empty the cluster or name a node past
  ``n_nodes`` — both are refused at compile time BY PHASE.

``generate_fault_plan`` builds the same dict shape from the CLI's
composable ``--nemesis`` kinds (``crash-restart``, ``link-degrade``,
``clock-skew``) on the partition nemesis's interval grid, so fault
lanes compose with each other AND with the existing partition nemesis
in one run. ``compile_fault_plan`` lowers a plan dict to the static
:class:`~.engine.FaultConfig` the runtime traces against.

A plan is ONE deterministic, fleet-shared schedule. Its randomized
sibling is the fault DISTRIBUTION (``--fault-fuzz``,
``spec`` → :mod:`~.fuzz`): the same three lanes, but with rates and
ranges that each instance samples into its OWN schedule on device —
and ``maelstrom shrink`` lowers any failing drawn schedule back INTO
this module's plan dialect (``fuzz.schedule_to_plan`` emits plans that
``validate_fault_plan``/``compile_fault_plan`` accept verbatim), so
the deterministic plan remains the single replay/repro currency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .engine import FaultConfig, NEUTRAL_RATE

# the composable --nemesis vocabulary beyond "partition"
FAULT_KINDS = ("crash-restart", "link-degrade", "clock-skew",
               "membership")

MAX_DELAY_TICKS = 1 << 14      # keeps deadlines far inside the 2^20
                               # delivery-priority horizon
MIN_RATE, MAX_RATE = 0.125, 8.0
MAX_MEMBER_NODES = 30          # membership bitmasks ride int32 lanes


class SpecError(ValueError):
    """A fault plan that cannot be compiled."""


def _err(msg: str) -> "SpecError":
    return SpecError(f"fault plan: {msg}")


def _get(d: Dict[str, Any], name: str, default=None):
    """Dash/underscore-tolerant key lookup (campaign specs are JSON
    written by humans; both spellings appear in the wild)."""
    if name in d:
        return d[name]
    alt = name.replace("_", "-")
    return d.get(alt, default)


def _node_id(v, n_nodes: int, what: str) -> int:
    try:
        i = int(v)
    except (TypeError, ValueError):
        raise _err(f"{what} {v!r} is not a node index")
    if not 0 <= i < n_nodes:
        raise _err(f"{what} {i} out of range [0, {n_nodes})")
    return i


def membership_walk(phases, n_nodes: int):
    """Resolve the membership lane to one ABSOLUTE member set per phase
    (inheritance applied), or ``None`` when no phase carries a
    membership key. Raises :class:`SpecError` — naming the offending
    phase — on a set that would empty the cluster, a node id past the
    ``n_nodes`` capacity, or a cluster too wide for the int32 member
    bitmask."""
    keys = ("members", "add", "remove")
    if not any(_get(ph, k) is not None for ph in phases for k in keys):
        return None
    if n_nodes > MAX_MEMBER_NODES:
        raise _err(f"membership lane supports at most "
                   f"{MAX_MEMBER_NODES} server nodes (int32 member "
                   f"bitmask), got n_nodes={n_nodes}")
    current = set(range(n_nodes))
    out = []
    for i, ph in enumerate(phases):
        members = _get(ph, "members")
        add = _get(ph, "add")
        remove = _get(ph, "remove")
        if members is not None and (add is not None
                                    or remove is not None):
            raise _err(f"phase {i} mixes 'members' with 'add'/'remove'"
                       f" — one absolute set or relative events, not "
                       f"both")
        if members is not None:
            current = {_node_id(v, n_nodes, f"phase {i} member")
                       for v in members}
        else:
            current = set(current)
            for v in (add or []):
                current.add(_node_id(v, n_nodes, f"phase {i} added "
                                                 f"node"))
            for v in (remove or []):
                current.discard(
                    _node_id(v, n_nodes, f"phase {i} removed node"))
        if not current:
            raise _err(f"phase {i} membership would EMPTY the cluster "
                       f"(every phase needs >= 1 member)")
        out.append(tuple(sorted(current)))
    return out


def membership_heal_phases(plan: Dict[str, Any],
                           n_nodes: Optional[int] = None) -> set:
    """Indices of phases whose ``members`` key removes NO node relative
    to the previous phase's resolved set — restores and no-ops. The
    shrinker and the minimality metric (``fuzz.plan_weight``) treat
    these as HEALS, exactly like rejoin ``add`` events: dropping one
    would EXTEND the membership outage (inheritance keeps the reduced
    set), which is the opposite of shrinking. When ``n_nodes`` is
    unknown the universe is inferred as the widest node id the plan
    itself names — a ``members`` set that silently excludes un-named
    trailing nodes then classifies as heal, which errs CONSERVATIVE
    (it is merely never offered as a drop candidate)."""
    phases = list((plan or {}).get("phases") or ())
    keys = ("members", "add", "remove")
    if not any(_get(ph, k) is not None for ph in phases for k in keys):
        return set()
    if n_nodes is None:
        named = [int(v) for ph in phases for k in keys
                 for v in (_get(ph, k) or [])]
        n_nodes = (max(named) + 1) if named else 1
    walk = membership_walk(phases, n_nodes)
    heals = set()
    prev = set(range(n_nodes))
    for i, cur in enumerate(walk):
        cur = set(cur)
        if _get(phases[i], "members") is not None and prev <= cur:
            heals.add(i)
        prev = cur
    return heals


def validate_fault_plan(plan: Dict[str, Any], n_nodes: int) -> None:
    """Raise :class:`SpecError` on a malformed plan (compile calls this
    first; the CLI calls it directly for early, friendly errors)."""
    if not isinstance(plan, dict):
        raise _err(f"top level must be a dict, got {type(plan).__name__}")
    phases = _get(plan, "phases")
    if not isinstance(phases, list) or not phases:
        raise _err("needs a non-empty 'phases' list")
    every_raw = _get(plan, "snapshot_every", 1)
    every = 1 if every_raw is None else int(every_raw)
    if every < 1:
        raise _err(f"snapshot_every must be >= 1, got {every}")
    prev = 0
    for i, ph in enumerate(phases):
        if not isinstance(ph, dict):
            raise _err(f"phase {i} is not a dict: {ph!r}")
        until = _get(ph, "until")
        if not isinstance(until, (int, float)) or int(until) <= prev:
            raise _err(f"phase {i} 'until' must be an int > {prev}, "
                       f"got {until!r}")
        prev = int(until)
        for v in _get(ph, "crash", []) or []:
            _node_id(v, n_nodes, f"phase {i} crash victim")
        for e in _get(ph, "links", []) or []:
            if not isinstance(e, dict):
                raise _err(f"phase {i} link entry is not a dict: {e!r}")
            _node_id(_get(e, "dst"), n_nodes, f"phase {i} link dst")
            _node_id(_get(e, "src"), n_nodes, f"phase {i} link src")
            d = _get(e, "delay", 0) or 0
            if not 0 <= int(d) <= MAX_DELAY_TICKS:
                raise _err(f"phase {i} link delay {d} out of "
                           f"[0, {MAX_DELAY_TICKS}]")
            p = float(_get(e, "loss", 0.0) or 0.0)
            if not 0.0 <= p <= 1.0:
                raise _err(f"phase {i} link loss {p} out of [0, 1]")
        skew = _get(ph, "skew", {}) or {}
        if not isinstance(skew, dict):
            raise _err(f"phase {i} skew must be a dict, got {skew!r}")
        for node, rate in skew.items():
            _node_id(node, n_nodes, f"phase {i} skew node")
            r = float(rate)
            if not MIN_RATE <= r <= MAX_RATE:
                raise _err(f"phase {i} skew rate {r} out of "
                           f"[{MIN_RATE}, {MAX_RATE}]")
    # membership: the walk itself validates (empty cluster / capacity
    # errors name the offending phase)
    membership_walk(phases, n_nodes)


def compile_fault_plan(plan: Optional[Dict[str, Any]], n_nodes: int,
                       stop_tick: int,
                       snapshot_every: Optional[int] = None
                       ) -> FaultConfig:
    """Lower a plan dict to the static :class:`FaultConfig`.
    ``plan=None`` compiles the disabled config (the pre-fault tick).
    ``snapshot_every`` (the ``fault_snapshot_every`` opt) overrides the
    plan's own setting when given."""
    if not plan:
        return FaultConfig()
    validate_fault_plan(plan, n_nodes)
    plan_every = _get(plan, "snapshot_every", 1)
    every = int(snapshot_every if snapshot_every is not None
                else (1 if plan_every is None else plan_every))
    untils: List[int] = []
    crash: List[tuple] = []
    links: List[tuple] = []
    skew: List[tuple] = []
    members = membership_walk(_get(plan, "phases"), n_nodes)
    for ph in _get(plan, "phases"):
        untils.append(int(_get(ph, "until")))
        crash.append(tuple(sorted(
            int(v) for v in (_get(ph, "crash", []) or []))))
        links.append(tuple(
            (int(_get(e, "dst")), int(_get(e, "src")),
             1 if _get(e, "block", False) else 0,
             int(_get(e, "delay", 0) or 0),
             int(round(float(_get(e, "loss", 0.0) or 0.0) * 1000)))
            for e in (_get(ph, "links", []) or [])))
        skew.append(tuple(sorted(
            (int(node), max(1, int(round(float(rate) * NEUTRAL_RATE))))
            for node, rate in (_get(ph, "skew", {}) or {}).items())))
    return FaultConfig(enabled=True, stop_tick=int(stop_tick),
                       snapshot_every=every, untils=tuple(untils),
                       crash=tuple(crash), links=tuple(links),
                       skew=tuple(skew),
                       members=(None if members is None
                                else tuple(members)),
                       n_nodes=int(n_nodes))


# --- the composable --nemesis generators -----------------------------------


def generate_fault_plan(kinds: Sequence[str], n_nodes: int,
                        n_ticks: int, interval: int,
                        stop_tick: int) -> Dict[str, Any]:
    """Build a plan dict from the CLI's fault ``--nemesis`` kinds on
    the partition nemesis's interval grid (alternating heal/fault
    phases, deterministic rotation — the plan is shared by every
    instance, so the schedule itself carries no RNG; per-instance
    variation comes from latency/election randomness):

    - ``crash-restart`` — every second phase holds one victim (rotating
      ``phase % n``) crashed: a minority at a time, so a model with
      durable recovery must stay correct.
    - ``link-degrade`` — every second phase degrades a rotating triple
      of directed edges: one blocked (asymmetric partition), one slow
      (``2 * interval // 5`` extra ticks), one lossy (25%).
    - ``clock-skew`` — one whole-run phase spreading node clock rates
      over 0.75x..1.75x (node ``i`` gets ``(48 + 16 * (i % 5)) / 64``).
    - ``membership`` — every second phase REMOVES one rotating node
      (always a minority, so a reconfiguration-aware model must stay
      correct AND live), and the following heal phase explicitly adds
      it back — a rolling remove/rejoin churn that drives the Raft
      joint-consensus machinery through a full ``C_old,new`` ->
      ``C_new`` round per window.
    """
    kinds = [k for k in kinds if k in FAULT_KINDS]
    if not kinds:
        return {}
    horizon = min(int(n_ticks), int(stop_tick))
    # clamp the grid so even a short run gets at least one heal/fault
    # alternation (phase 1 — the first FAULT phase — needs
    # 2*interval <= horizon): asking for faults and silently getting a
    # fault-free plan would be a lie. The partition nemesis's default
    # 10s interval vs a 2-3s smoke run is exactly that trap.
    interval = max(1, min(int(interval), horizon // 4 or 1))
    phases: List[Dict[str, Any]] = []
    if kinds == ["clock-skew"]:
        # skew alone needs no interval grid: one whole-run phase
        phases.append({"until": max(1, horizon),
                       "skew": _skew_spread(n_nodes)})
        return {"phases": phases}
    p = 0
    t = interval
    while t <= horizon:
        ph: Dict[str, Any] = {"until": t}
        active = p % 2 == 1          # odd phases fault, even heal —
        #                              the partition nemesis's cadence
        if active and "crash-restart" in kinds and n_nodes > 1:
            ph["crash"] = [(p // 2) % n_nodes]
        if "membership" in kinds and n_nodes > 1:
            if active:
                victim = (p // 2) % n_nodes
                ph["members"] = [i for i in range(n_nodes)
                                 if i != victim]
            else:
                # explicit restore: membership INHERITS, so a heal
                # phase must say "everyone" to end the removal window
                ph["members"] = list(range(n_nodes))
        if active and "link-degrade" in kinds and n_nodes > 1:
            a = (p // 2) % n_nodes
            b = (a + 1) % n_nodes
            c = (a + 2) % n_nodes if n_nodes > 2 else a
            ph["links"] = [
                {"dst": b, "src": a, "block": True},
                {"dst": a, "src": b, "delay": max(2, 2 * interval // 5)},
                {"dst": c, "src": b, "loss": 0.25},
            ]
        if "clock-skew" in kinds:
            ph["skew"] = _skew_spread(n_nodes)
        phases.append(ph)
        p += 1
        t += interval
    if not phases:
        phases.append({"until": max(1, horizon)})
    return {"phases": phases}


def _skew_spread(n_nodes: int) -> Dict[str, float]:
    return {str(i): (48 + 16 * (i % 5)) / NEUTRAL_RATE
            for i in range(n_nodes)}
