"""Automatic failing-schedule shrinking: fuzz hit -> minimal nemesis.

A randomized fuzz sweep (``faults/fuzz.py``) turns one run into 100k
distinct fault scenarios — and a hit into a needle nobody wants to
read: the flagged instance's schedule may crash five nodes across three
windows when ONE crash in ONE window was the trigger. ``maelstrom
shrink <run-dir>`` closes that loop per flagged instance:

1. **Reconstruct** the instance's concrete schedule from its seed
   (``fuzz.reconstruct_plan`` — schedules are bit-stable pure functions
   of ``(seed, instance_id)``) as a deterministic ``--fault-plan``
   dict.
2. **Verify** the reconstruction: replay the single instance through
   the pipelined executor (``tpu/pipeline.run_sim_pipelined`` with
   ``instance_ids=[id]`` — the instance-stable RNG makes node/client/
   restart draws identical to the fleet run) under that plan and
   require the on-device invariants to trip again. A non-failing
   reconstruction is reported loudly — it would mean the seed -> plan
   path is not bit-exact.
3. **Delta-debug** the plan to a local minimum: greedy passes that drop
   whole fault phases, drop individual victims (crash nodes, link
   edges, skewed nodes), and halve phase durations — keeping any
   reduction whose replay STILL fails — repeated to fixpoint under an
   attempt budget.
4. **Write** ``triage/instance-<id>/shrunk-plan.json`` (a pure plan
   file, replayable via ``--fault-plan``) plus ``shrink.json`` with the
   original/shrunk weights and the verification record.

Each candidate replay recompiles the tick (fault planes are baked
constants), so the replay config should be small — the shrink run
reuses the original run's opts with ``n_instances=1`` and recording
off; wall-clock is bounded by ``max_attempts``.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from . import fuzz as _fuzz

SHRINK_FILE = "shrink.json"
SHRUNK_PLAN_FILE = "shrunk-plan.json"


class ShrinkError(ValueError):
    """A run/instance that cannot be shrunk (not a fuzz run, or the
    reconstruction does not reproduce the failure)."""


def _phase_content(ph: Dict[str, Any]) -> int:
    return (len(ph.get("crash") or []) + len(ph.get("links") or [])
            + len(ph.get("skew") or {}))


def _normalize(plan: Dict[str, Any]) -> Dict[str, Any]:
    """Merge adjacent healthy phases and drop a healthy tail — pure
    cosmetics for the written artifact (searchsorted semantics are
    unchanged by either)."""
    phases = [dict(p) for p in plan.get("phases", ())]
    out: List[Dict[str, Any]] = []
    for ph in phases:
        if out and _phase_content(out[-1]) == 0 \
                and _phase_content(ph) == 0:
            out[-1]["until"] = ph["until"]
        else:
            out.append(ph)
    while out and _phase_content(out[-1]) == 0:
        out.pop()
    if not out:
        return {}
    return {**{k: v for k, v in plan.items() if k != "phases"},
            "phases": out}


def _candidates(plan: Dict[str, Any]):
    """Yield reduced candidate plans, most aggressive first: whole
    fault phases dropped, then single victims, then halved durations.
    Each candidate is an independent copy of ``plan``."""
    phases = plan.get("phases", ())
    fault_idx = [i for i, ph in enumerate(phases)
                 if _phase_content(ph) > 0]
    for i in fault_idx:
        cand = copy.deepcopy(plan)
        cand["phases"][i] = {"until": phases[i]["until"]}
        yield f"drop-phase-{i}", cand
    for i in fault_idx:
        ph = phases[i]
        for v in ph.get("crash") or []:
            cand = copy.deepcopy(plan)
            cand["phases"][i]["crash"] = [
                x for x in ph["crash"] if x != v]
            if not cand["phases"][i]["crash"]:
                del cand["phases"][i]["crash"]
            yield f"phase-{i}-drop-crash-{v}", cand
        for j in range(len(ph.get("links") or [])):
            cand = copy.deepcopy(plan)
            del cand["phases"][i]["links"][j]
            if not cand["phases"][i]["links"]:
                del cand["phases"][i]["links"]
            yield f"phase-{i}-drop-edge-{j}", cand
        for node in list((ph.get("skew") or {})):
            cand = copy.deepcopy(plan)
            del cand["phases"][i]["skew"][node]
            if not cand["phases"][i]["skew"]:
                del cand["phases"][i]["skew"]
            yield f"phase-{i}-drop-skew-{node}", cand
    for i in fault_idx:
        prev = int(phases[i - 1]["until"]) if i else 0
        width = int(phases[i]["until"]) - prev
        if width >= 2:
            cand = copy.deepcopy(plan)
            cand["phases"][i]["until"] = prev + width // 2
            yield f"phase-{i}-halve-duration", cand


def make_replayer(model, opts: Dict[str, Any], instance_id: int,
                  params=None):
    """Build ``replay(plan) -> bool`` (True = the single-instance
    deterministic replay trips the on-device invariants). The replay
    runs through the pipelined executor with the ORIGINAL run's opts —
    same seed, same instance id, recording/journal/telemetry stripped
    to the minimum the invariant lanes need."""
    from ..tpu.harness import make_sim_config
    from ..tpu.pipeline import run_sim_pipelined

    base = {**opts, "fault_fuzz": None, "n_instances": 1,
            "record_instances": 0, "journal_instances": 0,
            "funnel": False, "heartbeat": False, "fail_fast": False,
            "checkpoint_every": 0}
    seed = int(base.get("seed") or 0)
    chunk = int(base.get("chunk_ticks") or 100)
    ids = np.asarray([int(instance_id)], np.int32)

    def replay(plan: Optional[Dict[str, Any]]) -> bool:
        sim = make_sim_config(model, {**base,
                                      "fault_plan": plan or None})
        p = params if params is not None \
            else model.make_params(sim.net.n_nodes)
        res = run_sim_pipelined(model, sim, seed, p,
                                instance_ids=ids, chunk=chunk)
        return int(np.asarray(res.carry.violations)[0]) > 0

    return replay


def shrink_plan(plan: Dict[str, Any], replay,
                max_attempts: int = 24) -> Dict[str, Any]:
    """Greedy delta-debugging to a local minimum: try each candidate
    reduction, keep any that still fails, restart the pass on the
    reduced plan; stop at fixpoint or when ``max_attempts`` replays
    are spent. Returns ``{plan, attempts, kept}``."""
    current = _normalize(plan)
    attempts = 0
    kept: List[str] = []
    progress = True
    while progress and attempts < max_attempts:
        progress = False
        for label, cand in _candidates(current):
            if attempts >= max_attempts:
                break
            cand = _normalize(cand)
            attempts += 1
            if replay(cand if cand else None):
                current = cand
                kept.append(label)
                progress = True
                break       # restart candidate enumeration on the
                #             reduced plan (greedy-first-improvement)
    return {"plan": current, "attempts": attempts, "kept": kept}


def shrink_instance(model, opts: Dict[str, Any], instance_id: int,
                    params=None,
                    max_attempts: int = 24) -> Dict[str, Any]:
    """The full loop for one flagged instance: reconstruct -> verify ->
    delta-debug -> verify the minimum. Raises :class:`ShrinkError`
    when the run is not a fuzz run or the reconstructed plan does not
    reproduce the failure."""
    from ..tpu.harness import make_sim_config

    if not opts.get("fault_fuzz"):
        raise ShrinkError(
            "not a fault-fuzz run (no fault_fuzz in the repro opts) — "
            "deterministic-plan hits are already minimal-by-"
            "construction inputs for hand-editing")
    sim = make_sim_config(model, dict(opts))
    seed = int(opts.get("seed") or 0)
    plan0 = _fuzz.reconstruct_plan(sim.faults, sim.net.n_nodes, seed,
                                   instance_id)
    replay = make_replayer(model, opts, instance_id, params=params)
    if not plan0:
        raise ShrinkError(
            f"instance {instance_id}: reconstructed schedule is "
            f"all-healthy — a flagged instance with no faults means "
            f"the failure is fault-independent (triage it instead)")
    if not replay(plan0):
        raise ShrinkError(
            f"instance {instance_id}: the reconstructed deterministic "
            f"plan does NOT reproduce the violation — the seed -> "
            f"schedule replay was not bit-exact (this is a bug, "
            f"report it)")
    p0, v0 = _fuzz.plan_weight(plan0)
    res = shrink_plan(plan0, replay, max_attempts=max_attempts)
    shrunk = res["plan"]
    # the reduced plan gets one final CONFIRMING replay (an unreduced
    # plan is plan0, whose replay above already failed) — keeping the
    # gate's `verified` assertion load-bearing rather than a constant
    verified = (True if not res["kept"]
                else replay(shrunk if shrunk else None))
    p1, v1 = _fuzz.plan_weight(shrunk)
    return {
        "instance": int(instance_id),
        "seed": seed,
        "original-plan": plan0,
        "original-phases": p0, "original-victims": v0,
        "shrunk-plan": shrunk,
        "shrunk-phases": p1, "shrunk-victims": v1,
        "attempts": res["attempts"],
        "kept": res["kept"],
        "verified": verified,
        "reduced": (p1, v1) < (p0, v0),
    }


def shrink_run(run_dir: str, ids: Optional[List[int]] = None,
               max_instances: int = 4,
               max_attempts: int = 24) -> Dict[str, Any]:
    """``maelstrom shrink <run-dir>``: shrink each flagged instance's
    schedule and write its minimal plan under
    ``<run-dir>/triage/instance-<id>/``. Returns the summary (also
    written to ``triage/shrink-summary.json``)."""
    from ..checkers.triage import (TRIAGE_DIR, TriageError,
                                   load_run_info, resolve_model)

    try:
        info = load_run_info(run_dir)
    except TriageError as e:
        raise ShrinkError(str(e))
    opts = dict(info["opts"])
    opts["seed"] = info["seed"]
    if not opts.get("fault_fuzz"):
        raise ShrinkError(
            f"{info['run-dir']} is not a fault-fuzz run (its heartbeat "
            f"repro opts carry no fault_fuzz distribution); shrink "
            f"operates on randomized-schedule hits")
    targets = [int(i) for i in (ids if ids else info["flagged"])]
    dropped = max(0, len(targets) - int(max_instances))
    targets = targets[:int(max_instances)]
    out_dir = os.path.join(info["run-dir"], TRIAGE_DIR)
    summary: Dict[str, Any] = {
        "run-dir": info["run-dir"], "workload": info["workload"],
        "flagged": info["flagged"], "shrunk": [], "errors": [],
        "dropped": dropped, "out-dir": out_dir,
    }
    if not targets:
        summary["note"] = ("no flagged instances (run is clean or the "
                           "heartbeat saw no violation scan hits)")
        return summary
    model = resolve_model(info)
    params = model.make_params(int(opts.get("node_count", 1)))
    for gid in targets:
        inst_dir = os.path.join(out_dir, f"instance-{gid}")
        os.makedirs(inst_dir, exist_ok=True)
        try:
            rec = shrink_instance(model, opts, gid, params=params,
                                  max_attempts=max_attempts)
        except ShrinkError as e:
            summary["errors"].append({"instance": gid,
                                      "error": str(e)})
            continue
        with open(os.path.join(inst_dir, SHRUNK_PLAN_FILE), "w") as f:
            json.dump(rec["shrunk-plan"], f, indent=2)
        rec["shrunk-plan-file"] = os.path.join(inst_dir,
                                               SHRUNK_PLAN_FILE)
        with open(os.path.join(inst_dir, SHRINK_FILE), "w") as f:
            json.dump(rec, f, indent=2)
        summary["shrunk"].append(rec)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "shrink-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=repr)
    return summary


def render_shrink_report(summary: Dict[str, Any]) -> str:
    lines = [f"shrink: {summary['workload']} run at "
             f"{summary['run-dir']}"]
    if summary.get("note"):
        lines.append(summary["note"])
    for rec in summary.get("shrunk", ()):
        lines.append(
            f"  instance {rec['instance']}: "
            f"{rec['original-phases']} phase(s)/"
            f"{rec['original-victims']} victim(s) -> "
            f"{rec['shrunk-phases']}/{rec['shrunk-victims']} in "
            f"{rec['attempts']} replay(s); verified "
            f"{rec['verified']} -> {rec.get('shrunk-plan-file', '?')}")
    for err in summary.get("errors", ()):
        lines.append(f"  instance {err['instance']}: ERROR "
                     f"{err['error']}")
    if summary.get("dropped"):
        lines.append(f"  (+{summary['dropped']} flagged instance(s) "
                     f"beyond --max-instances)")
    return "\n".join(lines)
