"""Automatic failing-schedule shrinking: fuzz hit -> minimal nemesis.

A randomized fuzz sweep (``faults/fuzz.py``) turns one run into 100k
distinct fault scenarios — and a hit into a needle nobody wants to
read: the flagged instance's schedule may crash five nodes across three
windows when ONE crash in ONE window was the trigger. ``maelstrom
shrink <run-dir>`` closes that loop per flagged instance:

1. **Reconstruct** the instance's concrete schedule from its seed
   (``fuzz.reconstruct_plan`` — schedules are bit-stable pure functions
   of ``(seed, instance_id)``) as a deterministic ``--fault-plan``
   dict. A deterministic ``--fault-plan`` run needs no reconstruction:
   its plan IS the starting point, and the same minimizer applies
   (hand-built scenarios — the membership reconfiguration plans
   especially — are usually over-specified).
2. **Verify** the reconstruction: replay the single instance through
   the pipelined executor (``tpu/pipeline.run_sim_pipelined`` with
   ``instance_ids=[id]`` — the instance-stable RNG makes node/client/
   restart draws identical to the fleet run) under that plan and
   require the on-device invariants to trip again. A non-failing
   reconstruction is reported loudly — it would mean the seed -> plan
   path is not bit-exact.
3. **Delta-debug** the plan to a local minimum: first ddmin-style
   COMPLEMENT-HALVING rounds over the fault phases (drop half — then
   quarters, eighths, ... — of the fault-carrying phases in ONE
   replay; a kept drop removes many phases for one verification,
   which is where multi-phase schedules beat the old greedy-only
   pass), then the greedy passes that drop whole fault phases, drop
   individual victims (crash nodes, link edges, skewed nodes,
   membership removals), and halve phase durations — keeping any
   reduction whose replay STILL fails — repeated to fixpoint under an
   attempt budget.
4. **Write** ``triage/instance-<id>/shrunk-plan.json`` (a pure plan
   file, replayable via ``--fault-plan``) plus ``shrink.json`` with the
   original/shrunk weights and the verification record.

Each candidate replay recompiles the tick (fault planes are baked
constants), so the replay config should be small — the shrink run
reuses the original run's opts with ``n_instances=1`` and recording
off; wall-clock is bounded by ``max_attempts``.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from . import fuzz as _fuzz
from .spec import membership_heal_phases

SHRINK_FILE = "shrink.json"
SHRUNK_PLAN_FILE = "shrunk-plan.json"


class ShrinkError(ValueError):
    """A run/instance that cannot be shrunk (not a fuzz run, or the
    reconstruction does not reproduce the failure)."""


def _phase_content(ph: Dict[str, Any]) -> int:
    """State-changing keys of a phase — what _normalize must never
    merge away. Membership 'add' (rejoin) events and heal 'members'
    restores count here (they change the timeline) but NOT as fault
    content (they heal, the shrinker never targets them)."""
    return (_fault_content(ph) + len(ph.get("add") or []))


def _fault_content(ph: Dict[str, Any], members_fault: bool = True) -> int:
    """Shrink-targetable content of a phase. A ``members`` key is fault
    content only when it actually REMOVES a node — callers pass
    ``members_fault=False`` for the heal/restore phases identified by
    :func:`spec.membership_heal_phases` (dropping a restore would
    EXTEND the outage via inheritance, the opposite of shrinking)."""
    return (len(ph.get("crash") or []) + len(ph.get("links") or [])
            + len(ph.get("skew") or {})
            + len(ph.get("remove") or [])
            + (1 if members_fault
               and ph.get("members") is not None else 0))


def _normalize(plan: Dict[str, Any]) -> Dict[str, Any]:
    """Merge adjacent healthy phases and drop a healthy tail — pure
    cosmetics for the written artifact (searchsorted semantics are
    unchanged by either)."""
    phases = [dict(p) for p in plan.get("phases", ())]
    out: List[Dict[str, Any]] = []
    for ph in phases:
        if out and _phase_content(out[-1]) == 0 \
                and _phase_content(ph) == 0:
            out[-1]["until"] = ph["until"]
        else:
            out.append(ph)
    while out and _phase_content(out[-1]) == 0:
        out.pop()
    if not out:
        return {}
    return {**{k: v for k, v in plan.items() if k != "phases"},
            "phases": out}


def _strip_faults(ph: Dict[str, Any],
                  keep_members: bool = False) -> Dict[str, Any]:
    """A phase with its fault content removed: the timeline boundary
    stays, and so does any membership 'add' (rejoin) event or — with
    ``keep_members`` — a heal/restore ``members`` set; dropping a heal
    would ENLARGE the fault, not shrink it."""
    kept = {"until": ph["until"]}
    if ph.get("add"):
        kept["add"] = ph["add"]
    if keep_members and ph.get("members") is not None:
        kept["members"] = ph["members"]
    return kept


def _candidates(plan: Dict[str, Any], n_nodes=None):
    """Yield reduced candidate plans, most aggressive first: whole
    fault phases dropped, then single victims, then halved durations.
    Each candidate is an independent copy of ``plan``."""
    phases = plan.get("phases", ())
    # recomputed on every (normalized) reduction — phase indices shift
    heals = membership_heal_phases(plan, n_nodes)
    fault_idx = [i for i, ph in enumerate(phases)
                 if _fault_content(ph, members_fault=i not in heals) > 0]
    for i in fault_idx:
        cand = copy.deepcopy(plan)
        cand["phases"][i] = _strip_faults(phases[i],
                                          keep_members=i in heals)
        yield f"drop-phase-{i}", cand
    for i in fault_idx:
        ph = phases[i]
        for v in ph.get("crash") or []:
            cand = copy.deepcopy(plan)
            cand["phases"][i]["crash"] = [
                x for x in ph["crash"] if x != v]
            if not cand["phases"][i]["crash"]:
                del cand["phases"][i]["crash"]
            yield f"phase-{i}-drop-crash-{v}", cand
        for v in ph.get("remove") or []:
            # keep a node in the cluster (its later rejoin 'add'
            # becomes a harmless no-op — membership_walk adds are
            # idempotent)
            cand = copy.deepcopy(plan)
            cand["phases"][i]["remove"] = [
                x for x in ph["remove"] if x != v]
            if not cand["phases"][i]["remove"]:
                del cand["phases"][i]["remove"]
            yield f"phase-{i}-drop-remove-{v}", cand
        if ph.get("members") is not None and i not in heals:
            cand = copy.deepcopy(plan)
            del cand["phases"][i]["members"]
            yield f"phase-{i}-drop-members", cand
        for j in range(len(ph.get("links") or [])):
            cand = copy.deepcopy(plan)
            del cand["phases"][i]["links"][j]
            if not cand["phases"][i]["links"]:
                del cand["phases"][i]["links"]
            yield f"phase-{i}-drop-edge-{j}", cand
        for node in list((ph.get("skew") or {})):
            cand = copy.deepcopy(plan)
            del cand["phases"][i]["skew"][node]
            if not cand["phases"][i]["skew"]:
                del cand["phases"][i]["skew"]
            yield f"phase-{i}-drop-skew-{node}", cand
    for i in fault_idx:
        prev = int(phases[i - 1]["until"]) if i else 0
        width = int(phases[i]["until"]) - prev
        if width >= 2:
            cand = copy.deepcopy(plan)
            cand["phases"][i]["until"] = prev + width // 2
            yield f"phase-{i}-halve-duration", cand


def make_replayer(model, opts: Dict[str, Any], instance_id: int,
                  params=None):
    """Build ``replay(plan) -> bool`` (True = the single-instance
    deterministic replay trips the on-device invariants). The replay
    runs through the pipelined executor with the ORIGINAL run's opts —
    same seed, same instance id, recording/journal/telemetry stripped
    to the minimum the invariant lanes need."""
    from ..tpu.harness import make_sim_config
    from ..tpu.pipeline import run_sim_pipelined

    base = {**opts, "fault_fuzz": None, "n_instances": 1,
            "record_instances": 0, "journal_instances": 0,
            "funnel": False, "heartbeat": False, "fail_fast": False,
            "checkpoint_every": 0}
    seed = int(base.get("seed") or 0)
    chunk = int(base.get("chunk_ticks") or 100)
    ids = np.asarray([int(instance_id)], np.int32)

    def replay(plan: Optional[Dict[str, Any]]) -> bool:
        sim = make_sim_config(model, {**base,
                                      "fault_plan": plan or None})
        p = params if params is not None \
            else model.make_params(sim.net.n_nodes)
        res = run_sim_pipelined(model, sim, seed, p,
                                instance_ids=ids, chunk=chunk)
        return int(np.asarray(res.carry.violations)[0]) > 0

    return replay


def _drop_phase_set(plan: Dict[str, Any], idxs,
                    heals=frozenset()) -> Dict[str, Any]:
    cand = copy.deepcopy(plan)
    for i in idxs:
        cand["phases"][i] = _strip_faults(cand["phases"][i],
                                          keep_members=i in heals)
    return cand


def _ddmin_phases(plan: Dict[str, Any], replay, attempts: int,
                  max_attempts: int, kept: List[str], n_nodes=None):
    """ddmin-style complement reduction over the FAULT PHASES: drop
    whole subsets (halves, then quarters, ...) of the fault-carrying
    phases in one verified replay each. One kept drop eliminates
    ``len(phases)/k`` phases for ONE replay — on multi-phase schedules
    this converges in O(log) replays where the greedy single-phase
    pass pays one replay per phase. Every kept reduction is
    replay-verified, exactly like the greedy pass. Returns
    ``(plan, attempts)``."""
    current = plan
    k = 2
    while attempts < max_attempts:
        heals = membership_heal_phases(current, n_nodes)
        fault_idx = [i for i, ph in enumerate(current.get("phases", ()))
                     if _fault_content(ph, members_fault=i not in heals)
                     > 0]
        if len(fault_idx) < 2:
            break
        k = min(k, len(fault_idx))
        chunk = -(-len(fault_idx) // k)
        subsets = [fault_idx[j:j + chunk]
                   for j in range(0, len(fault_idx), chunk)]
        reduced = False
        for sub in subsets:
            if attempts >= max_attempts:
                break
            cand = _normalize(_drop_phase_set(current, sub, heals))
            attempts += 1
            if replay(cand if cand else None):
                current = cand
                kept.append("ddmin-drop-phases-" +
                            ",".join(str(i) for i in sub))
                k = max(2, k - 1)
                reduced = True
                break
        if not reduced:
            if k >= len(fault_idx):
                break          # singleton granularity: greedy takes over
            k = min(len(fault_idx), 2 * k)
    return current, attempts


def shrink_plan(plan: Dict[str, Any], replay,
                max_attempts: int = 24,
                ddmin: bool = True, n_nodes=None) -> Dict[str, Any]:
    """Delta-debug to a local minimum: ddmin complement-halving rounds
    over the fault phases first (``ddmin=False`` skips them — the
    pre-ddmin greedy-only behavior, kept for A/B), then the greedy
    candidate pass — try each reduction, keep any that still fails,
    restart on the reduced plan; stop at fixpoint or when
    ``max_attempts`` replays are spent. Returns
    ``{plan, attempts, kept}``."""
    current = _normalize(plan)
    attempts = 0
    kept: List[str] = []
    if ddmin:
        current, attempts = _ddmin_phases(current, replay, attempts,
                                          max_attempts, kept,
                                          n_nodes=n_nodes)
    progress = True
    while progress and attempts < max_attempts:
        progress = False
        for label, cand in _candidates(current, n_nodes=n_nodes):
            if attempts >= max_attempts:
                break
            cand = _normalize(cand)
            attempts += 1
            if replay(cand if cand else None):
                current = cand
                kept.append(label)
                progress = True
                break       # restart candidate enumeration on the
                #             reduced plan (greedy-first-improvement)
    return {"plan": current, "attempts": attempts, "kept": kept}


def shrink_instance(model, opts: Dict[str, Any], instance_id: int,
                    params=None,
                    max_attempts: int = 24) -> Dict[str, Any]:
    """The full loop for one flagged instance: reconstruct -> verify ->
    delta-debug -> verify the minimum. Fuzz runs reconstruct the
    instance's drawn schedule from the seed; deterministic
    ``--fault-plan`` runs delta-debug the PLAN ITSELF (a hand-built
    reconfiguration scenario is usually over-specified — extra link
    edges, over-long phases — and the minimizer applies verbatim).
    Raises :class:`ShrinkError` when the run carries no fault source
    or the starting plan does not reproduce the failure."""
    from ..tpu.harness import make_sim_config

    if not opts.get("fault_fuzz") and not opts.get("fault_plan"):
        raise ShrinkError(
            "not a fault run (neither fault_fuzz nor fault_plan in "
            "the repro opts) — nothing to shrink")
    sim = make_sim_config(model, dict(opts))
    seed = int(opts.get("seed") or 0)
    if opts.get("fault_fuzz"):
        plan0 = _fuzz.reconstruct_plan(sim.faults, sim.net.n_nodes,
                                       seed, instance_id)
    else:
        plan0 = dict(opts["fault_plan"])
    replay = make_replayer(model, opts, instance_id, params=params)
    if not plan0:
        raise ShrinkError(
            f"instance {instance_id}: reconstructed schedule is "
            f"all-healthy — a flagged instance with no faults means "
            f"the failure is fault-independent (triage it instead)")
    if not replay(plan0):
        raise ShrinkError(
            f"instance {instance_id}: the starting deterministic plan "
            f"does NOT reproduce the violation — for a fuzz run this "
            f"means the seed -> schedule replay was not bit-exact "
            f"(a bug, report it); for a plan run the flagged instance "
            f"is noise-dependent beyond the plan")
    n_nodes = int(sim.net.n_nodes)
    p0, v0 = _fuzz.plan_weight(plan0, n_nodes)
    res = shrink_plan(plan0, replay, max_attempts=max_attempts,
                      n_nodes=n_nodes)
    shrunk = res["plan"]
    # the reduced plan gets one final CONFIRMING replay (an unreduced
    # plan is plan0, whose replay above already failed) — keeping the
    # gate's `verified` assertion load-bearing rather than a constant
    verified = (True if not res["kept"]
                else replay(shrunk if shrunk else None))
    p1, v1 = _fuzz.plan_weight(shrunk, n_nodes)
    return {
        "instance": int(instance_id),
        "seed": seed,
        "original-plan": plan0,
        "original-phases": p0, "original-victims": v0,
        "shrunk-plan": shrunk,
        "shrunk-phases": p1, "shrunk-victims": v1,
        "attempts": res["attempts"],
        "kept": res["kept"],
        "verified": verified,
        "reduced": (p1, v1) < (p0, v0),
    }


def shrink_run(run_dir: str, ids: Optional[List[int]] = None,
               max_instances: int = 4,
               max_attempts: int = 24) -> Dict[str, Any]:
    """``maelstrom shrink <run-dir>``: shrink each flagged instance's
    schedule and write its minimal plan under
    ``<run-dir>/triage/instance-<id>/``. Returns the summary (also
    written to ``triage/shrink-summary.json``)."""
    from ..checkers.triage import (TRIAGE_DIR, TriageError,
                                   load_run_info, resolve_model)

    try:
        info = load_run_info(run_dir)
    except TriageError as e:
        raise ShrinkError(str(e))
    opts = dict(info["opts"])
    opts["seed"] = info["seed"]
    if not opts.get("fault_fuzz") and not opts.get("fault_plan"):
        raise ShrinkError(
            f"{info['run-dir']} is not a fault run (its heartbeat "
            f"repro opts carry neither a fault_fuzz distribution nor "
            f"a fault_plan); shrink minimizes randomized-schedule "
            f"hits and over-specified deterministic plans")
    targets = [int(i) for i in (ids if ids else info["flagged"])]
    dropped = max(0, len(targets) - int(max_instances))
    targets = targets[:int(max_instances)]
    out_dir = os.path.join(info["run-dir"], TRIAGE_DIR)
    summary: Dict[str, Any] = {
        "run-dir": info["run-dir"], "workload": info["workload"],
        "flagged": info["flagged"], "shrunk": [], "errors": [],
        "dropped": dropped, "out-dir": out_dir,
    }
    if not targets:
        summary["note"] = ("no flagged instances (run is clean or the "
                           "heartbeat saw no violation scan hits)")
        return summary
    model = resolve_model(info)
    params = model.make_params(int(opts.get("node_count", 1)))
    for gid in targets:
        inst_dir = os.path.join(out_dir, f"instance-{gid}")
        os.makedirs(inst_dir, exist_ok=True)
        try:
            rec = shrink_instance(model, opts, gid, params=params,
                                  max_attempts=max_attempts)
        except ShrinkError as e:
            summary["errors"].append({"instance": gid,
                                      "error": str(e)})
            continue
        with open(os.path.join(inst_dir, SHRUNK_PLAN_FILE), "w") as f:
            json.dump(rec["shrunk-plan"], f, indent=2)
        rec["shrunk-plan-file"] = os.path.join(inst_dir,
                                               SHRUNK_PLAN_FILE)
        with open(os.path.join(inst_dir, SHRINK_FILE), "w") as f:
            json.dump(rec, f, indent=2)
        summary["shrunk"].append(rec)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "shrink-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=repr)
    return summary


def render_shrink_report(summary: Dict[str, Any]) -> str:
    lines = [f"shrink: {summary['workload']} run at "
             f"{summary['run-dir']}"]
    if summary.get("note"):
        lines.append(summary["note"])
    for rec in summary.get("shrunk", ()):
        lines.append(
            f"  instance {rec['instance']}: "
            f"{rec['original-phases']} phase(s)/"
            f"{rec['original-victims']} victim(s) -> "
            f"{rec['shrunk-phases']}/{rec['shrunk-victims']} in "
            f"{rec['attempts']} replay(s); verified "
            f"{rec['verified']} -> {rec.get('shrunk-plan-file', '?')}")
    for err in summary.get("errors", ()):
        lines.append(f"  instance {err['instance']}: ERROR "
                     f"{err['error']}")
    if summary.get("dropped"):
        lines.append(f"  (+{summary['dropped']} flagged instance(s) "
                     f"beyond --max-instances)")
    return "\n".join(lines)
