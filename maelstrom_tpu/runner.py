"""Test assembly and execution.

``run_test`` wires everything together: build the simulated network +
journal, bring up services and node processes (or the TPU runtime), drive
concurrent client workers from the workload's generator with rate
staggering, interleave the partition nemesis, run the final phase (heal ->
recovery sleep -> final reads), tear down, then run the composed checkers
and write artifacts to the store directory.

Parity: reference src/maelstrom/core.clj maelstrom-test :53-102 (generator
assembly :67-80, checker composition :91-100) + jepsen.core/run!'s worker
loop, and doc/results.md for the store layout.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from datetime import datetime
from typing import Any, Dict, List, Optional

from .core.message import Message  # noqa: F401  (re-export convenience)
from .net.net import Latency, Net
from .net.journal import Journal
from .runtime.db import DB
from .gen.history import History, client_invokes
from .gen.generators import OpSource, stagger_delay
from .nemesis import PartitionNemesis
from .checkers.net_stats import net_stats_checker
from .utils.ids import node_names


DEFAULTS = dict(
    node_count=1,
    concurrency=5,          # parsed from e.g. "4n" by the CLI
    rate=10.0,              # ops/sec across all workers
    time_limit=20.0,        # seconds of main phase
    latency=0.0,            # mean inter-node latency, ms
    latency_dist="exponential",
    p_loss=0.0,
    nemesis=[],             # e.g. ["partition"]
    nemesis_interval=10.0,
    recovery_time=10.0,     # post-heal quiesce before final reads
    availability=None,      # None | "total" | float fraction
    log_stderr=False,
    log_net_send=False,
    log_net_recv=False,
    seed=None,
    store_root="store",
    snapshot_store=True,
)


class Worker(threading.Thread):
    def __init__(self, idx: int, runner: "TestRunner"):
        super().__init__(name=f"worker-{idx}", daemon=True)
        self.idx = idx
        self.runner = runner
        self.error: Optional[BaseException] = None

    def run(self):
        try:
            self.runner._worker_loop(self.idx)
        except BaseException as e:  # surfaced after join
            self.error = e
            traceback.print_exc()


class TestRunner:
    def __init__(self, workload_name: str, workload: Dict[str, Any],
                 opts: Dict[str, Any]):
        self.opts = {**DEFAULTS, **opts}
        self.workload_name = workload_name
        self.workload = workload
        self.node_ids = node_names(self.opts["node_count"])
        # store dir
        ts = datetime.now().strftime("%Y%m%d-%H%M%S-%f")
        self.store_dir = None
        if self.opts.get("snapshot_store"):
            self.store_dir = os.path.join(self.opts["store_root"],
                                          workload_name, ts)
            os.makedirs(self.store_dir, exist_ok=True)
        self.journal = Journal(self.store_dir)
        self.net = Net(latency=Latency(self.opts["latency"],
                                       self.opts["latency_dist"]),
                       p_loss=self.opts["p_loss"],
                       log_send=self.opts["log_net_send"],
                       log_recv=self.opts["log_net_recv"],
                       journal=self.journal,
                       seed=self.opts["seed"])
        self.history = History()
        self.deadline = None
        self._final_phase = threading.Event()
        self.rngs = {}

    # --- worker loop ------------------------------------------------------

    def _worker_loop(self, idx: int):
        import random
        rng = random.Random(None if self.opts["seed"] is None
                            else self.opts["seed"] + 1000 + idx)
        node = self.node_ids[idx % len(self.node_ids)]
        make_client = self.workload["client"]
        wclient = make_client(self.net, node, self.opts)
        try:
            # main phase
            while time.monotonic() < self.deadline:
                delay = stagger_delay(self.opts["rate"],
                                      self.opts["concurrency"], rng)
                if delay:
                    end = min(time.monotonic() + delay, self.deadline)
                    while True:
                        # clamp: time may pass between the loop check
                        # and computing the remainder (negative sleep
                        # raised ValueError and killed the worker)
                        remaining = end - time.monotonic()
                        if remaining <= 0:
                            break
                        time.sleep(min(0.05, remaining))
                if time.monotonic() >= self.deadline:
                    break
                op = self.source.next_op()
                if op is None:
                    break
                self._invoke(idx, wclient, op)
                if getattr(wclient, "crashed", False):
                    # crash-client mode: discard and reopen (the
                    # non-Reusable client lifecycle, kafka.clj:238-241)
                    try:
                        wclient.close()
                    except Exception:
                        pass
                    wclient = make_client(self.net, node, self.opts)
            # final phase barrier: runner heals + sleeps, then sets event
            self._final_phase.wait()
            final = self.workload.get("final_generator")
            if final is not None:
                tag, make_ops = final
                assert tag == "each-thread"
                for op in make_ops():
                    if callable(op):
                        op = op(rng)
                    self._invoke(idx, wclient, {**op, "final": True})
        finally:
            try:
                wclient.close()
            except Exception:
                pass

    def _invoke(self, process: int, wclient, op: dict):
        inv_extra = {"final": True} if op.pop("final", False) else {}
        inv = self.history.invoke(process, op["f"], op.get("value"),
                                  **inv_extra)
        try:
            completed = wclient.invoke(dict(op))
        except Exception as e:
            from .workloads.base import ClientCrashed
            if isinstance(e, ClientCrashed):
                wclient.crashed = True
                completed = {**op, "type": "info", "error": ["crash"]}
            else:
                completed = {**op, "type": "info",
                             "error": ["exception", repr(e)]}
        ctype = completed.get("type", "info")
        if ctype == "invoke":  # client forgot to set outcome
            ctype = "info"
        extra = {k: v for k, v in completed.items()
                 if k not in ("f", "value", "type", "process", "index",
                              "time")}
        self.history.complete(inv, ctype, value=completed.get("value"),
                              **extra)

    # --- run --------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        opts = self.opts
        log_dir = (os.path.join(self.store_dir, "node-logs")
                   if self.store_dir else None)
        runtime = self.workload.get("runtime")  # None => process runtime
        db = None
        if runtime is None:
            db = DB(self.net, self.node_ids, opts["bin"],
                    opts.get("bin_args", []), log_dir=log_dir,
                    log_stderr=opts["log_stderr"], seed=opts["seed"])
            db.setup()
        else:
            runtime.setup(self)

        self.source = OpSource(self.workload.get("generator"),
                               seed=opts["seed"])
        nemesis = None
        if "partition" in (opts["nemesis"] or []):
            nemesis = PartitionNemesis(
                self.net, self.node_ids, self.history,
                interval=opts["nemesis_interval"], seed=opts["seed"])

        workers = [Worker(i, self) for i in range(opts["concurrency"])]
        self.deadline = time.monotonic() + opts["time_limit"]
        crash = None
        try:
            for w in workers:
                w.start()
            if nemesis:
                nemesis.start()
            # wait out the main phase
            while time.monotonic() < self.deadline:
                time.sleep(0.05)
            # final phase: heal, quiesce, then final reads
            if nemesis:
                nemesis.heal_final()
            if self.workload.get("final_generator") is not None:
                time.sleep(opts["recovery_time"])
            self._final_phase.set()
            for w in workers:
                w.join(timeout=max(60.0, opts["time_limit"]))
        finally:
            self._final_phase.set()
            if nemesis:
                nemesis.heal_final()
            try:
                if db is not None:
                    db.teardown()
                elif runtime is not None:
                    runtime.teardown(self)
            except Exception as e:
                crash = e
        results = self.check()
        worker_errors = [repr(w.error) for w in workers
                         if w.error is not None]
        if worker_errors:
            # keep the history/artifacts: a broken worker invalidates the
            # run but everything recorded is still written and analyzed
            results["worker-errors"] = worker_errors
            results["valid?"] = False
        if crash is not None:
            results["crashed"] = repr(crash)
            results["valid?"] = False
        self.write_store(results)
        return results

    # --- analysis ---------------------------------------------------------

    def check(self) -> Dict[str, Any]:
        from .checkers import check_history
        history = self.history.records()
        return check_history(
            history, self.opts, self.workload.get("checker"),
            extra={"net": net_stats_checker(self.journal, history,
                                            drops=self.net.drop_stats())},
            name=f"{self.workload_name}-checker")

    def write_store(self, results: Dict[str, Any]):
        if not self.store_dir:
            self.journal.close()
            return
        self.history.write_jsonl(os.path.join(self.store_dir,
                                              "history.jsonl"))
        from .gen.history import write_txt
        write_txt(self.history.records(),
                  os.path.join(self.store_dir, "history.txt"))
        with open(os.path.join(self.store_dir, "results.json"), "w") as f:
            json.dump(results, f, indent=2, default=repr)
        try:
            from .net.viz import plot_lamport
            plot_lamport(self.journal,
                         os.path.join(self.store_dir, "messages.svg"))
        except Exception:
            traceback.print_exc()
        try:
            from .checkers.perf import plot_perf
            plot_perf(self.history.records(), self.store_dir)
        except Exception:
            traceback.print_exc()
        try:
            from .checkers.timeline import render_timeline
            render_timeline(self.history.records(),
                            os.path.join(self.store_dir, "timeline.html"))
        except Exception:
            traceback.print_exc()
        self.journal.close()
        # maintain store/<workload>/latest symlink (doc/results.md:7-9)
        latest = os.path.join(os.path.dirname(self.store_dir), "latest")
        try:
            if os.path.islink(latest):
                os.unlink(latest)
            os.symlink(os.path.basename(self.store_dir), latest)
        except OSError:
            pass


def run_test(workload_name: str, opts: Dict[str, Any]) -> Dict[str, Any]:
    """Look up the workload by name, build it with opts, and run."""
    from .workloads import get_workload
    merged = {**DEFAULTS, **opts}
    workload = get_workload(workload_name)(merged)
    return TestRunner(workload_name, workload, merged).run()
