"""Operation history.

A history is an ordered list of records, one per client invoke/completion
and nemesis event, in Jepsen's shape::

    {"index": 0, "time": <ns>, "process": 0, "type": "invoke",
     "f": "read", "value": None}
    {"index": 1, "time": <ns>, "process": 0, "type": "ok",
     "f": "read", "value": 5}

``type`` is one of invoke / ok / fail / info. Checkers consume histories;
they are also serialized to the store dir as ``history.jsonl`` (and
optionally Jepsen-compatible EDN for external checkers).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterable, List, Optional


class History:
    def __init__(self):
        self._records: List[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.monotonic_ns()

    def now(self) -> int:
        return time.monotonic_ns() - self._t0

    def append(self, record: dict) -> dict:
        with self._lock:
            record = dict(record)
            record["index"] = len(self._records)
            record.setdefault("time", self.now())
            self._records.append(record)
            return record

    def invoke(self, process, f, value, **extra) -> dict:
        return self.append({"process": process, "type": "invoke",
                            "f": f, "value": value, **extra})

    def complete(self, invocation: dict, type: str, value=None,
                 **extra) -> dict:
        rec = {"process": invocation["process"], "type": type,
               "f": invocation["f"],
               "value": invocation["value"] if value is None else value}
        rec.update(extra)
        return self.append(rec)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def __len__(self):
        with self._lock:
            return len(self._records)

    def __iter__(self):
        return iter(self.records())

    def write_jsonl(self, path: str):
        with open(path, "w") as f:
            for r in self.records():
                f.write(json.dumps(r) + "\n")

    @staticmethod
    def from_records(records: Iterable[dict]) -> "History":
        h = History()
        for i, r in enumerate(records):
            r = dict(r)
            r.setdefault("index", i)
            r.setdefault("time", i)
            h._records.append(r)
        return h


def write_txt(records: Iterable[dict], path: str) -> None:
    """Condensed human-readable history — the reference's ``history.txt``
    (doc/results.md:24-26): columns process, type, f, value, error."""
    rows = []
    for r in records:
        val = r.get("value")
        rows.append((str(r.get("process", "")),
                     str(r.get("type", "")),
                     str(r.get("f", "")),
                     "" if val is None else json.dumps(val),
                     str(r.get("error", "") or "")))
    widths = [max((len(row[c]) for row in rows), default=0)
              for c in range(4)]
    with open(path, "w") as f:
        for row in rows:
            line = "  ".join(row[c].ljust(widths[c]) for c in range(4))
            if row[4]:
                line += "  " + row[4]
            f.write(line.rstrip() + "\n")


# --- analysis helpers used by checkers ------------------------------------

def ok_ops(history, f: Optional[str] = None) -> List[dict]:
    return [r for r in history
            if r["type"] == "ok" and (f is None or r["f"] == f)]


def client_invokes(history) -> List[dict]:
    return [r for r in history
            if r["type"] == "invoke" and r.get("process") != "nemesis"]


def pairs(history) -> List[Dict[str, Optional[dict]]]:
    """Match invokes with their completions per process. An invoke with no
    completion (still pending at test end) pairs with None."""
    open_ops: Dict = {}
    out = []
    for r in history:
        p = r.get("process")
        if r["type"] == "invoke":
            entry = {"invoke": r, "complete": None}
            open_ops[p] = entry
            out.append(entry)
        elif r["type"] in ("ok", "fail", "info") and p in open_ops:
            open_ops.pop(p)["complete"] = r
    return out
