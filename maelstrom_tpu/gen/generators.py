"""Operation generators.

A generator is any iterable/iterator producing *op templates*: dicts with at
least ``{"f": ...}`` and usually ``{"value": ...}``; entries may also be
callables ``(rng) -> op`` for per-draw randomness. The scheduler pulls ops
from a shared generator across worker threads, staggering pulls so the whole
test averages ``rate`` ops/sec, until the time limit; then each worker runs
the per-thread ``final`` generator (e.g. final reads).

Parity: reference generator assembly at src/maelstrom/core.clj:67-80
(stagger 1/rate -> nemesis interleave -> time-limit -> final phase) built on
jepsen.generator; the combinators here (mix, each_thread, repeat_op,
stagger semantics) mirror the jepsen.generator ops the workloads use.
"""

from __future__ import annotations

import itertools
import random
import threading
from typing import Callable, Iterable, Iterator, Optional


def op(f, value=None, **extra):
    d = {"f": f, "value": value}
    d.update(extra)
    return d


def repeat_op(f, value=None):
    """Infinite stream of identical op templates (e.g. unique-ids
    generate)."""
    return itertools.repeat(op(f, value))


def mix(*makers: Callable[[random.Random], dict]):
    """Infinite random mix of op makers, like jepsen.generator/mix."""
    def gen(rng: random.Random) -> Iterator[dict]:
        while True:
            yield rng.choice(makers)(rng)
    return gen


class OpSource:
    """Thread-safe shared pull point over a generator.

    The generator may be: an iterator/iterable of ops, or a callable
    ``(rng) -> iterator``. Ops may themselves be callables ``(rng) -> op``.
    """

    def __init__(self, gen, seed: Optional[int] = None):
        self.rng = random.Random(seed)
        if callable(gen):
            gen = gen(self.rng)
        self._it = iter(gen) if gen is not None else iter(())
        self._lock = threading.Lock()

    def next_op(self) -> Optional[dict]:
        with self._lock:
            try:
                item = next(self._it)
            except StopIteration:
                return None
        if callable(item):
            item = item(self.rng)
        return dict(item)


def stagger_delay(rate: float, concurrency: int, rng: random.Random) -> float:
    """Per-worker sleep before each op so the *aggregate* op rate across all
    workers averages ``rate`` ops/sec, with exponential jitter (the
    equivalent of jepsen's (gen/stagger (/ rate)))."""
    if rate <= 0:
        return 0.0
    mean = concurrency / rate
    return rng.expovariate(1.0 / mean) if mean > 0 else 0.0


def each_thread(make_ops: Callable[[], Iterable[dict]]):
    """A final-phase generator: every worker thread independently runs its
    own copy of make_ops() (like jepsen's gen/each-thread)."""
    return ("each-thread", make_ops)
