"""Schema system + RPC registry — the single source of truth for message
vocabularies.

Every workload declares its RPCs with :func:`rpc`: a name, a docstring, and
request/response schemas. The registry drives (a) runtime validation of
requests and responses at the client boundary, (b) generated protocol docs,
and (c) the fixed-width payload encodings used by the TPU runtime.

Parity: reference src/maelstrom/client.clj:228-270 (defrpc macro + registry),
src/maelstrom/doc.clj (doc generation from the registry).

Schemas are intentionally tiny — just enough to validate JSON bodies and to
render readable docs. A schema is one of:

- a python type: ``int``, ``str``, ``bool``, ``float`` (accepts int too)
- ``Any`` — anything
- ``[elem]`` — list with homogeneous element schema
- ``{key: schema, ...}`` with string keys; wrap a key in :class:`Opt` to mark
  it optional; an ``Ellipsis`` key allows arbitrary extra entries
- :class:`MapOf`\\ (key_schema, val_schema) — homogeneous dict
- :class:`Enum`\\ (*values) — one of the literal values
- :class:`OneOf`\\ (*schemas) — union
- ``None`` — JSON null only
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any as TAny, Dict, List, Optional


class _AnyType:
    def __repr__(self):
        return "Any"


Any = _AnyType()


class Opt:
    """Marks a dict key as optional."""

    def __init__(self, key: str):
        self.key = key

    def __repr__(self):
        return f"Opt({self.key!r})"

    def __hash__(self):
        return hash(("Opt", self.key))

    def __eq__(self, other):
        return isinstance(other, Opt) and other.key == self.key


class MapOf:
    def __init__(self, key_schema, val_schema):
        self.key_schema = key_schema
        self.val_schema = val_schema

    def __repr__(self):
        return f"MapOf({render(self.key_schema)}, {render(self.val_schema)})"


class Enum:
    def __init__(self, *values):
        self.values = values

    def __repr__(self):
        return "Enum(" + ", ".join(map(repr, self.values)) + ")"


class OneOf:
    def __init__(self, *schemas):
        self.schemas = schemas

    def __repr__(self):
        return "OneOf(" + ", ".join(render(s) for s in self.schemas) + ")"


class SchemaError(ValueError):
    pass


def check(schema, value, path="value"):
    """Validate value against schema; raises SchemaError with a path."""
    if schema is Any:
        return
    if schema is None:
        if value is not None:
            raise SchemaError(f"{path}: expected null, got {value!r}")
        return
    if schema is int:
        if not isinstance(value, int) or isinstance(value, bool):
            raise SchemaError(f"{path}: expected an integer, got {value!r}")
        return
    if schema is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SchemaError(f"{path}: expected a number, got {value!r}")
        return
    if schema is str:
        if not isinstance(value, str):
            raise SchemaError(f"{path}: expected a string, got {value!r}")
        return
    if schema is bool:
        if not isinstance(value, bool):
            raise SchemaError(f"{path}: expected a boolean, got {value!r}")
        return
    if isinstance(schema, Enum):
        if value not in schema.values:
            raise SchemaError(
                f"{path}: expected one of {schema.values!r}, got {value!r}")
        return
    if isinstance(schema, OneOf):
        errs = []
        for s in schema.schemas:
            try:
                check(s, value, path)
                return
            except SchemaError as e:
                errs.append(str(e))
        raise SchemaError(f"{path}: no alternative matched {value!r}: "
                          + "; ".join(errs))
    if isinstance(schema, MapOf):
        if not isinstance(value, dict):
            raise SchemaError(f"{path}: expected a map, got {value!r}")
        for k, v in value.items():
            check(schema.key_schema, k, f"{path} key {k!r}")
            check(schema.val_schema, v, f"{path}[{k!r}]")
        return
    if isinstance(schema, list):
        if len(schema) != 1:
            # tuple-style positional schema
            if not isinstance(value, list) or len(value) != len(schema):
                raise SchemaError(
                    f"{path}: expected a {len(schema)}-element list, got "
                    f"{value!r}")
            for i, (s, v) in enumerate(zip(schema, value)):
                check(s, v, f"{path}[{i}]")
            return
        if not isinstance(value, list):
            raise SchemaError(f"{path}: expected a list, got {value!r}")
        for i, v in enumerate(value):
            check(schema[0], v, f"{path}[{i}]")
        return
    if isinstance(schema, dict):
        if not isinstance(value, dict):
            raise SchemaError(f"{path}: expected a map, got {value!r}")
        open_map = any(k is Ellipsis for k in schema)
        known = set()
        for k, vschema in schema.items():
            if k is Ellipsis:
                continue
            optional = isinstance(k, Opt)
            key = k.key if optional else k
            known.add(key)
            if key not in value:
                if not optional:
                    raise SchemaError(f"{path}: missing required key {key!r} "
                                      f"in {value!r}")
                continue
            check(vschema, value[key], f"{path}[{key!r}]")
        if not open_map:
            extra = set(value) - known
            if extra:
                raise SchemaError(
                    f"{path}: unexpected keys {sorted(extra)!r} in {value!r}")
        return
    raise SchemaError(f"{path}: unknown schema {schema!r}")


def valid(schema, value) -> bool:
    try:
        check(schema, value)
        return True
    except SchemaError:
        return False


def render(schema) -> str:
    """Human-readable schema rendering for docs."""
    if schema is Any:
        return "any"
    if schema is None:
        return "null"
    if schema is int:
        return "Int"
    if schema is float:
        return "Number"
    if schema is str:
        return "String"
    if schema is bool:
        return "Bool"
    if isinstance(schema, (Enum, OneOf, MapOf)):
        return repr(schema)
    if isinstance(schema, list):
        return "[" + ", ".join(render(s) for s in schema) + "]"
    if isinstance(schema, dict):
        parts = []
        for k, v in schema.items():
            if k is Ellipsis:
                parts.append("...")
            elif isinstance(k, Opt):
                parts.append(f"{k.key}?: {render(v)}")
            else:
                parts.append(f"{k}: {render(v)}")
        return "{" + ", ".join(parts) + "}"
    return repr(schema)


# --- RPC registry ----------------------------------------------------------

@dataclass
class RPCDef:
    namespace: str               # workload/service name, e.g. "broadcast"
    name: str                    # message type, e.g. "broadcast"
    doc: str
    request: dict
    response: dict
    response_type: str = ""

    def full_request_schema(self) -> dict:
        s = {"type": Enum(self.name), Opt("msg_id"): int, Ellipsis: Any}
        s.update(self.request)
        return s

    def full_response_schema(self) -> dict:
        s = {"type": Enum(self.response_type),
             Opt("msg_id"): int, Opt("in_reply_to"): int, Ellipsis: Any}
        s.update(self.response)
        return s


# namespace -> name -> RPCDef, insertion-ordered for stable docs
REGISTRY: Dict[str, Dict[str, RPCDef]] = {}


def rpc(namespace: str, name: str, doc: str, request: dict, response: dict,
        response_type: Optional[str] = None) -> RPCDef:
    """Declare an RPC: registers it and returns the definition.

    ``request``/``response`` are body schemas *excluding* the envelope fields
    (type / msg_id / in_reply_to), which are added automatically.
    """
    d = RPCDef(namespace=namespace, name=name, doc=doc, request=request,
               response=response,
               response_type=response_type or (name + "_ok"))
    REGISTRY.setdefault(namespace, {})[name] = d
    return d


def get_rpc(namespace: str, name: str) -> Optional[RPCDef]:
    return REGISTRY.get(namespace, {}).get(name)
