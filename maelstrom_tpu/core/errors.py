"""Protocol error catalog.

The wire protocol defines a fixed set of numeric error codes carried in
``{"type": "error", "code": <int>, "text": <str>}`` bodies. Each code is either
*definite* (the requested operation certainly did not happen) or *indefinite*
(the outcome is unknown — e.g. a timeout). Checkers rely on this distinction:
a definite error maps a client op to ``fail``, an indefinite one to ``info``.

Parity: reference resources/errors.edn:1-44 and
src/maelstrom/client.clj:22-39 (error registry + exception mapping).
Codes >= 1000 are reserved for user-defined errors and treated as definite
unless declared otherwise (reference resources/protocol-intro.md:133-135).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ErrorDef:
    code: int
    name: str
    definite: bool
    doc: str


_ERRORS = [
    ErrorDef(0, "timeout", False,
             "Indicates that the requested operation could not be completed "
             "within a timeout."),
    ErrorDef(1, "node-not-found", True,
             "Thrown when a client sends an RPC request to a node which does "
             "not exist."),
    ErrorDef(10, "not-supported", True,
             "Use this error to indicate that a requested operation is not "
             "supported by the current implementation."),
    ErrorDef(11, "temporarily-unavailable", True,
             "Indicates that the operation definitely cannot be performed at "
             "this time -- perhaps because the server is in a read-only "
             "state, has not yet been initialized, believes its peers to be "
             "down, and so on."),
    ErrorDef(12, "malformed-request", True,
             "The client's request did not conform to the server's "
             "expectations, and could not possibly have been processed."),
    ErrorDef(13, "crash", False,
             "Indicates that some kind of general, indefinite error "
             "occurred."),
    ErrorDef(14, "abort", True,
             "Indicates that some kind of general, definite error occurred."),
    ErrorDef(20, "key-does-not-exist", True,
             "The client requested an operation on a key which does not "
             "exist (assuming the operation should not automatically create "
             "missing keys)."),
    ErrorDef(21, "key-already-exists", True,
             "The client requested the creation of a key which already "
             "exists, and the server will not overwrite it."),
    ErrorDef(22, "precondition-failed", True,
             "The requested operation expected some conditions to hold, and "
             "those conditions were not met."),
    ErrorDef(30, "txn-conflict", True,
             "The requested transaction has been aborted because of a "
             "conflict with another transaction."),
]

ERRORS_BY_CODE = {e.code: e for e in _ERRORS}
ERRORS_BY_NAME = {e.name: e for e in _ERRORS}


def definite(code: int) -> bool:
    """Is this error code definite? Unknown (user) codes default to definite."""
    e = ERRORS_BY_CODE.get(code)
    return e.definite if e is not None else True


class RPCError(Exception):
    """An ``error`` body received in reply to an RPC request."""

    def __init__(self, code: int, text: str = ""):
        self.code = code
        self.text = text
        e = ERRORS_BY_CODE.get(code)
        self.name = e.name if e else f"error-{code}"
        self.definite = definite(code)
        super().__init__(f"RPC error {code} ({self.name}): {text}")

    def to_body(self, in_reply_to=None) -> dict:
        body = {"type": "error", "code": self.code, "text": self.text}
        if in_reply_to is not None:
            body["in_reply_to"] = in_reply_to
        return body


def timeout(text="timed out") -> RPCError:
    return RPCError(0, text)


def node_not_found(text) -> RPCError:
    return RPCError(1, text)


def not_supported(text) -> RPCError:
    return RPCError(10, text)


def temporarily_unavailable(text) -> RPCError:
    return RPCError(11, text)


def malformed_request(text) -> RPCError:
    return RPCError(12, text)


def crash(text) -> RPCError:
    return RPCError(13, text)


def abort(text) -> RPCError:
    return RPCError(14, text)


def key_does_not_exist(text) -> RPCError:
    return RPCError(20, text)


def key_already_exists(text) -> RPCError:
    return RPCError(21, text)


def precondition_failed(text) -> RPCError:
    return RPCError(22, text)


def txn_conflict(text) -> RPCError:
    return RPCError(30, text)
