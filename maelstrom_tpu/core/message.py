"""Message model.

A message is ``{id, src, dest, body}`` where ``body`` is a JSON-serializable
dict carrying at least a ``type`` field; requests carry ``msg_id`` and replies
``in_reply_to``. Parity: reference src/maelstrom/net/message.clj:8-25 and
resources/protocol-intro.md.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class Message:
    id: int                      # globally unique, harness-assigned
    src: str                     # node id, e.g. "n1", "c3", "lin-kv"
    dest: str
    body: Dict[str, Any] = field(default_factory=dict)

    @property
    def type(self) -> Optional[str]:
        return self.body.get("type")

    @property
    def msg_id(self) -> Optional[int]:
        return self.body.get("msg_id")

    @property
    def in_reply_to(self) -> Optional[int]:
        return self.body.get("in_reply_to")

    def to_wire(self) -> Dict[str, Any]:
        """The JSON dict a node sees on its stdin (id is harness-internal)."""
        return {"id": self.id, "src": self.src, "dest": self.dest,
                "body": self.body}

    @staticmethod
    def from_wire(d: Dict[str, Any], id: int = -1) -> "Message":
        return Message(id=id, src=d["src"], dest=d["dest"], body=d["body"])

    def validate(self) -> "Message":
        if not isinstance(self.src, str) or not self.src:
            raise ValueError(f"message src must be a non-empty string: {self}")
        if not isinstance(self.dest, str) or not self.dest:
            raise ValueError(f"message dest must be a non-empty string: {self}")
        if not isinstance(self.body, dict):
            raise ValueError(f"message body must be a dict: {self}")
        return self


def reply_body(request_body: Dict[str, Any], **fields) -> Dict[str, Any]:
    """Build a reply body, wiring in_reply_to from the request's msg_id."""
    body = dict(fields)
    if "msg_id" in request_body:
        body["in_reply_to"] = request_body["msg_id"]
    return body
