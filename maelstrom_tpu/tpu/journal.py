"""Per-message journal for TPU-runtime instances.

The device streams back the raw sent rows and delivered inboxes of the
first ``journal_instances`` instances (runtime.TickOutputs); this module
decodes ONE instance's traffic into the same interface the host
:class:`~..net.journal.Journal` exposes — ``events()`` for the Lamport
``messages.svg`` renderer (net/viz.py) and ``stats()`` for the net-stats
checker breakdown — closing the r1 observability gap where device runs
had aggregate counters only (VERDICT r1 missing #5; reference
src/maelstrom/net/journal.clj:225-347, net/checker.clj:28-70).

Send/recv pairing keys on the runtime-stamped trailing NETID lane (the
send-time message-ID allocation of net.clj:196-201); journaling runs
always carry it (``NetConfig.netid`` — the narrow default wire format
drops the lane, and ``make_sim_config`` refuses journaling without it).
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

from . import wire


def _node_name(idx: int, n_nodes: int) -> str:
    return f"n{idx}" if idx < n_nodes else f"c{idx - n_nodes}"


class TpuJournal:
    """Decoded message journal of one journaled instance.

    ``sends``: [T, J, M, L]; ``recvs``: [T, J, NT, K, L] (numpy int32).
    """

    def __init__(self, model, cfg, sends: np.ndarray, recvs: np.ndarray,
                 instance: int = 0, ms_per_tick: float = 1.0):
        self.model = model
        self.cfg = cfg
        self.ms_per_tick = ms_per_tick
        self._events: List[dict] = []
        n = cfg.n_nodes
        T = sends.shape[0]
        for t in range(T):
            # recvs first: anything delivered at t was sent at an earlier
            # tick, so its send event is already out
            for row in recvs[t, instance].reshape(-1, recvs.shape[-1]):
                if row[wire.VALID] == 1:
                    self._events.append(self._event("recv", t, row))
            for row in sends[t, instance]:
                if row[wire.VALID] == 1:
                    self._events.append(self._event("send", t, row))

    def _event(self, etype: str, t: int, row: np.ndarray) -> dict:
        n = self.cfg.n_nodes
        body_vals = [int(x) for x in
                     row[wire.BODY:wire.BODY + self.cfg.body_lanes]]
        body = {"type": int(row[wire.TYPE])}
        if row[wire.MSGID] >= 0:
            body["msg_id"] = int(row[wire.MSGID])
        if row[wire.REPLYTO] >= 0:
            body["in_reply_to"] = int(row[wire.REPLYTO])
        # trim trailing zero lanes for a readable label
        while body_vals and body_vals[-1] == 0:
            body_vals.pop()
        if body_vals:
            body["b"] = body_vals
        return {
            "time": int(t * self.ms_per_tick * 1_000_000),
            "type": etype,
            "message": {
                # journaled runs always carry the trailing NETID lane
                # (make_sim_config refuses journaling without it)
                "id": int(row[self.cfg.netid_lane]),
                "src": _node_name(int(row[wire.SRC]), n),
                "dest": _node_name(int(row[wire.DEST]), n),
                "body": body,
            },
        }

    def events(self) -> Iterator[dict]:
        return iter(self._events)

    def stats(self) -> Dict[str, Dict[str, int]]:
        from ..utils.ids import is_client
        counts = {k: {"send-count": 0, "recv-count": 0, "msg-count": 0}
                  for k in ("all", "clients", "servers")}
        ids = {"all": set(), "clients": set(), "servers": set()}
        for ev in self._events:
            m = ev["message"]
            cls = ("clients" if is_client(m["src"]) or is_client(m["dest"])
                   else "servers")
            key = "send-count" if ev["type"] == "send" else "recv-count"
            counts["all"][key] += 1
            counts[cls][key] += 1
            ids["all"].add(m["id"])
            ids[cls].add(m["id"])
        for k in counts:
            counts[k]["msg-count"] = len(ids[k])
        return counts
