"""Certified AOT executable store — seconds-to-first-tick for the fleet.

The production dispatch callables (``tpu/pipeline.py::make_chunk_fn``
and ``parallel/mesh.py::make_sharded_chunk_fn`` — the same surfaces
JXP403 and the SHD8xx auditor already certify) are AOT-lowered,
compiled, and serialized (``jax.experimental.serialize_executable``)
into a content-addressed on-disk store. ``run_tpu_test``,
``run_sim_sharded_chunked``, bench.py, and the campaign runner consult
the store BEFORE tracing: a hit deserializes the executable and skips
trace+compile entirely, a miss compiles once and populates the entry.

Keying is two-tier, because a hit must never pay a trace:

* the **store key** (the dispatch-time lookup) is a sha256 over facts
  the host knows without tracing — the canonical sim/model config, the
  carry/wire leaf avals (layout x wire width x instance count), the
  static chunk arguments, the mesh shape, a digest of the traced source
  files, the jax version, and the device kind. Any of those drifting is
  a safe miss (recompile + repopulate), never a wrong executable.
* the **canonical jaxpr digest** (the certificate) is recorded in the
  entry's sidecar meta at populate time and re-verified by ``maelstrom
  lint --aot`` (analysis/aot_audit.py): EXE901 fires when a stored
  fingerprint no longer matches the jaxpr the current source traces to,
  EXE902 when the DESERIALIZED executable lost its donation aliasing,
  EXE903 when its collective census drifted from shard_manifest.json,
  EXE904 when the recorded toolchain/device-kind no longer matches.

``MAELSTROM_AOT=0`` is the kill switch; ``--aot-store DIR|off`` picks
the directory (default: the resolved compile-cache dir + ``.aot`` —
``.jax_cache`` gets a ``.jax_cache.aot`` sibling). Loads are refused —
by name, not silently — when the entry's recorded jax version or
device kind differs from the running toolchain (the runtime face of
EXE904), and a payload whose bytes no longer match their recorded
sha256 is treated as a miss (the runtime face of EXE901). Every other
failure degrades to the ordinary jit path: the store is an
accelerator, never a correctness dependency.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import re
import tempfile
import time
import warnings
from typing import Any, Dict, Iterator, Optional, Tuple

ENV_VAR = "MAELSTROM_AOT"
STORE_VERSION = 1
DEFAULT_SUFFIX = ".aot"

# the packages whose source feeds the traced chunk computation; the
# digest over them is the cheap (no-trace) drift guard in the store key
# — analysis/, campaign/, telemetry/, cli never enter the jaxpr
_SOURCE_PACKAGES = ("tpu", "parallel", "models", "faults", "checkers")

# HLO collective ops counted in the stored executable's census
# (EXE903); mirrors analysis/shard_audit.hlo_collective_census
_HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_HEX = re.compile(r"0x[0-9a-fA-F]+")
_WS = re.compile(r"\s+")

_src_cache: Dict[str, str] = {}


class _uncached_compile:
    """Bypass the persistent XLA compile cache for one populate
    compile. An executable SERVED by that cache serializes into a
    payload whose jitted symbols are missing at deserialize time
    (``Symbols not found`` on CPU) — the store must only ever hold
    binaries from a real compile, so populate pays one even when the
    XLA cache has the entry. Flipping the config flag alone is not
    enough: ``is_cache_used`` latches its verdict at the first compile
    of the process, so the latch must be reset around the flip (and
    reset again after, so the restored flag re-initializes cleanly)."""

    def __enter__(self):
        import jax
        try:
            from jax._src import compilation_cache as cc
            self._prev = jax.config.jax_enable_compilation_cache
            cc.reset_cache()
            jax.config.update("jax_enable_compilation_cache", False)
        except Exception:
            self._prev = None
        return self

    def __exit__(self, *exc):
        if self._prev is not None:
            import jax
            from jax._src import compilation_cache as cc
            jax.config.update("jax_enable_compilation_cache",
                              self._prev)
            cc.reset_cache()
        return False


def aot_enabled() -> bool:
    """``MAELSTROM_AOT=0`` kills every store consultation."""
    return os.environ.get(ENV_VAR, "").strip() != "0"


def resolve_store_dir(flag: Optional[str],
                      compile_cache_flag: Optional[str] = None
                      ) -> Optional[str]:
    """The effective store dir, or ``None`` when disabled.

    ``flag``: ``None``/``"auto"`` rides the compile cache (the resolved
    cache dir + ``.aot``; a disabled compile cache disables the store
    too), ``"off"``/``"0"``/``""`` disables, anything else is the
    directory. The ``MAELSTROM_AOT=0`` kill switch wins over all."""
    if not aot_enabled():
        return None
    if flag is not None and str(flag).strip() in ("off", "0", ""):
        return None
    if flag is not None and str(flag).strip() != "auto":
        return os.path.abspath(str(flag))
    from ..utils.compile_cache import DEFAULT_DIR, resolve_cache_dir
    cache = resolve_cache_dir(DEFAULT_DIR if compile_cache_flag is None
                              else compile_cache_flag)
    if cache is None:
        return None
    return os.path.abspath(cache) + DEFAULT_SUFFIX


def source_digest() -> str:
    """sha256 over every traced-surface source file (tpu/, parallel/,
    models/, faults/, checkers/). Part of the store key: an edited
    source is a guaranteed store MISS before any trace happens — the
    cheap runtime face of the EXE901 gate. Cached per process."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cached = _src_cache.get(pkg_root)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for sub in _SOURCE_PACKAGES:
        base = os.path.join(pkg_root, sub)
        for dirpath, dirnames, filenames in sorted(os.walk(base)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                h.update(os.path.relpath(path, pkg_root).encode())
                with open(path, "rb") as f:
                    h.update(f.read())
    digest = h.hexdigest()[:16]
    _src_cache[pkg_root] = digest
    return digest


def _canon(x: Any) -> Any:
    """Canonical JSON-able form of a config value: dataclasses and
    namedtuples flatten field-by-field, arrays become
    (dtype, shape, value-hash), callables their qualname — so the store
    key covers every Python constant the trace would bake in."""
    import numpy as np
    if x is None or isinstance(x, (bool, int, str)):
        return x
    if isinstance(x, float):
        return repr(x)
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return {"__dc__": type(x).__name__,
                **{f.name: _canon(getattr(x, f.name))
                   for f in dataclasses.fields(x)}}
    if isinstance(x, tuple) and hasattr(x, "_fields"):
        return {"__nt__": type(x).__name__,
                **{k: _canon(v) for k, v in zip(x._fields, x)}}
    if isinstance(x, dict):
        return {"__d__": sorted(
            ([str(k), _canon(v)] for k, v in x.items()),
            key=lambda kv: kv[0])}
    if isinstance(x, (list, tuple, set, frozenset)):
        items = [_canon(v) for v in x]
        return sorted(map(json.dumps, items)) \
            if isinstance(x, (set, frozenset)) else items
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        try:
            arr = np.asarray(x)
        except Exception:   # abstract value: shapes/dtypes only
            return {"__s__": [str(x.dtype), list(x.shape)]}
        return {"__a__": [str(arr.dtype), list(arr.shape),
                          hashlib.sha256(arr.tobytes()).hexdigest()[:16]]}
    if callable(x):
        return {"__f__": f"{getattr(x, '__module__', '?')}."
                         f"{getattr(x, '__qualname__', repr(x))}"}
    return {"__r__": repr(x)}


def _aval_sig(tree: Any) -> Dict[str, Any]:
    """Tree structure + per-leaf (dtype, shape) — the carry/wire shape
    face of the fingerprint (layout, wire width, instance count)."""
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    return {"treedef": str(treedef),
            "leaves": [[str(getattr(l, "dtype", "?")),
                        list(getattr(l, "shape", ()))] for l in leaves]}


def store_key(sig: Dict[str, Any]) -> str:
    """Content address of one executable: sha256 of the canonical
    signature."""
    blob = json.dumps(_canon(sig), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def _device_sig() -> Tuple[str, str]:
    import jax
    dev = jax.devices()[0]
    return dev.platform, getattr(dev, "device_kind", dev.platform)


def pipelined_signature(model, sim, params, instance_ids, cap,
                        unroll: int, scan_k: int, length: int,
                        carry) -> Dict[str, Any]:
    """Everything that determines the single-device chunk executable.
    ``params`` and ``instance_ids`` are hashed BY VALUE — the pipelined
    chunk fn closes over them, so they are burned into the binary."""
    import jax
    platform, kind = _device_sig()
    return {
        "store-version": STORE_VERSION, "kind": "pipelined",
        "model": getattr(model, "name", type(model).__name__),
        "model-config": {k: v for k, v in vars(model).items()
                         if not k.startswith("_")},
        "sim": sim, "params": params, "instance-ids": instance_ids,
        "cap": cap, "unroll": unroll, "scan-k": scan_k,
        "length": length, "carry": _aval_sig(carry),
        "jax": jax.__version__, "platform": platform,
        "device-kind": kind, "n-devices": jax.device_count(),
        "src": source_digest(),
    }


def sharded_signature(model, sim, mesh, params, scan_k: int,
                      length: int, wire) -> Dict[str, Any]:
    """Everything that determines the sharded chunk executable.
    ``params`` cross the wire as an argument, so only their avals
    matter; the mesh shape and device census are part of the key."""
    import jax
    platform, kind = _device_sig()
    return {
        "store-version": STORE_VERSION, "kind": "sharded",
        "model": getattr(model, "name", type(model).__name__),
        "model-config": {k: v for k, v in vars(model).items()
                         if not k.startswith("_")},
        "sim": sim, "params": _aval_sig(params),
        "scan-k": scan_k, "length": length, "wire": _aval_sig(wire),
        "mesh": [[str(n), int(s)] for n, s in
                 zip(mesh.axis_names, mesh.devices.shape)],
        "jax": jax.__version__, "platform": platform,
        "device-kind": kind, "n-devices": jax.device_count(),
        "src": source_digest(),
    }


def jaxpr_digest(closed) -> str:
    """The canonical fingerprint of a traced computation: sha256 of the
    jaxpr's pretty-printed text with addresses and whitespace scrubbed
    (stable across processes and repeated traces — pinned by
    tests/test_aot.py). This is the certificate ``maelstrom lint
    --aot`` re-derives from source and compares against every stored
    entry (EXE901)."""
    txt = _WS.sub(" ", _HEX.sub("0x", str(closed)))
    return hashlib.sha256(txt.encode()).hexdigest()[:32]


def hlo_collective_census(compiled_text: str) -> Dict[str, int]:
    """Count ICI collective ops in compiled HLO text (the stored-HLO
    half of the EXE903 drift gate; shard_audit has the jaxpr half)."""
    return {op: n for op in _HLO_COLLECTIVES
            if (n := compiled_text.count(f" {op}(")) > 0}


def entry_label(model, sim, kind: str,
                mesh_size: Optional[int] = None) -> str:
    """The coarse, content-independent identity of an entry —
    ``<workload>/n=<nodes>/<layout>/<kind>[/s=<mesh>]`` — what the lint
    pass uses to pair store entries with its audit subjects even after
    the content hash drifted (EXE901 needs to NAME the drifted entry,
    not merely fail to find it)."""
    base = (f"{getattr(model, 'name', type(model).__name__)}"
            f"/n={sim.net.n_nodes}/{sim.layout}/{kind}")
    if mesh_size is not None:
        base += f"/s={mesh_size}"
    return base


# --------------------------------------------------------------------
# the on-disk store
# --------------------------------------------------------------------

class AotStore:
    """Content-addressed executable store: ``<key>.bin`` holds the
    pickled (payload, in_tree, out_tree) triple from
    ``serialize_executable.serialize``, ``<key>.json`` the audit
    sidecar (fingerprint, donation aliases, collective census,
    toolchain). Writes are atomic (tempfile + rename) so a killed
    populate never leaves a half-entry."""

    def __init__(self, root: str):
        self.root = root

    def _bin(self, key: str) -> str:
        return os.path.join(self.root, key + ".bin")

    def _meta(self, key: str) -> str:
        return os.path.join(self.root, key + ".json")

    def meta(self, key: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._meta(key)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def entries(self) -> Iterator[Tuple[str, Dict[str, Any]]]:
        """(key, meta) for every readable entry, key-sorted."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return
        for name in names:
            if not name.endswith(".json"):
                continue
            meta = self.meta(name[:-5])
            if meta is not None:
                yield name[:-5], meta

    def load_payload(self, key: str) -> Optional[Tuple[bytes, Any, Any]]:
        """The raw (payload, in_tree, out_tree) triple — integrity-
        checked against the sidecar's payload sha but NOT toolchain-
        gated (the lint pass needs to load entries it will then refuse
        by name)."""
        meta = self.meta(key)
        if meta is None:
            return None
        try:
            with open(self._bin(key), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if (hashlib.sha256(blob).hexdigest()
                != meta.get("payload-sha256")):
            return None   # tampered/truncated payload: never load it
        try:
            payload, in_tree, out_tree = pickle.loads(blob)
        except Exception:
            return None
        return payload, in_tree, out_tree

    def load(self, key: str):
        """Deserialize an entry into a callable Compiled, or ``None``
        on any miss: absent, integrity-failed, or recorded for a
        different jax version / device kind (the runtime face of
        EXE904 — a foreign binary is refused, not loaded)."""
        import jax
        meta = self.meta(key)
        if meta is None:
            return None
        platform, kind = _device_sig()
        if (meta.get("jax-version") != jax.__version__
                or meta.get("device-kind") != kind
                or meta.get("platform") != platform):
            return None
        triple = self.load_payload(key)
        if triple is None:
            return None
        try:
            from jax.experimental import serialize_executable
            return serialize_executable.deserialize_and_load(*triple)
        except Exception:
            return None

    def put(self, key: str, compiled, meta: Dict[str, Any]) -> bool:
        """Serialize + write one entry atomically. Returns False (and
        stores nothing) when the executable does not serialize on this
        backend."""
        try:
            from jax.experimental import serialize_executable
            triple = serialize_executable.serialize(compiled)
            blob = pickle.dumps(triple)
            # round-trip self-check: a payload this process cannot
            # load back (e.g. serialized from a persistent-cache-
            # served executable) must never be stored — every entry
            # on disk is loadable by construction
            serialize_executable.deserialize_and_load(
                *pickle.loads(blob))
        except Exception:
            return False
        os.makedirs(self.root, exist_ok=True)
        meta = dict(meta, **{"payload-sha256":
                             hashlib.sha256(blob).hexdigest()})
        for path, data, mode in ((self._bin(key), blob, "wb"),
                                 (self._meta(key),
                                  json.dumps(meta, indent=1,
                                             sort_keys=True) + "\n",
                                  "w")):
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, mode) as f:
                    f.write(data)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        return True


def build_meta(sig: Dict[str, Any], cache_key: str, entry: str,
               digest: Optional[str], compiled,
               donated_leaves: int) -> Dict[str, Any]:
    """The audit sidecar of one entry: everything ``maelstrom lint
    --aot`` checks without re-deserializing, plus the canonical
    fingerprint EXE901 compares."""
    import jax
    try:
        text = compiled.as_text()
    except Exception:
        text = ""
    try:
        from ..analysis.ir_lint import aliased_params_of
        aliased = sorted(aliased_params_of(text))
    except Exception:
        aliased = []
    platform, kind = _device_sig()
    return {
        "version": STORE_VERSION,
        "key": cache_key,
        "entry": entry,
        "kind": sig["kind"],
        "model": sig["model"],
        "fingerprint": {
            "jaxpr-digest": digest,
            "src-digest": sig["src"],
            "carry-layout": getattr(sig.get("sim"), "layout", None),
            "chunk-length": sig["length"],
            "mesh": sig.get("mesh"),
        },
        "jax-version": jax.__version__,
        "platform": platform,
        "device-kind": kind,
        "donated-leaves": donated_leaves,
        "aliased-params": aliased,
        "collectives": hlo_collective_census(text),
        "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


# --------------------------------------------------------------------
# dispatch wrappers (the production integration points)
# --------------------------------------------------------------------

def _fresh_record(store_dir: str) -> Dict[str, Any]:
    return {"store": store_dir, "hit": False, "load-s": 0.0,
            "fingerprint": None, "lengths": {}}


def finalize_record(rec: Optional[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    if rec is not None:
        rec["load-s"] = round(rec["load-s"], 4)
    return rec


def _note_aot(hit: bool) -> None:
    from ..utils.compile_cache import note_aot
    note_aot(hit)


def wrap_pipelined(chunk_fn, *, model, sim, params, instance_ids, cap,
                   unroll: int, scan_k: int, store_dir: Optional[str]):
    """Wrap the jitted single-device ``chunk_fn(carry, t0, length)``
    with the store: per static chunk length, a hit deserializes the
    stored executable (no trace, no compile), a miss AOT-compiles
    through ``chunk_fn.lower`` and populates the entry. Any store
    failure falls back to the plain jit path for that length — the
    returned callable is drop-in and trajectories are bit-identical
    either way. Returns ``(wrapped, record)``; ``(None, None)`` when
    the store is disabled."""
    if store_dir is None:
        return None, None
    import jax
    import jax.numpy as jnp
    from .runtime import default_instance_ids
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)
    store = AotStore(store_dir)
    record = _fresh_record(store_dir)
    per_length: Dict[int, Any] = {}

    def _resolve(template, length: int):
        try:
            sds = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                template)
            sig = pipelined_signature(model, sim, params, instance_ids,
                                      cap, unroll, scan_k, length, sds)
            key = store_key(sig)
            if record["fingerprint"] is None:
                record["fingerprint"] = key
            t0 = time.monotonic()
            compiled = store.load(key)
            if compiled is not None:
                record["load-s"] += time.monotonic() - t0
                record["hit"] = True
                record["lengths"][str(length)] = "hit"
                _note_aot(True)
                return compiled
            _note_aot(False)
            record["lengths"][str(length)] = "miss"
            tsds = jax.ShapeDtypeStruct((), jnp.int32)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with _uncached_compile():
                    compiled = chunk_fn.lower(sds, tsds,
                                              length=length).compile()
                closed = jax.make_jaxpr(
                    lambda c, t: chunk_fn(c, t, length=length))(sds, tsds)
            meta = build_meta(sig, key,
                              entry_label(model, sim, "pipelined"),
                              jaxpr_digest(closed), compiled,
                              donated_leaves=len(jax.tree.leaves(sds)))
            if store.put(key, compiled, meta):
                record["lengths"][str(length)] = "populated"
            return compiled
        except Exception as e:
            record["lengths"][str(length)] = "error"
            record["error"] = repr(e)[:200]
            return lambda c, t: chunk_fn(c, t, length=length)

    def wrapped(carry, t0, length):
        fn = per_length.get(length)
        if fn is None:
            fn = per_length[length] = _resolve(carry, length)
        return fn(carry, t0)

    return wrapped, record


def wrap_sharded(chunk_fn, *, model, sim, mesh, params, scan_k: int,
                 store_dir: Optional[str]):
    """The sharded twin of :func:`wrap_pipelined`: wraps the jitted
    ``chunk_fn(wire, t0, params, length)`` from
    ``make_sharded_chunk_fn``. The mesh shape and device census join
    the key, and params stay a runtime argument (only their avals are
    fingerprinted). Returns ``(wrapped, record)`` or ``(None, None)``
    when disabled."""
    if store_dir is None:
        return None, None
    import jax
    import jax.numpy as jnp
    store = AotStore(store_dir)
    record = _fresh_record(store_dir)
    per_length: Dict[int, Any] = {}
    mesh_size = int(mesh.size)

    def _resolve(template, length: int):
        try:
            wsds = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                template)
            psds = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.asarray(x).dtype),
                params)
            sig = sharded_signature(model, sim, mesh, psds, scan_k,
                                    length, wsds)
            key = store_key(sig)
            if record["fingerprint"] is None:
                record["fingerprint"] = key
            t0 = time.monotonic()
            compiled = store.load(key)
            if compiled is not None:
                record["load-s"] += time.monotonic() - t0
                record["hit"] = True
                record["lengths"][str(length)] = "hit"
                _note_aot(True)
                return compiled
            _note_aot(False)
            record["lengths"][str(length)] = "miss"
            tsds = jax.ShapeDtypeStruct((), jnp.int32)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with _uncached_compile():
                    compiled = chunk_fn.lower(wsds, tsds, psds,
                                              length=length).compile()
                closed = jax.make_jaxpr(
                    lambda w, t, p: chunk_fn(w, t, p, length=length))(
                        wsds, tsds, psds)
            meta = build_meta(sig, key,
                              entry_label(model, sim, "sharded",
                                          mesh_size=mesh_size),
                              jaxpr_digest(closed), compiled,
                              donated_leaves=len(jax.tree.leaves(wsds)))
            if store.put(key, compiled, meta):
                record["lengths"][str(length)] = "populated"
            return compiled
        except Exception as e:
            record["lengths"][str(length)] = "error"
            record["error"] = repr(e)[:200]
            return lambda w, t, p: chunk_fn(w, t, p, length=length)

    def wrapped(wire, t0, params_arg, length):
        fn = per_length.get(length)
        if fn is None:
            fn = per_length[length] = _resolve(wire, length)
        return fn(wire, t0, params_arg)

    return wrapped, record


# --------------------------------------------------------------------
# provenance (heartbeat / campaign resume)
# --------------------------------------------------------------------

def pipelined_fingerprint(model, sim, params=None, chunk: int = 100,
                          event_cap: Optional[int] = None,
                          unroll: int = 1, scan_k: int = 8,
                          instance_ids=None) -> str:
    """The store key of a run's PRIMARY chunk executable, computed the
    way ``run_sim_pipelined`` would — but via ``eval_shape`` only, no
    trace, no compile. The heartbeat run-start record carries it;
    campaign resume and triage recompute it and refuse a drifted
    executable by name (EXE901)."""
    import jax
    from .pipeline import event_capacity, plan_chunks
    from .runtime import default_instance_ids, init_carry
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)
    full_plans = plan_chunks(sim.n_ticks, chunk)
    cap = (event_capacity(sim, model, full_plans[0][1])
           if not event_cap else int(event_cap))
    carry = jax.eval_shape(
        lambda: init_carry(model, sim, 0, params, instance_ids))
    sig = pipelined_signature(model, sim, params, instance_ids, cap,
                              unroll, scan_k, full_plans[0][1], carry)
    return store_key(sig)


def prewarm_pipelined(model, sim, store_dir: str, params=None,
                      chunk: int = 100, event_cap: Optional[int] = None,
                      unroll: int = 1, scan_k: Optional[int] = None,
                      instance_ids=None) -> Dict[str, str]:
    """AOT-compile and store every chunk executable a
    ``run_sim_pipelined(model, sim, chunk=chunk)`` run would dispatch,
    without running the simulation (shape templates only — no carry is
    ever materialized, so a 98k-instance rung prewarm's costs one
    compile, zero device memory). The seconds-to-first-tick prewarm:
    ``tools/tpu_opportunist.sh`` runs it for the scaling-ladder configs
    during healthy TPU windows, so the ladder's first real dispatch
    deserializes instead of compiling. Returns ``{length: "hit" |
    "populated" | "error: ..."}`` per distinct chunk length in the
    plan; an already-stored length is left untouched."""
    import jax
    import jax.numpy as jnp
    from .pipeline import (DEFAULT_SCAN_TOP_K, event_capacity,
                           make_chunk_fn, plan_chunks)
    from .runtime import default_instance_ids, init_carry
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)
    if scan_k is None:
        scan_k = DEFAULT_SCAN_TOP_K
    plans = plan_chunks(sim.n_ticks, chunk)
    cap = (event_capacity(sim, model, plans[0][1])
           if not event_cap else int(event_cap))
    chunk_fn = make_chunk_fn(model, sim, params, instance_ids, cap,
                             unroll, scan_k=scan_k)
    store = AotStore(store_dir)
    carry_sds = jax.eval_shape(
        lambda: init_carry(model, sim, 0, params, instance_ids))
    tsds = jax.ShapeDtypeStruct((), jnp.int32)
    out: Dict[str, str] = {}
    for length in sorted({ln for _, ln in plans}):
        try:
            sig = pipelined_signature(model, sim, params, instance_ids,
                                      cap, unroll, scan_k, length,
                                      carry_sds)
            cache_key = store_key(sig)
            if store.meta(cache_key) is not None:
                out[str(length)] = "hit"
                continue
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with _uncached_compile():
                    compiled = chunk_fn.lower(carry_sds, tsds,
                                              length=length).compile()
                closed = jax.make_jaxpr(
                    lambda c, t: chunk_fn(c, t, length=length))(
                        carry_sds, tsds)
            meta = build_meta(sig, cache_key,
                              entry_label(model, sim, "pipelined"),
                              jaxpr_digest(closed), compiled,
                              donated_leaves=len(
                                  jax.tree.leaves(carry_sds)))
            out[str(length)] = ("populated"
                                if store.put(cache_key, compiled, meta)
                                else "error: store write failed")
        except Exception as e:
            out[str(length)] = f"error: {repr(e)[:160]}"
    return out
