"""Host-side harness for the TPU runtime: configure, run, decode, check.

``run_tpu_test`` mirrors ``runner.run_test``'s contract for the device
runtime: build a :class:`SimConfig` from CLI-style opts, run the jitted
scan, decode the recorded instances' event streams into per-instance
op histories, run the workload checker on every recorded instance, and
aggregate — plus whole-fleet message statistics from the device counters.

The virtual clock maps wall-clock knobs onto ticks: by default 1 tick ==
1 simulated millisecond (so ``--latency 100`` is 100 ticks and a 5s RPC
timeout is 5000 ticks); the ``ms_per_tick`` option coarsens the clock as
a fidelity/throughput trade. Rates are converted from ops/sec to per-tick
client firing probabilities.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .decode import ETYPE_NAMES  # noqa: F401 — canonical copy lives
                                 # with the decoder; re-exported for
                                 # older importers of this module
from .netsim import LATENCY_DISTS, NetConfig
from .runtime import (ClientConfig, Model, NemesisConfig, SimConfig,
                      default_instance_ids, run_sim)
from ..telemetry.recorder import TelemetryConfig

MS_PER_TICK = 1  # default virtual clock resolution (override per run)


TPU_DEFAULTS = dict(
    node_count=1,
    concurrency=2,           # clients per instance
    rate=100.0,              # ops/sec per instance
    time_limit=2.0,          # simulated seconds
    latency=10.0,            # mean inter-node latency, ms (= ticks)
    latency_dist="exponential",
    p_loss=0.0,
    nemesis=[],
    nemesis_interval=0.5,    # simulated seconds between phase flips
    rpc_timeout=1.0,         # simulated seconds
    recovery_time=0.5,       # final heal + quiesce window (simulated s)
    n_instances=64,
    record_instances=8,
    pool_slots=128,
    inbox_k=8,
    ms_per_tick=MS_PER_TICK,  # virtual-clock resolution (fidelity knob)
    journal_instances=0,      # instances with full per-message journals
                              # (Lamport SVG + msgs-per-op; costs device
                              # output bandwidth, so opt-in)
    netid=None,               # wire format's trailing NETID journal-
                              # pairing lane: None (auto) carries it
                              # exactly when journal_instances > 0 —
                              # the narrow default format drops the
                              # lane the lane manifest proves dead.
                              # True forces the old 9-header-width row
                              # (the BENCH_WIDE / wide-vs-narrow A/B
                              # knob); trajectories are bit-identical
                              # either way (tests/test_analysis_lanes)
    layout="auto",            # carry batch-axis position: "auto" picks
                              # batch-minor on accelerators (TPU tiling
                              # pads the lead layout's tiny trailing dims
                              # ~8x) and batch-lead on CPU (~10% faster
                              # there); trajectories are bit-identical
                              # either way (runtime.SimConfig.layout)
    telemetry=True,           # device flight recorder (doc/
                              # observability.md); False removes the
                              # telemetry leaves from the carry entirely
    telemetry_stride=0,       # ticks per fleet-series window (0 = auto:
                              # <= 256 windows whatever the horizon)
    telemetry_hist_buckets=16,  # log2 ticks-to-ack histogram lanes
    profile_dir=None,         # jax.profiler trace capture directory
    device_profile="auto",    # per-chunk device-time attribution
                              # (telemetry/profiler.py): "auto" captures
                              # the first K chunks then every Nth, "on"
                              # every chunk, "off" none. Captured chunks
                              # gain the heartbeat device-ms lane and
                              # feed results.perf.phases.device; purely
                              # observational — trajectories are
                              # bit-identical at every setting
                              # (tests/test_profiler.py)
    pipeline="auto",          # chunked donated executor (tpu/pipeline.py):
                              # "auto" uses it whenever the horizon spans
                              # multiple chunks; "on"/"off" force it. The
                              # pipelined path is bit-identical to the
                              # monolithic scan (tests/test_pipeline.py)
    chunk_ticks=100,          # ticks per pipelined device dispatch
    event_capacity=0,         # compacted event rows per chunk (0 = auto
                              # from the client rate; overflow is flagged
                              # in perf.phases.pipeline, never silent)
    heartbeat=True,           # stream one heartbeat.jsonl record per
                              # chunk into the store dir (telemetry/
                              # stream.py; needs store_root — purely
                              # observational, bit-identical off/on)
    fail_fast=False,          # stop dispatching chunks once the
                              # device-side violation scan trips (at
                              # most one in-flight chunk runs past the
                              # detection; results gain "fail-fast")
    scan_top_k=8,             # violation-scan lanes per chunk: the
                              # heartbeat names the top-K earliest
                              # tripping instances, not just the argmin
                              # (tpu/pipeline.violation_scan)
    checkpoint_every=0,       # chunks between durable carry checkpoints
                              # (campaign/checkpoint.py; 0 = off). A
                              # checkpointed run killed at ANY point
                              # resumes bit-exactly via `maelstrom
                              # campaign resume <run-dir>`
    run_tag=None,             # store-dir suffix (<ts>-<tag>) so
                              # concurrent runs sharing a test name get
                              # collision-free dirs (campaign items
                              # pass item<k>)
    fault_plan=None,          # declarative fault-plan dict (maelstrom_
                              # tpu/faults/spec.py; CLI --fault-plan):
                              # crash-restart, link degradation, clock
                              # skew phases, compiled into the tick.
                              # Mutually exclusive with the generated
                              # fault --nemesis kinds
    fault_fuzz=None,          # fault DISTRIBUTION dict (maelstrom_tpu/
                              # faults/fuzz.py; CLI --fault-fuzz):
                              # per-instance RANDOMIZED crash/link/skew
                              # schedules drawn on device from the
                              # schedule-RNG lane — every instance runs
                              # a different scenario; `maelstrom
                              # shrink` minimizes the failing ones.
                              # Mutually exclusive with fault_plan and
                              # the fault --nemesis kinds
    fault_snapshot_every=None,  # ticks between snapshot-slab captures
                              # for crash recovery (None defers to the
                              # plan's own snapshot_every, default 1 =
                              # write-through durability; larger
                              # strides model async persistence, where
                              # losing the tail on a crash is a
                              # legitimate finding)
    compile_cache=".jax_cache",  # persistent XLA compile cache dir
                              # (resumed/queued runs skip recompiles;
                              # MAELSTROM_COMPILE_CACHE=0 disables,
                              # perf.phases gains hit/miss counts)
    aot_store="auto",         # certified AOT executable store (tpu/
                              # aot_store.py): "auto" rides the compile
                              # cache's sibling (.jax_cache.aot), a dir
                              # pins it, "off" (or MAELSTROM_AOT=0)
                              # disables. A warm store deserializes the
                              # chunk executable and skips trace+compile
                              # entirely (perf.phases.aot.hit); entries
                              # are certified by `maelstrom lint --aot`
                              # (EXE9xx, doc/lint.md)
    check_workers=None,       # host verdict pipeline (checkers/
                              # pool.py): checker-farm worker processes
                              # running the per-instance workload
                              # checkers in parallel, fed streaming
                              # per-chunk slabs. 0 = serial (the
                              # oracle path); None = auto (pool only
                              # when >= 16 recorded instances on a
                              # multi-core host). Verdicts and stored
                              # histories are byte-identical at every
                              # setting, incl. auto-fallback when the
                              # pool dies (tests/test_check_pool.py)
    check_mode="farm",        # verdict routing (checkers/
                              # device_summary.py): "farm" checks every
                              # recorded instance on host (the PR-13
                              # pipeline); "device" carries per-instance
                              # summary lanes in the tick and the farm
                              # confirms ONLY flagged instances (host
                              # cost scales with violations found, not
                              # instances simulated); "both" runs the
                              # full farm AND the lanes and cross-audits
                              # them (the A/B oracle — verdicts must be
                              # byte-identical)
    seed=0,
)


def resolve_layout(layout: str) -> str:
    """Resolve the ``layout`` opt to a concrete SimConfig layout."""
    layout = layout.strip()
    if layout == "auto":
        import jax
        return "minor" if jax.default_backend() != "cpu" else "lead"
    if layout not in ("lead", "minor"):
        raise ValueError(f"unknown carry layout {layout!r} "
                         "(expected auto/lead/minor)")
    return layout


def make_sim_config(model: Model, opts: Dict[str, Any]) -> SimConfig:
    o = {**TPU_DEFAULTS, **opts}
    mpt = o["ms_per_tick"]
    n_ticks = int(o["time_limit"] * 1000 / mpt)
    # netsim's delivery priority encodes the deadline as
    # ((1 << 20) - deliver_tick) * S: past 2^20 ticks priorities go
    # negative and messages silently stop being delivered. Refuse the
    # config instead (raise ms_per_tick to coarsen the clock).
    if n_ticks >= (1 << 20):
        raise ValueError(
            f"time_limit {o['time_limit']}s at {mpt} ms/tick needs "
            f"{n_ticks} ticks, past the 2^20-tick delivery horizon "
            f"(netsim age_rank encoding); raise --ms-per-tick")
    # cross-check against the PROVEN per-model overflow-free bound from
    # the range manifest (analysis/absint.py) instead of trusting the
    # one global constant: a model whose counters provably overflow
    # earlier is refused BY NAME at config time, not corrupted at tick
    # 2^k. Models without a proven entry fall back to the global cap.
    # The analysis's own audit configs opt out (range_horizon_check) —
    # re-proving a bound must never be blocked by the stale bound it
    # is about to replace.
    from ..analysis.absint import proven_horizon_log2
    cap_log2 = (proven_horizon_log2(getattr(model, "name", None))
                if o.get("range_horizon_check", True) else None)
    if cap_log2 is not None and n_ticks >= (1 << cap_log2):
        raise ValueError(
            f"time_limit {o['time_limit']}s at {mpt} ms/tick needs "
            f"{n_ticks} ticks, past model {model.name!r}'s PROVEN "
            f"overflow-free horizon 2^{cap_log2} "
            f"(analysis/range_manifest.json); shorten the run, raise "
            f"--ms-per-tick, or re-prove a wider bound with `maelstrom "
            f"lint --ranges --update-ranges`")
    journal_instances = min(o["journal_instances"], o["n_instances"])
    # per-model wire format: the NETID journal-pairing lane rides only
    # when this run records journals (or the caller forces the wide
    # format for an A/B); the journal decoder needs the lane, so
    # journaling with netid=False is refused rather than mis-decoded
    netid = o.get("netid")
    netid = journal_instances > 0 if netid is None else bool(netid)
    if journal_instances > 0 and not netid:
        raise ValueError(
            "journal_instances > 0 needs the wire format's NETID "
            "pairing lane; drop netid=False or disable journaling")
    net = NetConfig(
        n_nodes=o["node_count"],
        n_clients=o["concurrency"],
        pool_slots=o["pool_slots"],
        inbox_k=o["inbox_k"],
        body_lanes=model.body_lanes,
        latency_mean=float(o["latency"]) / mpt,
        latency_dist=LATENCY_DISTS[o["latency_dist"]],
        p_loss=float(o["p_loss"]),
        netid=netid,
    )
    # final window layout (the reference's heal -> quiesce -> final reads,
    # core.clj:74-80): partitions stop at stop_tick, clients keep running
    # the main mix through a quiesce gap of half the window, then switch to
    # final reads. Clamped so a short run can't degenerate into a
    # final-phase-only test with the nemesis silently disabled.
    recovery_ticks = min(int(o["recovery_time"] * 1000 / mpt),
                         n_ticks // 2)
    stop_tick = n_ticks - recovery_ticks
    client = ClientConfig(
        n_clients=o["concurrency"],
        rate=min(1.0, float(o["rate"]) / o["concurrency"] / 1000.0
                 * mpt),
        timeout_ticks=int(o["rpc_timeout"] * 1000 / mpt),
        final_start=stop_tick + recovery_ticks // 2,
    )
    nemesis = NemesisConfig(
        enabled="partition" in (o["nemesis"] or []),
        interval=max(1, int(o["nemesis_interval"] * 1000 / mpt)),
        kind=o.get("nemesis_kind", "random-halves"),
        stop_tick=stop_tick,
        schedule=tuple(sorted(
            ((int(until), tuple((int(d), int(s)) for d, s in pairs))
             for until, pairs in o.get("nemesis_schedule", ())),
            key=lambda p: p[0])),  # searchsorted needs monotonic untils
    )
    # the fault-plan engine (maelstrom_tpu/faults/): an explicit plan
    # dict, or the composable fault --nemesis kinds generated on the
    # partition nemesis's interval grid; both heal at stop_tick
    from ..faults import (FAULT_KINDS, compile_fault_fuzz,
                          compile_fault_plan, generate_fault_plan)
    fault_kinds = [k for k in (o["nemesis"] or []) if k in FAULT_KINDS]
    plan = o.get("fault_plan")
    fuzz_dist = o.get("fault_fuzz")
    if plan and fault_kinds:
        raise ValueError(
            f"--fault-plan and the generated fault nemesis kinds "
            f"({', '.join(fault_kinds)}) are mutually exclusive — put "
            f"the faults in the plan file")
    if fuzz_dist and (plan or fault_kinds):
        raise ValueError(
            "--fault-fuzz (per-instance randomized schedules) is "
            "mutually exclusive with --fault-plan and the generated "
            "fault nemesis kinds — one run speaks one schedule source")
    if not plan and fault_kinds:
        plan = generate_fault_plan(
            fault_kinds, o["node_count"], n_ticks,
            max(1, int(o["nemesis_interval"] * 1000 / mpt)), stop_tick)
    snap_every = o.get("fault_snapshot_every")
    snap_every = None if snap_every is None else int(snap_every)
    if fuzz_dist:
        faults = compile_fault_fuzz(fuzz_dist, o["node_count"],
                                    stop_tick,
                                    snapshot_every=snap_every)
    else:
        faults = compile_fault_plan(plan, o["node_count"], stop_tick,
                                    snapshot_every=snap_every)
    if fault_kinds and not faults.active:
        # the user explicitly asked for these fault kinds; silently
        # running fault-free (e.g. crash-restart/link-degrade on a
        # single-node cluster, which they cannot target) would report
        # a "valid" verdict that tested nothing
        raise ValueError(
            f"--nemesis {'/'.join(fault_kinds)} generated no fault "
            f"lanes for node_count={o['node_count']} (crash-restart "
            f"and link-degrade need >= 2 server nodes; use clock-skew "
            f"or an explicit --fault-plan for single-node workloads)")
    stride = int(o.get("telemetry_stride") or 0)
    if stride <= 0:
        # auto: bound the fleet series to <= 256 windows however long
        # the horizon is (memory = n_windows * SERIES_LANES int32s)
        stride = max(1, -(-n_ticks // 256))
    telemetry = TelemetryConfig(
        enabled=bool(o.get("telemetry", True)),
        # clamp to int32-safe bucket thresholds (recorder compares
        # against 2^k for k < hist_buckets; 2^31 would wrap negative)
        hist_buckets=min(max(int(o.get("telemetry_hist_buckets", 16)),
                             1), 31),
        stride=stride,
        n_windows=max(1, -(-n_ticks // stride)))
    check_mode = o.get("check_mode") or "farm"
    if check_mode not in ("farm", "device", "both"):
        raise ValueError(f"unknown check_mode {check_mode!r} "
                         "(expected farm/device/both)")
    return SimConfig(net=net, client=client, nemesis=nemesis,
                     faults=faults,
                     n_instances=o["n_instances"], n_ticks=n_ticks,
                     record_instances=min(o["record_instances"],
                                          o["n_instances"]),
                     journal_instances=journal_instances,
                     layout=resolve_layout(o["layout"]),
                     telemetry=telemetry,
                     check_summary=check_mode in ("device", "both"))


def events_to_histories(model: Model, events: np.ndarray,
                        final_start: int = 1 << 30,
                        ms_per_tick: float = MS_PER_TICK
                        ) -> List[List[dict]]:
    """Decode the [T, R, C, 2, 2 + model.ev_vals] device event tensor into one
    Jepsen-style history per recorded instance. Invocations at/after
    ``final_start`` are tagged ``final`` (post-heal final reads).

    Vectorized: one NumPy column pass over the nonzero events
    (``tpu/decode.py``), byte-identical to the original per-event loop
    (kept as ``decode.reference_histories``, the pinned oracle). The
    pipelined executor's compact buffers never even build this dense
    tensor — ``run_tpu_test`` streams them through
    :class:`..tpu.decode.StreamDecoder` directly."""
    from .decode import LazyHistories, decode_dense
    events = np.asarray(events)
    slabs = decode_dense(model, events)
    return LazyHistories(model, slabs, events.shape[1], final_start,
                         ms_per_tick).materialize()


def _phase_timed_run(model: Model, sim: SimConfig, seed: int, params,
                     profile_dir: Optional[str] = None):
    """Dispatch :func:`run_sim` with per-phase wall-clock timers
    (trace/lower, compile, execute) via the jit AOT API, optionally
    under a ``jax.profiler`` trace capture. Falls back to one opaque
    ``total-s`` timing on jax versions without a working AOT path — the
    run itself never depends on the instrumentation."""
    import jax
    import jax.numpy as jnp

    phases: Dict[str, float] = {}
    profiling = False
    if profile_dir:
        try:
            jax.profiler.start_trace(profile_dir)
            profiling = True
        except Exception as e:  # profiler backend unavailable
            phases["profile-error"] = repr(e)[:160]
    seed_arr = jnp.int32(seed)
    t0 = time.monotonic()
    try:
        dispatch = None
        try:
            lowered = run_sim.lower(model, sim, seed_arr, params)
            phases["trace-s"] = round(time.monotonic() - t0, 4)
            t1 = time.monotonic()
            compiled = lowered.compile()
            phases["compile-s"] = round(time.monotonic() - t1, 4)
            dispatch = lambda: compiled(seed_arr, params)
        except Exception as e:
            # AOT setup only — an execution failure below must raise,
            # not silently re-dispatch the whole simulation
            phases = {k: v for k, v in phases.items()
                      if k == "profile-error"}
            phases["aot-error"] = repr(e)[:160]
        t2 = time.monotonic()
        if dispatch is None:
            out = jax.block_until_ready(
                run_sim(model, sim, seed_arr, params))
            phases["total-s"] = round(time.monotonic() - t0, 4)
        else:
            out = jax.block_until_ready(dispatch())
            phases["execute-s"] = round(time.monotonic() - t2, 4)
    finally:
        if profiling:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
    return out, phases


def resolve_pipeline(sim: SimConfig, opts: Dict[str, Any]) -> bool:
    """Decide whether a run takes the chunked pipelined executor
    (tpu/pipeline.py) or the monolithic single-dispatch scan. ``auto``
    pipelines any horizon whose chunk plan spans multiple dispatches —
    single-chunk runs keep the single compile."""
    mode = opts.get("pipeline", "auto")
    if mode in (True, "on"):
        return True
    if mode in (False, "off", None):
        return False
    from .pipeline import plan_chunks
    return len(plan_chunks(sim.n_ticks,
                           int(opts.get("chunk_ticks") or 100))) > 1


def _pipelined_phase_run(model: Model, sim: SimConfig, seed: int, params,
                         opts: Dict[str, Any],
                         profile_dir: Optional[str] = None,
                         heartbeat=None, checkpoint_cb=None,
                         resume=None, event_sink=None):
    """The chunked executor under the same phase-timer/profiler contract
    as :func:`_phase_timed_run`: returns (PipelineResult, phases) with
    the per-chunk dispatch/fetch/decode overlap stats under
    ``phases["pipeline"]``. ``heartbeat``/``opts["fail_fast"]``/
    ``checkpoint_cb``/``resume`` thread through to
    :func:`..tpu.pipeline.run_sim_pipelined`."""
    import jax

    from .pipeline import run_sim_pipelined

    phases: Dict[str, Any] = {}
    profiling = False
    if profile_dir:
        try:
            jax.profiler.start_trace(profile_dir)
            profiling = True
        except Exception as e:
            phases["profile-error"] = repr(e)[:160]
    # per-chunk device-time attribution (telemetry/profiler.py):
    # observational, bit-identical on/off; "off" skips construction
    # entirely so the cost-model weight trace is never paid
    prof = None
    mode = str(opts.get("device_profile") or "auto")
    if mode != "off":
        from ..telemetry.profiler import DeviceProfiler
        prof = DeviceProfiler(mode, model=model, sim=sim, params=params)
    t0 = time.monotonic()
    try:
        res = run_sim_pipelined(
            model, sim, seed, params,
            chunk=int(opts.get("chunk_ticks") or 100),
            event_cap=int(opts.get("event_capacity") or 0) or None,
            heartbeat=heartbeat,
            fail_fast=bool(opts.get("fail_fast")),
            scan_k=int(opts.get("scan_top_k") or 1),
            checkpoint_cb=checkpoint_cb,
            checkpoint_every=int(opts.get("checkpoint_every") or 0),
            resume=resume,
            # the streaming verdict pipeline consumes the compact
            # chunks directly — never reconstruct the dense tensor
            event_sink=event_sink,
            dense_events=event_sink is None,
            check_mode=opts.get("check_mode"),
            profiler=prof,
            aot_store=_resolve_aot_dir(opts))
    finally:
        if profiling:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
    phases["total-s"] = round(time.monotonic() - t0, 4)
    phases["pipeline"] = res.perf
    if "aot" in res.perf:
        # the certified-store outcome surfaces as its own phase
        # ({hit, load-s, fingerprint}, doc/observability.md)
        phases["aot"] = res.perf["aot"]
    if prof is not None and prof.records:
        # device ms/tick per named scope, next to the host timers
        phases["device"] = prof.summary()
    return res, phases


def _resolve_aot_dir(opts: Dict[str, Any]) -> Optional[str]:
    """The run's effective AOT store dir (None = disabled); defaults
    ride the compile cache's ``.aot`` sibling."""
    from .aot_store import resolve_store_dir
    return resolve_store_dir(opts.get("aot_store", "auto"),
                             opts.get("compile_cache"))


def aot_fingerprint_for(model: Model, opts: Dict[str, Any],
                        params=None) -> Optional[str]:
    """Recompute the store key of a run's primary chunk executable from
    (model, opts) alone — eval_shape only, no trace or compile. The
    heartbeat run-start record carries it; `maelstrom triage` and
    ``campaign.runner.resume_run`` recompute it and refuse a drifted
    executable BY NAME (EXE901) instead of silently replaying against
    different code. Returns None when the store is disabled or the
    fingerprint cannot be derived."""
    full = {**TPU_DEFAULTS, **(opts or {})}
    if _resolve_aot_dir(full) is None:
        return None
    try:
        from .aot_store import pipelined_fingerprint
        sim = make_sim_config(model, full)
        if params is None:
            params = model.make_params(sim.net.n_nodes)
        return pipelined_fingerprint(
            model, sim, params,
            chunk=int(full.get("chunk_ticks") or 100),
            event_cap=int(full.get("event_capacity") or 0) or None,
            scan_k=int(full.get("scan_top_k") or 1))
    except Exception:
        return None


# opts that fully determine a run's trajectory (plus the model identity)
# — the heartbeat's run-start record carries them so `maelstrom triage`
# can rebuild the exact SimConfig and replay flagged instances
# bit-exactly on a run dir that never produced a results.json.
_REPRO_OPT_KEYS = (
    "node_count", "concurrency", "rate", "time_limit", "latency",
    "latency_dist", "p_loss", "nemesis", "nemesis_interval",
    "nemesis_kind", "nemesis_schedule", "rpc_timeout", "recovery_time",
    "n_instances", "record_instances", "journal_instances", "netid",
    "pool_slots",
    "inbox_k", "ms_per_tick", "layout", "telemetry", "telemetry_stride",
    "telemetry_hist_buckets", "chunk_ticks", "event_capacity", "seed",
    "topology", "availability", "consistency_models", "key_count",
    # behavioral knobs `campaign resume` replays from the header so a
    # resumed run re-runs under the SAME policy it started with
    "pipeline", "fail_fast", "scan_top_k", "funnel", "funnel_max",
    "checkpoint_every", "check_workers", "check_mode",
    # the certified executable store (tpu/aot_store.py): a resumed run
    # must consult the SAME store — and the recorded fingerprint gates
    # the resume on source drift (EXE901)
    "aot_store",
    # fault-plan engine (maelstrom_tpu/faults/): the plan — or the
    # fuzz distribution whose per-instance schedules derive from the
    # seed — is part of the trajectory, so triage/resume/shrink must
    # rebuild it
    "fault_plan", "fault_fuzz", "fault_snapshot_every",
    # model-selection flags (native-engine vocabulary parity): the
    # replay must rebuild the same mutant/crash-mode automaton
    "crash_clients", "txn_dirty_apply")


def heartbeat_meta(model: Model, sim: SimConfig,
                   opts: Dict[str, Any]) -> Dict[str, Any]:
    """The run-start record's payload: enough to label a live report
    (`maelstrom watch`) and to replay the run (`maelstrom triage`)."""
    import json
    repro = {}
    for k in _REPRO_OPT_KEYS:
        if k in opts:
            try:
                json.dumps(opts[k])
            except (TypeError, ValueError):
                continue
            repro[k] = opts[k]
    meta = {
        "workload": model.name,
        "instances": sim.n_instances,
        "ticks": sim.n_ticks,
        "record-instances": sim.record_instances,
        "journal-instances": sim.journal_instances,
        # the RESOLVED wire format (header + body widths + netid lane):
        # triage / campaign resume rebuild narrowed runs bit-exactly
        # from it instead of inheriting whatever the default layout is
        "wire-format": sim.net.wire_format,
        "chunk-ticks": int(opts.get("chunk_ticks") or 100),
        "layout": sim.layout,
        "seed": int(opts.get("seed") or 0),
        "opts": repro,
        # scalar model knobs (log_cap, heartbeat, n_keys, topology, ...)
        # — get_model's defaults may differ from how THIS model was
        # built, and the replay must rebuild the identical automaton
        "model-config": {k: v for k, v in vars(model).items()
                         if isinstance(v, (bool, int, float, str))},
    }
    if sim.faults.active:
        # label the live report (`maelstrom watch`); the repro opts
        # above carry the full plan (or the deterministic generator
        # inputs / the fuzz distribution) for the bit-exact replay
        from ..faults.engine import plan_summary
        meta["faults"] = plan_summary(sim.faults)
    if sim.faults.has_fuzz:
        # schedule-space coverage counters: one host-side re-draw of
        # the fleet's windows (pure function of the seed) labels how
        # much of the fault space this sweep visits
        from ..faults import fuzz as faults_fuzz
        meta["fault-fuzz"] = faults_fuzz.fleet_coverage(
            faults_fuzz.fleet_windows(
                sim.faults, sim.net.n_nodes,
                int(opts.get("seed") or 0),
                np.arange(sim.n_instances, dtype=np.int32)))
    return meta


def run_tpu_test(model: Model, opts: Optional[Dict[str, Any]] = None,
                 params=None,
                 resume_from: Optional[str] = None) -> Dict[str, Any]:
    """Configure, run, decode, check — one device test.

    ``resume_from`` continues a checkpointed run IN PLACE: pass the
    killed run's store dir (with ``opts`` rebuilt from its heartbeat
    header — ``campaign.runner.resume_run`` does both), and the run
    restores the carry + host accumulators from
    ``<run_dir>/checkpoint/``, appends to the heartbeat, and overwrites
    the run dir's artifacts with results bit-identical to an
    uninterrupted run."""
    from ..utils.compile_cache import (CacheStats, enable_compile_cache,
                                       phase_record)
    opts = {**TPU_DEFAULTS, **(opts or {})}
    cache_dir = enable_compile_cache(opts.get("compile_cache"))
    cache_stats = CacheStats() if cache_dir else None
    sim = make_sim_config(model, opts)
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    # the store dir exists from the first tick on: the streaming
    # heartbeat (telemetry/stream.py) writes into it DURING the run, so
    # `maelstrom watch` / `triage` work on runs that die mid-horizon
    run_dir = None
    hb = None
    resume = None
    if resume_from is not None:
        from ..campaign.checkpoint import (CheckpointError,
                                           load_checkpoint,
                                           restore_carry)
        from .pipeline import ResumeState, _init_pipelined
        import jax
        import jax.numpy as jnp
        run_dir = os.path.realpath(resume_from)
        ck = load_checkpoint(run_dir)
        if ck is None:
            raise CheckpointError(
                f"{run_dir} has no checkpoint to resume from "
                f"(checkpointing is opt-in: --checkpoint-every K)")
        if ck["kind"] != "pipelined":
            raise CheckpointError(
                f"{run_dir} holds a {ck['kind']!r} checkpoint; "
                f"run_tpu_test resumes single-device runs only")
        # abstract template: restore_carry only needs treedef +
        # shapes/dtypes — eval_shape avoids materializing (and then
        # discarding) a full init carry on device at resume time
        template = jax.eval_shape(
            lambda: _init_pipelined(
                model, sim, jnp.int32(opts["seed"]), params,
                jnp.asarray(default_instance_ids(sim))))
        resume = ResumeState(
            carry=restore_carry(template, ck["carry"]),
            ticks=int(ck["ticks"]), chunks=int(ck["chunks"]),
            compact=tuple(ck["compact"]),
            journal=tuple(ck["journal"]))
        opts = {**opts, "pipeline": "on"}   # checkpoints are chunked
    elif opts.get("store_dir"):
        # caller pre-created (and recorded) the run dir — the campaign
        # runner does, so a killed worker's item still knows where its
        # checkpoint lives and the next claimer can resume it
        run_dir = opts["store_dir"]
        os.makedirs(run_dir, exist_ok=True)
    elif opts.get("store_root"):
        run_dir = prepare_store_dir(model.name, opts["store_root"],
                                    tag=opts.get("run_tag"))
    use_pipe = resolve_pipeline(sim, opts)
    if opts.get("fail_fast") and not use_pipe:
        # fail-fast needs per-chunk dispatch to have anything to stop;
        # a monolithic run would silently burn the whole horizon while
        # the user believes the protection is active
        import sys
        print("note: --fail-fast has no effect on the monolithic "
              "executor (single-dispatch horizon); use --pipeline on "
              "or a multi-chunk --time-limit/--chunk-ticks",
              file=sys.stderr)
    if run_dir and opts.get("heartbeat", True):
        from ..telemetry.stream import HeartbeatWriter
        if resume is not None:
            hb = HeartbeatWriter(
                run_dir, resume_from=resume.ticks,
                meta={"workload": model.name,
                      "chunks-done": resume.chunks})
        else:
            # the executable fingerprint (tpu/aot_store.py) rides the
            # run-start record: triage / campaign resume recompute it
            # and refuse a drifted executable by name (EXE901)
            aot_fp = (aot_fingerprint_for(model, opts, params)
                      if use_pipe else None)
            hb = HeartbeatWriter(
                run_dir, meta=dict(heartbeat_meta(model, sim, opts),
                                   pipeline=bool(use_pipe),
                                   **({"aot-fingerprint": aot_fp}
                                      if aot_fp else {})))
    checkpoint_cb = None
    if int(opts.get("checkpoint_every") or 0) > 0:
        if run_dir and use_pipe:
            from ..campaign.checkpoint import make_checkpoint_cb
            checkpoint_cb = make_checkpoint_cb(
                run_dir, kind="pipelined",
                meta={"workload": model.name,
                      "seed": int(opts["seed"]),
                      "layout": sim.layout,
                      "chunk-ticks": int(opts.get("chunk_ticks")
                                         or 100)})
        else:
            # durability the user asked for would silently not exist —
            # say so (the --fail-fast note above sets the precedent)
            import sys
            why = ("no store dir to hold checkpoint/" if not run_dir
                   else "the monolithic executor has no chunk "
                        "boundaries to checkpoint at")
            print(f"note: --checkpoint-every has no effect here "
                  f"({why}); the run will NOT be resumable",
                  file=sys.stderr)
    # --- the host verdict pipeline (checkers/pool.py): a persistent
    # checker farm spawned BEFORE dispatch (worker startup overlaps the
    # device compile), fed per-chunk event slabs from the pipelined
    # executor's consume side so decode + dict materialization + the
    # per-workload checkers run WHILE later chunks compute on device.
    # check_workers=0 is the serial oracle; any pool failure falls back
    # to it with identical verdicts.
    from ..checkers.pool import VerdictPipeline, resolve_check_workers
    check_workers = resolve_check_workers(opts.get("check_workers"),
                                          sim.record_instances)
    verdict = VerdictPipeline(model, sim.client.n_clients,
                              sim.record_instances,
                              sim.client.final_start,
                              opts["ms_per_tick"], opts, check_workers)
    if resume is not None:
        # the resumed segments' chunks are host-side already — replay
        # them through the stream decoder ahead of the live suffix so
        # histories cover the full horizon in chunk order
        for _rows, _n in resume.compact:
            verdict.feed_chunk(np.asarray(_rows), int(_n), 0, 0)
    t0 = time.monotonic()
    pipe_res = None
    dense_np = None
    try:
        if use_pipe:
            pipe_res, phases = _pipelined_phase_run(
                model, sim, opts["seed"], params, opts,
                opts.get("profile_dir"), heartbeat=hb,
                checkpoint_cb=checkpoint_cb, resume=resume,
                event_sink=verdict.feed_chunk)
            carry = pipe_res.carry
            journal_sends = pipe_res.journal_sends
            journal_recvs = pipe_res.journal_recvs
            # the pipelined executor accounted its own (overlapped)
            # event fetch under phases["pipeline"]; fetch-s below covers
            # only the telemetry pull + fleet reduction
            t_fetch = time.monotonic()
        else:
            (carry, ys), phases = _phase_timed_run(
                model, sim, opts["seed"], params,
                opts.get("profile_dir"))
            # fetch-s includes the dense event tensor's device-to-host
            # transfer on the monolithic path (doc/observability.md)
            t_fetch = time.monotonic()
            dense_np = (np.asarray(ys.events) if ys.events is not None
                        else np.zeros((sim.n_ticks, 0,
                                       sim.client.n_clients,
                                       2, 2 + model.ev_vals), np.int32))
            journal_sends = (np.asarray(ys.journal_sends)
                             if ys.journal_sends is not None else None)
            journal_recvs = (np.asarray(ys.journal_recvs)
                             if ys.journal_recvs is not None else None)
    except BaseException:
        verdict.close()
        if hb is not None:
            # no run-end record: the heartbeat prefix IS the crash
            # artifact (`maelstrom watch` reports the run as dead)
            hb.close()
        raise
    fleet = None
    if carry.telemetry is not None:
        import jax
        from ..telemetry.fleet import fleet_summary
        tel_host = jax.tree.map(np.asarray, carry.telemetry)
        fleet = fleet_summary(tel_host, sim, opts["ms_per_tick"])
    phases["fetch-s"] = round(time.monotonic() - t_fetch, 4)
    wall = time.monotonic() - t0

    if dense_np is not None:
        # monolithic path: the dense tensor decodes AFTER the fetch-s
        # stamp, so fetch-s keeps meaning device-to-host transfer and
        # the column decode is accounted once, under check.decode-s
        verdict.feed_dense(dense_np)
    # decode finalize + per-instance verdicts: pooled (instance-ordered
    # assembly) or serial — byte-identical either way; histories stay
    # lazy column slabs until something (store writer, availability,
    # journal stats) actually reads the dict records.
    # --check-mode device: the device summary lanes + invariants decide
    # WHICH recorded instances the farm confirms; everything unflagged
    # was proven clean on device and never costs host checker work
    check_mode = opts.get("check_mode") or "farm"
    violations = np.asarray(carry.violations)
    summ_np = (np.asarray(carry.check_summary)
               if carry.check_summary is not None else None)
    flagged_all = violations > 0
    if summ_np is not None:
        from ..checkers import device_summary
        flagged_all = flagged_all | (
            summ_np[:, device_summary.L_FLAGS] != 0)
    flagged_ids = np.nonzero(flagged_all)[0]
    if check_mode == "device":
        per_instance, histories, check_rec = verdict.finish(
            flagged=[int(i) for i in flagged_ids
                     if i < sim.record_instances])
    else:
        per_instance, histories, check_rec = verdict.finish()
    if summ_np is not None:
        check_rec["check-mode"] = check_mode
        farm_n = check_rec.get("farm-instances", len(per_instance))
        check_rec["farm-load-fraction"] = round(
            farm_n / max(1, sim.record_instances), 6)
    phases["check"] = check_rec
    availability = None
    if opts.get("availability") is not None:
        from ..checkers.availability import availability_checker
        availability = availability_checker(
            [r for h in histories for r in h], opts["availability"])
    from ..checkers import compose_valid
    n_valid = sum(1 for r in per_instance
                  if r.get("valid?") in (True, "unknown"))
    stats = carry.stats
    total_msgs = int(stats.delivered)
    n_violating = int((violations > 0).sum())
    # three-valued verdict (reference doc/results.md:58-64); an on-device
    # invariant violation on any instance is a definite failure
    overall = compose_valid(r.get("valid?", True) for r in per_instance)
    if n_violating > 0:
        overall = False
    # a checker that RAISED is a definite invalid-with-reason: the
    # structured blow-up dict (instance id + checker name + truncated
    # traceback, checkers.checker_failure) already carries valid?=False
    # through compose_valid; the count makes it visible at the top
    checker_errors = sum(1 for r in per_instance if "traceback" in r)
    violating_ids = np.nonzero(violations)[0]
    results = {
        "valid?": overall,
        "invariants": {
            "violating-instances": n_violating,
            "violating-instance-ids": violating_ids[:1024].tolist(),
            "total-violation-ticks": int(violations.sum()),
        },
        "instance-count": sim.n_instances,
        "checked-instances": len(per_instance),
        "valid-instances": n_valid,
        **({"checker-errors": checker_errors} if checker_errors else {}),
        # every recorded instance's verdict, tagged with its index — an
        # invalid instance at ANY index keeps its full detail in the
        # artifact; valid verdicts beyond the first 32 collapse to a
        # one-key summary so bench-scale runs don't bloat results.json
        "instances": [
            dict(r, instance=i)
            if r.get("valid?") is not True or i < 32
            else {"instance": i, "valid?": True}
            for i, r in enumerate(per_instance)],
        "net": {
            "sent": int(stats.sent),
            "delivered": int(stats.delivered),
            "dropped-partition": int(stats.dropped_partition),
            "dropped-loss": int(stats.dropped_loss),
            "dropped-overflow": int(stats.dropped_overflow),
        },
    }
    if summ_np is not None:
        from ..checkers import device_summary
        results["check"] = {
            "mode": check_mode,
            # fleet-wide (not just recorded): triage replays these
            "flagged-instances": int(flagged_all.sum()),
            "flagged-instance-ids": flagged_ids[:1024].tolist(),
            "farm-instances": check_rec.get("farm-instances",
                                            len(per_instance)),
            "farm-load-fraction": check_rec.get("farm-load-fraction",
                                                1.0),
            "summary-bytes-per-tick":
                device_summary.summary_bytes_per_tick(sim.n_instances),
        }
        if check_mode == "both":
            # the A/B oracle: the farm checked EVERYTHING, so any
            # farm-invalid recorded instance the lanes did NOT flag is
            # a screening gap — device mode would have synthesized a
            # clean verdict for it
            missed = [i for i, r in enumerate(per_instance)
                      if r.get("valid?") is False
                      and not bool(flagged_all[i])]
            results["check"]["device-vs-farm"] = {
                "complete": not missed, "missed-instance-ids": missed}
            if missed:
                results["valid?"] = False
    pipe_stats = phases.get("pipeline")
    # on a fail-fast stop only the dispatched prefix ran — perf must
    # report the ticks that actually executed, not the planned horizon
    # (a 2x-inflated instance-ticks-per-sec otherwise)
    ticks_run = (pipe_stats["ticks-dispatched"]
                 if pipe_stats and pipe_stats.get("stopped-early")
                 else sim.n_ticks)
    cache_rec = phase_record(opts.get("compile_cache"), cache_stats)
    if cache_rec is not None:
        phases["compile-cache"] = cache_rec
    results["perf"] = {
        "wall-s": wall,
        "ticks": ticks_run,
        "msgs-per-sec": total_msgs / wall if wall > 0 else 0.0,
        "instance-ticks-per-sec": (sim.n_instances * ticks_run / wall
                                   if wall > 0 else 0.0),
        "phases": phases,
    }
    if pipe_stats and pipe_stats.get("overflowed-chunks"):
        # a compacted event buffer overflowed: decoded histories are
        # missing events, so a "valid" verdict must not read as full
        # coverage (raise event_capacity / lower chunk_ticks to fix)
        results["events-truncated"] = True
    if pipe_stats and pipe_stats.get("stopped-early"):
        # --fail-fast tripped: the run covers only the dispatched
        # prefix; the device-side scan says where it went wrong
        from ..telemetry.stream import (scan_to_violation,
                                        scan_to_violations)
        have_scan = pipe_res is not None and pipe_res.scan is not None
        results["fail-fast"] = {
            "stopped": True,
            "ticks-dispatched": pipe_stats["ticks-dispatched"],
            "ticks-planned": sim.n_ticks,
            "first-violation": (scan_to_violation(pipe_res.scan)
                                if have_scan else None),
            # all top-K lanes the device scan named (--scan-top-k)
            "violations": (scan_to_violations(pipe_res.scan)
                           if have_scan else []),
        }
    if fleet is not None:
        # the condensed fleet view rides in results.json; the full dict
        # (series, histograms, per-instance spreads) is the store's
        # fleet-metrics.json, rendered by `maelstrom fleet-stats`
        results["telemetry"] = {k: v for k, v in fleet.items()
                                if k not in ("series", "latency-hist",
                                             "per-instance")}
    if availability is not None:
        results["availability"] = availability
        if availability["valid?"] is False:
            results["valid?"] = False
    # --- the invariant-trip funnel (SURVEY §7: full checkers on samples
    # + any instance whose invariants trip). Instances are RNG-stable by
    # id, so the violating ones — wherever they sit in a 100k-instance
    # sweep — are re-simulated bit-exactly with recording enabled and
    # put through the full workload checker, yielding a checkable
    # history + explainable verdict per tripped instance.
    funnel = None
    if opts.get("funnel", True) and len(violating_ids) > 0:
        funnel_max = int(opts.get("funnel_max", 32))
        target_ids = [int(i) for i in violating_ids[:funnel_max]]
        funnel = replay_instances(model, opts, target_ids, params=params)
        funnel["total-violating"] = n_violating
        results["funnel"] = {k: v for k, v in funnel.items()
                             if k != "histories"}
    journal = None
    if sim.journal_instances > 0:
        from ..checkers.net_stats import net_stats_checker
        from .journal import TpuJournal
        journal = TpuJournal(model, sim.net, journal_sends,
                             journal_recvs, instance=0,
                             ms_per_tick=opts["ms_per_tick"])
        # instance 0's own drop counters ride along when the flight
        # recorder ran, so the journal block and fleet-metrics.json
        # agree (checkers/net_stats.py vocabulary)
        drops = None
        if carry.telemetry is not None:
            tel = carry.telemetry
            drops = {
                "dropped-partition": int(tel.dropped_partition[0]),
                "dropped-loss": int(tel.dropped_loss[0]),
                "dropped-overflow": int(tel.dropped_overflow[0]),
            }
        ns = net_stats_checker(journal, histories[0] if histories else [],
                               drops=drops)
        results["net"]["journal"] = {
            "stats": ns["stats"],
            "msgs-per-op": ns["msgs-per-op"],
            **({"drops": ns["drops"]} if drops is not None else {}),
            "instance": 0,
        }
    if run_dir is not None:
        _write_store(model.name, opts.get("store_root") or "", results,
                     histories, journal, funnel=funnel, fleet=fleet,
                     store_dir=run_dir)
    if hb is not None:
        hb.finish(
            status=("stopped" if results.get("fail-fast") else
                    "complete"),
            **{"valid?": results["valid?"],
               "violating-instances": n_violating,
               # the verdict-stage summary (perf.phases.check) rides
               # the run-end record so `maelstrom watch` prices the
               # host side of a finished run too
               "check": check_rec,
               **({"store-dir": run_dir} if run_dir else {})})
    return results


def replay_instances(model: Model, opts: Dict[str, Any],
                     instance_ids: List[int],
                     params=None) -> Dict[str, Any]:
    """Re-simulate exactly ``instance_ids`` (same seed/config) with full
    history recording, run the workload checker on each, and return
    ``{ids, verdicts, histories, replayed-violating}``. Bit-exactness
    rests on the instance-stable RNG (runtime._instance_keys): each
    re-simulated instance replays the identical trajectory it had in the
    original batch, so its history IS the history of the violation."""
    import jax.numpy as jnp

    opts = {**TPU_DEFAULTS, **opts}
    K = len(instance_ids)
    sub_opts = {**opts, "n_instances": K, "record_instances": K,
                "journal_instances": 0}
    sim = make_sim_config(model, sub_opts)
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    carry, ys = run_sim(model, sim, opts["seed"], params,
                        jnp.asarray(instance_ids, dtype=jnp.int32))
    from .decode import LazyHistories, decode_dense
    histories = LazyHistories(model, decode_dense(model,
                                                  np.asarray(ys.events)),
                              K, sim.client.final_start,
                              opts["ms_per_tick"])
    # the shared verdict helper (checkers/pool.py): lazy slabs hand
    # through so a big funnel batch can take the checker farm too —
    # small ones resolve to the serial path; either way the blow-up
    # reporting contract (checker_failure dicts) is the one the main
    # verdict stage speaks
    from ..checkers.pool import check_instances, resolve_check_workers
    verdicts = check_instances(
        model, histories, opts,
        workers=resolve_check_workers(opts.get("check_workers"), K),
        final_start=sim.client.final_start,
        ms_per_tick=opts["ms_per_tick"])
    for iid, h, v in zip(instance_ids, histories, verdicts):
        v["instance"] = int(iid)
        v["ops"] = sum(1 for r in h if r["type"] == "invoke")
    replay_viol = np.asarray(carry.violations)
    return {
        "ids": [int(i) for i in instance_ids],
        # self-check: the replay must trip the same instances' invariants
        # — a mismatch would mean the replay was NOT bit-exact
        "replayed-violating": int((replay_viol > 0).sum()),
        "verdicts": verdicts,
        "histories": {int(i): h
                      for i, h in zip(instance_ids, histories)},
    }


def prepare_store_dir(name: str, store_root: str,
                      suffix: str = "-tpu",
                      tag: Optional[str] = None) -> str:
    """Create a run's store directory (and point the ``latest`` symlink
    at it) BEFORE the run starts, so live artifacts — the streaming
    heartbeat.jsonl — have somewhere to go while the fleet is still on
    device. ``_write_store`` fills the same directory at the end.

    Concurrency-safe: two runs sharing a test name get DISTINCT dirs
    (``exist_ok=False`` + a collision counter — campaign items also
    pass ``tag`` for human-readable ``<ts>-item<k>`` names) and the
    ``latest`` symlink is repointed atomically (symlink-temp-then-
    rename), so a reader never sees it missing or dangling mid-swap."""
    from datetime import datetime
    ts = datetime.now().strftime("%Y%m%d-%H%M%S-%f")
    base = f"{ts}-{tag}" if tag else ts
    parent = os.path.join(store_root, f"{name}{suffix}")
    d = os.path.join(parent, base)
    for attempt in range(2, 100):
        try:
            os.makedirs(d, exist_ok=False)
            break
        except FileExistsError:
            d = os.path.join(parent, f"{base}-{attempt}")
    latest = os.path.join(parent, "latest")
    try:
        tmp = os.path.join(parent,
                           f".latest-tmp-{os.getpid()}-{id(d)}")
        os.symlink(os.path.basename(d), tmp)
        os.replace(tmp, latest)   # atomic repoint — never unlink-first
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return d


def _write_store(name: str, store_root: str, results: Dict[str, Any],
                 histories, journal=None, funnel=None,
                 suffix: str = "-tpu", fleet=None,
                 store_dir: Optional[str] = None) -> None:
    """Store artifacts for a TPU (or native-engine) run: results.json +
    one history per recorded instance (the store layout of
    doc/results.md, minus node logs — there are no node processes),
    plus the Lamport diagram when a per-message journal was recorded and
    the fleet-metrics.json + dashboard SVGs when telemetry ran.
    ``store_dir`` reuses a directory :func:`prepare_store_dir` already
    created (heartbeat runs stream into it mid-run)."""
    import json
    d = store_dir or prepare_store_dir(name, store_root, suffix)
    if fleet is not None:
        from ..telemetry.fleet import (write_fleet_metrics,
                                       write_fleet_svgs)
        write_fleet_metrics(fleet, d)
        write_fleet_svgs(fleet, d)
    if journal is not None:
        from ..net.viz import plot_lamport
        plot_lamport(journal, os.path.join(d, "messages.svg"))
    if histories:
        # latency/rate plots + timeline from the first recorded
        # instance's history (store artifact parity with the process
        # runner, doc/results.md)
        from ..checkers.perf import plot_perf
        from ..checkers.timeline import render_timeline
        plot_perf(histories[0], d)
        render_timeline(histories[0], os.path.join(d, "timeline.html"))
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump(results, f, indent=2, default=repr)
    from ..gen.history import write_txt
    for i, h in enumerate(histories):
        with open(os.path.join(d, f"history-{i}.jsonl"), "w") as f:
            for r in h:
                f.write(json.dumps(r) + "\n")
        write_txt(h, os.path.join(d, f"history-{i}.txt"))
    # funnel: one checkable history per invariant-tripping instance,
    # named by its ORIGINAL instance id in the big batch
    if funnel:
        for iid, h in funnel["histories"].items():
            p = os.path.join(d, f"funnel-history-{iid}.jsonl")
            with open(p, "w") as f:
                for r in h:
                    f.write(json.dumps(r) + "\n")
    results["store-dir"] = d
