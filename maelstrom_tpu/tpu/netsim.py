"""Device-resident simulated network: the TPU-native equivalent of
``net.clj``'s priority queues.

Per protocol instance, in-flight messages live in a fixed pool of ``S``
slots. Each virtual-clock tick:

- :func:`deliver` hands every node up to ``K`` deliverable messages
  (deadline <= t, destined to it, not blocked by the receiver-side
  partition matrix). Blocked-but-due messages are *dropped*, matching the
  reference's recv-side silent drop (net.clj:234). Excess deliverable
  messages simply stay queued for the next tick.
- :func:`enqueue` inserts newly sent messages into free pool slots with a
  sampled latency deadline (constant / uniform / exponential, in ticks),
  probabilistic loss, and zero latency on client links (net.clj:178-187).
  Pool overflow drops messages and counts them (an explicit, journaled
  form of packet loss — SURVEY §7 hard parts). The fault engine's
  link-degradation lane (``maelstrom_tpu/faults/``) generalizes the
  boolean partition plane to per-directed-edge quality: blocks fold
  into the delivery partition matrix, while extra latency and elevated
  loss ride ``enqueue``'s ``edge_delay`` / ``edge_loss_pm`` planes.
  Both planes are per-call arguments precisely so the fault FUZZER
  (``faults/fuzz.py``) can vmap a DIFFERENT plane per instance —
  deterministic plans close over one shared plane, randomized
  schedules batch them, and the enqueue math is identical either way
  (zero-valued planes stay value-identical to the healthy path, and
  the edge-loss roll keeps its own folded key so enabling the lane
  never perturbs the base latency/loss draws).

Everything is pure, fixed-shape, and vmappable over the instance axis;
`vmap(deliver)` / `vmap(enqueue)` are the hot ops of the whole TPU runtime.
No scalar loops: slot selection is argsort/top_k over lane masks, which XLA
lowers to vectorized sort networks.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import wire

LATENCY_CONSTANT = 0
LATENCY_UNIFORM = 1
LATENCY_EXPONENTIAL = 2

LATENCY_DISTS = {"constant": LATENCY_CONSTANT, "uniform": LATENCY_UNIFORM,
                 "exponential": LATENCY_EXPONENTIAL}


class NetConfig(NamedTuple):
    """Static network parameters (python-level, closed over at trace time)."""
    n_nodes: int            # server nodes
    n_clients: int
    pool_slots: int         # S
    inbox_k: int            # max deliveries per node per tick
    body_lanes: int
    latency_mean: float     # mean latency in ticks
    latency_dist: int       # LATENCY_* enum
    p_loss: float
    netid: bool = False     # wire format carries the trailing NETID
                            # journal-pairing lane (on only when a run
                            # records per-message journals — the narrow
                            # default drops the lane the manifest
                            # proves dead; see tpu/wire.py)

    @property
    def n_total(self) -> int:
        return self.n_nodes + self.n_clients

    @property
    def lanes(self) -> int:
        return wire.lanes(self.body_lanes, self.netid)

    @property
    def netid_lane(self) -> int:
        """Index of the trailing NETID lane (netid formats only)."""
        return wire.netid_lane(self.lanes)

    @property
    def wire_format(self) -> dict:
        return wire.format_desc(self.body_lanes, self.netid)


class NetStats(NamedTuple):
    """Per-instance counters (int32)."""
    sent: jnp.ndarray
    delivered: jnp.ndarray
    dropped_partition: jnp.ndarray
    dropped_loss: jnp.ndarray
    dropped_overflow: jnp.ndarray

    @staticmethod
    def zeros():
        z = jnp.int32(0)
        return NetStats(z, z, z, z, z)


def empty_pool(cfg: NetConfig) -> jnp.ndarray:
    return jnp.zeros((cfg.pool_slots, cfg.lanes), dtype=jnp.int32)


def pool_occupancy(pool: jnp.ndarray) -> jnp.ndarray:
    """Occupied slot count of a batch-leading pool ([..., S, L] ->
    [...]): the telemetry recorder's in-flight gauge and high-water-mark
    source. The VALID lane is 0/1, so summing its low bit over the slot
    axis is exact — the explicit ``& 1`` mask is a no-op on real data
    and keeps the figure provably bounded under the range analyzer's
    per-lane widening (analysis/absint.py)."""
    return jnp.sum(pool[..., wire.VALID] & 1, axis=-1).astype(jnp.int32)


def no_partitions(cfg: NetConfig) -> jnp.ndarray:
    """partitions[dest, src] True = dest refuses traffic from src."""
    return jnp.zeros((cfg.n_total, cfg.n_total), dtype=bool)


def deliver(pool: jnp.ndarray, partitions: jnp.ndarray, t: jnp.ndarray,
            cfg: NetConfig) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                     jnp.ndarray]:
    """One delivery round for a single instance.

    Returns ``(pool', inbox, n_delivered, n_dropped_partition)`` where
    ``inbox`` is ``[n_total, K, lanes]`` (invalid rows zeroed).

    Not jitted here: the only production callers are the (jitted) tick
    functions, where an inner jit boundary is pure trace overhead — and
    it double-counts every full-width output in the static byte gate.
    The full-width row is touched exactly twice: one fill-gather builds
    the inbox (out-of-range sentinel rows fill with zeros, replacing
    the masked-select + zero-broadcast cascade) and one fill-gather
    rebuilds the pool with delivered/dropped slots cleared.
    """
    S = cfg.pool_slots
    valid = pool[:, wire.VALID] == 1
    due = valid & (pool[:, wire.DTICK] <= t)
    dest = pool[:, wire.DEST]
    # partitions and physics key on the PHYSICAL sender (origin), so a
    # node proxying a client request cannot tunnel through a partition
    origin = pool[:, wire.ORIGIN]
    blocked = partitions[dest, origin]        # [S]

    # drop due+blocked messages now (recv-side partition drop)
    drop_mask = due & blocked
    # candidate deliveries per node: [NT, S]
    node_ids = jnp.arange(cfg.n_total, dtype=jnp.int32)
    cand = (due & ~blocked)[None, :] & (dest[None, :] == node_ids[:, None])

    # pick K due slots per node, oldest deadline first (prevents parked
    # high-index slots from being starved by fresh low-index arrivals),
    # tie-broken by slot index for determinism
    slot_order = jnp.arange(S, dtype=jnp.int32)
    age_rank = ((1 << 20) - pool[:, wire.DTICK]) * S
    prio = jnp.where(cand, age_rank[None, :] + (S - slot_order)[None, :], 0)
    if cfg.inbox_k == 1:
        # K=1 (the headline config): argmax beats top_k's general sort;
        # identical selection incl. tie-breaking (prio values are unique
        # by construction — the slot-index term — and both pick the
        # first maximum)
        topi = jnp.argmax(prio, axis=1)[:, None]         # [NT, 1]
        topv = jnp.take_along_axis(prio, topi, axis=1)
    else:
        topv, topi = jax.lax.top_k(prio, cfg.inbox_k)    # [NT, K]
    take = topv > 0
    # one fill-gather streams each taken row into the inbox: non-taken
    # slots aim past the pool (index S) and fill with the zero row —
    # value-identical to where(take, pool[topi], 0) without
    # materializing the mask + zero tensor at full row width
    srows = jnp.where(take, topi, S)
    inbox = pool.at[srows].get(mode="fill", fill_value=0)

    # clear delivered + dropped slots from the pool (scatter-free: slot s
    # is taken iff some (node, k) selected it — see enqueue's note on
    # vmapped scatters). Cleared slots re-gather the zero fill row.
    flat_i = topi.reshape(-1)
    flat_take = take.reshape(-1)
    taken_slots = jnp.any(
        (flat_i[None, :] == slot_order[:, None]) & flat_take[None, :],
        axis=1)
    cleared = taken_slots | drop_mask
    keep_rows = jnp.where(cleared, S, slot_order)
    pool = pool.at[keep_rows].get(mode="fill", fill_value=0)
    return pool, inbox, jnp.sum(take).astype(jnp.int32), \
        jnp.sum(drop_mask).astype(jnp.int32)


def _sample_latency(key, n, cfg: NetConfig) -> jnp.ndarray:
    if cfg.latency_mean <= 0:
        return jnp.zeros((n,), dtype=jnp.int32)
    if cfg.latency_dist == LATENCY_CONSTANT:
        return jnp.full((n,), round(cfg.latency_mean), dtype=jnp.int32)
    u = jax.random.uniform(key, (n,), minval=1e-6, maxval=1.0)
    if cfg.latency_dist == LATENCY_UNIFORM:
        lat = u * (2.0 * cfg.latency_mean)
    else:  # exponential
        lat = -cfg.latency_mean * jnp.log(u)
    return lat.astype(jnp.int32)


def enqueue(pool: jnp.ndarray, msgs: jnp.ndarray, t: jnp.ndarray,
            key: jnp.ndarray, cfg: NetConfig,
            edge_delay=None, edge_loss_pm=None
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Insert outgoing messages (``[M, lanes]``, invalid rows ignored) into
    the pool. Returns ``(pool', n_sent, n_lost, n_overflow)``.

    ``edge_delay`` / ``edge_loss_pm`` are the fault engine's link-
    degradation planes (``[NT, NT]`` int32 per ``(dest, origin)`` edge:
    extra latency ticks, per-mille loss probability —
    ``maelstrom_tpu/faults/``). ``None`` — every fault-free run — keeps
    the pre-fault graph; zero-valued planes are value-identical to it,
    and the edge-loss roll uses its own folded key so enabling the lane
    never perturbs the base latency/loss draws.

    Not jitted here (the tick functions are the jit boundary — see
    :func:`deliver`). Placement streams the full-width row exactly
    once: all routing math runs on header columns, the two compaction
    permutations compose into one slot -> original-message index map,
    and a single gather + deadline-column stitch builds each placed
    row — the old path re-materialized every outgoing row twice (the
    deadline scatter and the compaction gather) before placement."""
    M = msgs.shape[0]
    S = cfg.pool_slots
    msg_valid = msgs[:, wire.VALID] == 1

    k_lat, k_loss = jax.random.split(key)
    # latency: zero on client links
    is_client_edge = ((msgs[:, wire.ORIGIN] >= cfg.n_nodes) |
                      (msgs[:, wire.DEST] >= cfg.n_nodes))
    lat = _sample_latency(k_lat, M, cfg)
    lat = jnp.where(is_client_edge, 0, lat)
    if edge_delay is not None:
        # slow links: per-directed-edge extra ticks (keyed on the
        # physical sender, like partitions and the base latency)
        lat = lat + edge_delay[msgs[:, wire.DEST], msgs[:, wire.ORIGIN]]
    # deliverable no earlier than the next tick — kept as a column and
    # stitched into the placed rows below (never scattered into all M)
    dtick = t + 1 + lat

    # loss
    if cfg.p_loss > 0:
        lost = (jax.random.uniform(k_loss, (M,)) < cfg.p_loss) & msg_valid
    else:
        lost = jnp.zeros((M,), dtype=bool)
    if edge_loss_pm is not None:
        # elevated per-edge loss: an independent roll on its own key
        pm = edge_loss_pm[msgs[:, wire.DEST], msgs[:, wire.ORIGIN]]
        u = jax.random.uniform(jax.random.fold_in(key, 2), (M,))
        lost = lost | ((u * 1000.0 < pm.astype(jnp.float32))
                       & msg_valid)
    live = msg_valid & ~lost

    # free-slot assignment: argsort puts empty slots first (stable)
    pool_valid = pool[:, wire.VALID] == 1
    order = jnp.argsort(pool_valid)                  # empty slots first
    free_count = jnp.sum(~pool_valid)
    # compact live messages to the front so slot j gets the j-th live msg
    live_order = jnp.argsort(~live)                  # live msgs first
    live_c = live[live_order]
    n_live = jnp.sum(live)

    j = jnp.arange(M)
    can_place = live_c & (j < free_count)
    # rows that don't place target an out-of-bounds slot id and so can
    # never collide with a placed row's slot
    target = jnp.where(can_place, order[jnp.minimum(j, S - 1)], S)
    # placement as the INVERSE mapping — each slot gathers the one
    # message that targets it (at most one: `order` is a permutation and
    # can_place is a j-prefix). Gather + select instead of a batched
    # scatter: vmapped scatters lower to serialized updates on TPU and
    # dominated the whole tick at large instance counts (8.8x cost from
    # 4k->16k instances, vs ~linear for every other phase).
    hit = target[None, :] == jnp.arange(S)[:, None]   # [S, M]
    has = jnp.any(hit, axis=1)
    src = jnp.argmax(hit, axis=1)          # slot -> compacted msg index
    msg_src = live_order[src]              # slot -> ORIGINAL msg index
    placed = msgs[msg_src]                 # the one full-width gather
    # single-lane deadline stitch (a lane-precise column write, which
    # keeps the liveness analyzer's per-lane demand masks exact across
    # the placement — a lane-axis concatenate here would widen them)
    placed = placed.at[:, wire.DTICK].set(dtick[msg_src])
    pool = jax.lax.select(
        jnp.broadcast_to(has[:, None], pool.shape), placed, pool)
    n_placed = jnp.sum(can_place)
    overflow = n_live - n_placed
    # sent counts every valid message, including ones the network then
    # loses — matching the reference, which journals the send before the
    # loss roll (net.clj:208-215)
    n_sent = jnp.sum(msg_valid)
    return pool, n_sent.astype(jnp.int32), jnp.sum(lost).astype(jnp.int32), \
        overflow.astype(jnp.int32)
