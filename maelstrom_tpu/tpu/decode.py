"""Vectorized host-side event decode: device buffers -> op histories.

The original decoder (``harness.events_to_histories``) walked every
nonzero event of the dense ``[T, R, C, 2, 2 + ev_vals]`` tensor in a
Python loop — one ``events[t, r, c, slot]`` scalar gather, one
``int()``-per-lane conversion, and one dict build per event — and the
pipelined executor first *reconstructed* that dense tensor from the
compacted chunk buffers just to scan it again. At fleet scale the
decode stage was the serial wall between the device finishing and the
checkers starting.

This module replaces both moves with NumPy column operations:

- :func:`decode_dense` / :func:`decode_compact` make ONE vectorized
  pass over the event buffers (no dense reconstruction on the compact
  path) and emit per-instance **column slabs** — ``(tick, process,
  etype, vals[ev_vals])`` arrays in exactly the serial decoder's
  history order (tick, then process, then completion-before-invoke
  slot order);
- the Jepsen-style dict records are materialized **lazily, only at the
  checker boundary** (:func:`materialize_records` /
  :class:`LazyHistories`), from ``ndarray.tolist()`` columns instead of
  per-element numpy scalar indexing — and byte-identical to the serial
  decoder's output by construction (``tests/test_check_pool.py`` pins
  ``json.dumps`` equality against :func:`reference_histories`);
- chunks can be decoded **incrementally** (:class:`StreamDecoder`)
  as the pipelined executor fetches them, so decode overlaps device
  compute and the per-instance slabs can stream straight into the
  parallel checker farm (``checkers/pool.py``).

:func:`reference_histories` preserves the original per-event loop as
the bit-identity oracle (and the "before" side of the decode-speedup
scoreboard in doc/results.md).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .runtime import (EV_FAIL, EV_INFO, EV_INVOKE, EV_NONE, EV_OK, Model)

ETYPE_NAMES = {EV_OK: "ok", EV_FAIL: "fail", EV_INFO: "info"}


class EventSlab(NamedTuple):
    """One instance's decoded events as columns, in history order.

    ``vals`` is ``[n, ev_vals]`` (the msg-id lane is never carried —
    the serial decoder drops it too). A slab is cheap to pickle, which
    is what lets the checker pool ship per-instance work to worker
    processes without materializing dict records in the parent."""
    ticks: np.ndarray      # [n] int32
    procs: np.ndarray      # [n] int32 (client index == history process)
    etypes: np.ndarray     # [n] int32 (EV_* codes)
    vals: np.ndarray       # [n, ev_vals] int32

    @property
    def n_events(self) -> int:
        return int(self.ticks.shape[0])


def empty_slab(ev_vals: int) -> EventSlab:
    return EventSlab(ticks=np.zeros((0,), np.int32),
                     procs=np.zeros((0,), np.int32),
                     etypes=np.zeros((0,), np.int32),
                     vals=np.zeros((0, ev_vals), np.int32))


def concat_slabs(slabs: Sequence[EventSlab], ev_vals: int) -> EventSlab:
    """Concatenate chunk-order slabs of one instance. Chunks cover
    disjoint, increasing tick spans, so concatenation preserves the
    global history order."""
    if not slabs:
        return empty_slab(ev_vals)
    if len(slabs) == 1:
        return slabs[0]
    return EventSlab(
        ticks=np.concatenate([s.ticks for s in slabs]),
        procs=np.concatenate([s.procs for s in slabs]),
        etypes=np.concatenate([s.etypes for s in slabs]),
        vals=np.concatenate([s.vals for s in slabs], axis=0))


def _split_by_instance(order: np.ndarray, insts: np.ndarray,
                       ticks: np.ndarray, procs: np.ndarray,
                       etypes: np.ndarray, vals: np.ndarray,
                       n_instances: int) -> Dict[int, EventSlab]:
    """Apply the history sort ``order`` and split the columns into one
    slab per instance present. ``order`` must sort primarily by
    ``insts`` so each instance's rows are contiguous."""
    insts = insts[order]
    ticks, procs = ticks[order], procs[order]
    etypes, vals = etypes[order], vals[order]
    out: Dict[int, EventSlab] = {}
    if insts.shape[0] == 0:
        return out
    # contiguous [start, stop) runs per instance index
    bounds = np.searchsorted(insts, np.arange(n_instances + 1))
    for inst in range(n_instances):
        lo, hi = int(bounds[inst]), int(bounds[inst + 1])
        if lo == hi:
            continue
        out[inst] = EventSlab(ticks=ticks[lo:hi], procs=procs[lo:hi],
                              etypes=etypes[lo:hi], vals=vals[lo:hi])
    return out


def decode_dense(model: Model, events: np.ndarray
                 ) -> Dict[int, EventSlab]:
    """One vectorized pass over the dense ``[T, R, C, 2, 2 + ev_vals]``
    tensor: nonzero scan, column gather, history sort, per-instance
    split. Instances with no events are simply absent from the map."""
    events = np.asarray(events)
    T, R, C, _, _ = events.shape
    V = model.ev_vals
    nz = np.argwhere(events[..., 0] != EV_NONE)
    if nz.shape[0] == 0:
        return {}
    t, r, c, slot = nz[:, 0], nz[:, 1], nz[:, 2], nz[:, 3]
    rows = events[t, r, c, slot]
    etype = rows[:, 0]
    vals = rows[:, 1:1 + V]
    order = np.lexsort((slot, c, t, r))
    return _split_by_instance(order, r, t.astype(np.int32),
                              c.astype(np.int32),
                              etype.astype(np.int32),
                              vals.astype(np.int32, copy=False), R)


def decode_compact(model: Model, n_clients: int, n_instances: int,
                   chunks: Sequence[Tuple[np.ndarray, int]]
                   ) -> Dict[int, EventSlab]:
    """Decode per-chunk compacted ``(rows, count)`` buffers (the
    pipelined executor's fetch payloads, ``tpu/pipeline.py``) straight
    into per-instance slabs — the dense tensor is never rebuilt.
    Overflowed chunks contribute their retained ``cap`` rows, exactly
    like ``expand_compact_events`` (overflow stays a flagged,
    non-silent condition at the executor level)."""
    used = []
    for rows, count in chunks:
        n = min(int(count), rows.shape[0])
        if n:
            used.append(np.asarray(rows[:n]))
    if not used:
        return {}
    allrows = used[0] if len(used) == 1 else np.concatenate(used, axis=0)
    return decode_compact_rows(model, n_clients, n_instances, allrows)


def decode_compact_rows(model: Model, n_clients: int, n_instances: int,
                        rows: np.ndarray) -> Dict[int, EventSlab]:
    """Column-decode already-trimmed compact rows
    ``[(tick, loc, etype, vals...)]`` (``loc = (r * C + c) * 2 +
    slot``)."""
    V = model.ev_vals
    t = rows[:, 0]
    loc = rows[:, 1]
    etype = rows[:, 2]
    vals = rows[:, 3:3 + V]
    r, rem = np.divmod(loc, n_clients * 2)
    c, slot = np.divmod(rem, 2)
    order = np.lexsort((slot, c, t, r))
    return _split_by_instance(order, r, t.astype(np.int32),
                              c.astype(np.int32),
                              etype.astype(np.int32),
                              vals.astype(np.int32, copy=False),
                              n_instances)


def materialize_records(model: Model, slab: EventSlab, final_start: int,
                        ms_per_tick: float,
                        index_base: int = 0) -> List[dict]:
    """Build the Jepsen-style dict records for one slab — the lazy
    checker-boundary step, shared verbatim by the in-process path and
    the checker-pool workers so both produce byte-identical histories.
    ``index_base`` continues a streaming instance's running ``index``
    counter across chunk slabs."""
    ticks = slab.ticks.tolist()
    procs = slab.procs.tolist()
    etypes = slab.etypes.tolist()
    vals = slab.vals.tolist()
    invoke_record = model.invoke_record
    complete_record = model.complete_record
    recs: List[dict] = []
    append = recs.append
    idx = index_base
    for tick, proc, etype, v in zip(ticks, procs, etypes, vals):
        time_ns = int(tick * ms_per_tick * 1_000_000)
        if etype == EV_INVOKE:
            rec = invoke_record(*v)
            rec.update({"process": proc, "type": "invoke",
                        "time": time_ns})
            if tick >= final_start:
                rec["final"] = True
        else:
            rec = complete_record(*v, etype)
            rec.update({"process": proc, "type": ETYPE_NAMES[etype],
                        "time": time_ns})
        rec["index"] = idx
        idx += 1
        append(rec)
    return recs


class LazyHistories(Sequence):
    """A sequence of per-instance histories that materializes each
    instance's dict records on first access (and caches them). Shapes
    exactly like the serial decoder's ``List[List[dict]]`` for every
    consumer that iterates/indexes — store writers, plots, the
    availability checker — while fleets whose verdicts came back from
    the checker pool never pay for records nobody reads."""

    def __init__(self, model: Model, slabs: Dict[int, EventSlab],
                 n_instances: int, final_start: int,
                 ms_per_tick: float):
        self._model = model
        self._slabs = slabs
        self._n = n_instances
        self._final_start = final_start
        self._ms_per_tick = ms_per_tick
        self._cache: Dict[int, List[dict]] = {}

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        if i not in self._cache:
            slab = self._slabs.get(i)
            self._cache[i] = ([] if slab is None else
                              materialize_records(
                                  self._model, slab, self._final_start,
                                  self._ms_per_tick))
        return self._cache[i]

    def slab(self, i: int) -> Optional[EventSlab]:
        return self._slabs.get(i)

    def materialize(self) -> List[List[dict]]:
        return [self[i] for i in range(self._n)]


class StreamDecoder:
    """Incremental column decode for the pipelined executor: feed each
    chunk's compacted payload as it is fetched (overlapping device
    compute), then :meth:`finish` into a :class:`LazyHistories`. The
    per-chunk per-instance slabs are also handed to ``on_slabs`` — the
    checker pool's streaming feed."""

    def __init__(self, model: Model, n_clients: int, n_instances: int,
                 final_start: int, ms_per_tick: float, on_slabs=None):
        self._model = model
        self._C = n_clients
        self._R = n_instances
        self._final_start = final_start
        self._ms_per_tick = ms_per_tick
        self._on_slabs = on_slabs
        self._per_instance: Dict[int, List[EventSlab]] = {}
        self.decode_s = 0.0

    def feed(self, rows: np.ndarray, count: int, *_span) -> None:
        import time
        t0 = time.monotonic()
        n = min(int(count), rows.shape[0])
        slabs = (decode_compact_rows(self._model, self._C, self._R,
                                     np.asarray(rows[:n]))
                 if n else {})
        for inst, slab in slabs.items():
            self._per_instance.setdefault(inst, []).append(slab)
        self.decode_s += time.monotonic() - t0
        if self._on_slabs is not None and slabs:
            self._on_slabs(slabs)

    def feed_dense(self, events: np.ndarray) -> None:
        """Monolithic-path entry: one dense tensor instead of chunks."""
        import time
        t0 = time.monotonic()
        slabs = decode_dense(self._model, events)
        for inst, slab in slabs.items():
            self._per_instance.setdefault(inst, []).append(slab)
        self.decode_s += time.monotonic() - t0
        if self._on_slabs is not None and slabs:
            self._on_slabs(slabs)

    def finish(self) -> LazyHistories:
        import time
        t0 = time.monotonic()
        V = self._model.ev_vals
        merged = {inst: concat_slabs(parts, V)
                  for inst, parts in self._per_instance.items()}
        self.decode_s += time.monotonic() - t0
        return LazyHistories(self._model, merged, self._R,
                             self._final_start, self._ms_per_tick)


# --- the serial reference oracle ------------------------------------------


def reference_histories(model: Model, events: np.ndarray,
                        final_start: int = 1 << 30,
                        ms_per_tick: float = 1
                        ) -> List[List[dict]]:
    """The original per-event Python decoder, kept verbatim as the
    bit-identity oracle for the vectorized path (and the "before" side
    of the decode scoreboard in doc/results.md). Do not optimize."""
    T, R, C, _, _ = events.shape
    histories: List[List[dict]] = [[] for _ in range(R)]
    etypes = events[..., 0]
    nz = np.argwhere(etypes != EV_NONE)
    nz = nz[np.lexsort((nz[:, 3], nz[:, 2], nz[:, 1], nz[:, 0]))]
    for t, r, c, slot in nz:
        ev = events[t, r, c, slot]
        etype = int(ev[0])
        vals = [int(x) for x in ev[1:-1]]
        time_ns = int(int(t) * ms_per_tick * 1_000_000)
        if etype == EV_INVOKE:
            rec = model.invoke_record(*vals)
            rec.update({"process": int(c), "type": "invoke",
                        "time": time_ns})
            if t >= final_start:
                rec["final"] = True
        else:
            rec = model.complete_record(*vals, etype)
            rec.update({"process": int(c), "type": ETYPE_NAMES[etype],
                        "time": time_ns})
        h = histories[r]
        rec["index"] = len(h)
        h.append(rec)
    return histories
