"""Fixed-width message encoding for the device-resident network.

The process runtime ships JSON; the TPU runtime ships int32 lanes. The
row layout is a per-model **wire format**: a fixed 8-lane header, the
model's declared ``body_lanes`` payload lanes, and — only when a run
records per-message journals — one trailing NETID lane:

====  ===========================================================
lane  meaning
====  ===========================================================
0     valid (0/1)
1     src   (logical sender: node index; clients follow server nodes)
2     dest
3     deliver_tick (virtual-clock deadline, the net.clj ns deadline)
4     type  (workload-specific enum)
5     msg_id
6     in_reply_to (-1 if none)
7     origin (PHYSICAL sender — the node/client that put the message on
      the wire; differs from src when a node proxies a client request.
      Latency sampling and partition drops key on origin, reply routing
      on src)
8+    body lanes (workload-specific payload encoding)
last  NETID (only when ``netid`` is on): network-unique message id,
      stamped by the runtime at send time (tick * fanout + row) — the
      journal's send/recv pairing key (role of net.clj's message-ID
      allocator, net.clj:196-201). The lane-liveness manifest proved
      it dead in every registered model when journaling is off
      (``analysis/lane_manifest.json``), so the narrow default format
      simply does not carry it.
====  ===========================================================

Workload vocabularies (the ``defrpc`` schemas of SURVEY §2.2) map onto the
body lanes per workload; capped body width is a stated design constraint of
the TPU runtime (SURVEY §7 hard parts: fixed shapes vs dynamic protocols).
Rows are sized by :func:`lanes`; every consumer reads the resolved format
from ``NetConfig`` (``body_lanes`` + ``netid``), never from a global
worst-case width — the per-family specialization of ROADMAP item 2.
"""

from __future__ import annotations

import jax.numpy as jnp

VALID = 0
SRC = 1
DEST = 2
DTICK = 3
TYPE = 4
MSGID = 5
REPLYTO = 6
ORIGIN = 7
BODY = 8          # first body lane

HDR_LANES = 8


def lanes(body_lanes: int, netid: bool = False) -> int:
    """Row width of the wire format: 8 header + body (+ NETID)."""
    return HDR_LANES + body_lanes + (1 if netid else 0)


def netid_lane(n_lanes: int) -> int:
    """Index of the trailing NETID lane in an ``netid=True`` row."""
    return n_lanes - 1


def format_desc(body_lanes: int, netid: bool = False) -> dict:
    """JSON-able description of a resolved wire format — recorded into
    heartbeat run-start records and bench metric lines so narrowed runs
    rebuild (and report) the exact row layout they ran under."""
    return {"header_lanes": HDR_LANES, "body_lanes": int(body_lanes),
            "netid": bool(netid),
            "lanes": lanes(body_lanes, netid),
            "bytes_per_msg_row": 4 * lanes(body_lanes, netid)}


def empty_msgs(n: int, body_lanes: int, netid: bool = False
               ) -> jnp.ndarray:
    return jnp.zeros((n, lanes(body_lanes, netid)), dtype=jnp.int32)


def make_msg(src, dest, type_, msg_id=-1, reply_to=-1, body=(),
             body_lanes: int = 6, origin=None, netid: bool = False):
    """Build one message row (traced-friendly). ``origin`` defaults to
    ``src``; the runtime's node phase re-stamps it with the emitting
    node's index anyway. ``netid`` widens the row by the trailing
    journal-pairing lane (left zero here — the runtime stamps it at
    send time); models pass ``cfg.netid`` so their rows match the
    run's resolved format."""
    if len(body) > body_lanes:
        raise ValueError(
            f"make_msg: body has {len(body)} values but the wire "
            f"format carries body_lanes={body_lanes} — the .at[BODY+i] "
            f"writes past the row end would silently clip/alias under "
            f"jit; widen the model's body_lanes or shrink the body")
    m = jnp.zeros((lanes(body_lanes, netid),), dtype=jnp.int32)
    m = m.at[VALID].set(1)
    m = m.at[SRC].set(src)
    m = m.at[DEST].set(dest)
    m = m.at[TYPE].set(type_)
    m = m.at[MSGID].set(msg_id)
    m = m.at[REPLYTO].set(reply_to)
    m = m.at[ORIGIN].set(src if origin is None else origin)
    for i, b in enumerate(body):
        m = m.at[BODY + i].set(b)
    return m
