"""The device tick loop: vectorized protocol instances under ``lax.scan``.

One *instance* = one simulated cluster (N server nodes + C clients) with its
own message pool, partition matrix, and RNG stream. The runtime stacks
``n_instances`` of them along a leading batch axis and steps them all in
lockstep:

    tick t:
      faults    : fault-plan phase select; crash-restart wipe from the
                  snapshot slab (maelstrom_tpu/faults/)
      nemesis   : recompute per-instance partition matrices from schedule
                  (fault-plan edge blocks fold in)
      deliver   : vmap(netsim.deliver)   -> per-node inboxes
      node step : vmap over instances, vmap over nodes, scan over inbox
                  (per-node local clocks under the clock-skew lane)
      client step: decode replies -> history events; sample/encode new ops
      enqueue   : vmap(netsim.enqueue)   -> pool with latency/loss (and
                  fault-plan edge delay/loss) applied

The whole loop is a single ``lax.scan`` over ticks, jitted once; the only
host traffic is the initial state upload and the final history/stat
download. History events are recorded for the first ``record_instances``
instances only (checker input); aggregate counters cover all instances
(SURVEY §7: cheap vectorized invariants everywhere, full checkers on
samples).

This module replaces the reference's thread-per-pipe + sleep-per-message
hot path (net.clj:189-247, process.clj:136-166) — the design is the
batched exchange sketched in SURVEY §5 "Distributed communication backend".
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from . import netsim, wire
from .netsim import NetConfig, NetStats
from ..checkers import device_summary
from ..faults import engine as faults_engine
from ..faults import fuzz as faults_fuzz
from ..faults.engine import FaultConfig, NO_PLANES
from ..telemetry import recorder as flight
from ..telemetry.recorder import TelemetryConfig

# --- history events -------------------------------------------------------

# event lanes: [etype, vals[model.ev_vals], msg_id] — width 2 + ev_vals.
# Default models record 4 value lanes (f, a, b, c); wide-payload models
# (transactions, kafka) raise Model.ev_vals and the last lane is always
# the msg id.
EV_TYPE = 0
EV_VALS = 1          # first value lane; msg_id lives at lane 1 + ev_vals

EV_NONE = 0
EV_INVOKE = 1
EV_OK = 2
EV_FAIL = 3
EV_INFO = 4

# client op lanes: [f, a, b, c]
OP_LANES = 4


class ClientConfig(NamedTuple):
    n_clients: int
    rate: float              # P(new op per idle client per tick)
    timeout_ticks: int
    final_start: int = 1 << 30   # from this tick on, clients issue only
                                 # final-phase ops (reference final
                                 # generator: post-heal reads)


class Model:
    """A vectorized node state machine (one per TPU workload).

    Subclasses define the node automaton *and* the client-side op
    vocabulary. All methods are traced; shapes must be static. ``row`` is
    the model's per-node state pytree (arrays without the node axis —
    the runtime vmaps over nodes and instances).
    """

    name: str = "?"
    body_lanes: int = 6
    max_out: int = 1          # messages emitted per handled message
    tick_out: int = 0         # messages emitted by the per-tick hook
    idempotent_fs: Tuple[int, ...] = ()   # f codes safe to fail on timeout
    op_lanes: int = OP_LANES  # width of a client op row (default f,a,b,c)
    ev_vals: int = 4          # value lanes per history event; models with
                              # wide payloads (transactions) raise this and
                              # implement decode_reply_wide
    fused_node = False        # True: the runtime drives the node step
                              # through the compartmentalized protocol
                              # (decode_inbox/node_rng/inbox_step/
                              # assemble_replies/fused_tick) instead of
                              # scanning handle()+tick() — see the
                              # fused-protocol section below

    # models are stateless singletons: hash by type so fresh instances hit
    # the jit cache instead of forcing a recompile per Model()
    def __hash__(self):
        return hash(type(self))

    def __eq__(self, other):
        return type(self) is type(other)

    def make_params(self, n_nodes: int):
        """Build the model's static parameter pytree (e.g. a topology
        adjacency matrix); passed to every traced method as ``params``."""
        return None

    def init_row(self, n_nodes: int, node_idx, key, params) -> Any:
        raise NotImplementedError

    def handle(self, row, node_idx, msg, t, key, cfg: NetConfig, params
               ) -> Tuple[Any, jnp.ndarray]:
        """Process one message; return (row', outs[max_out, L]).

        CONTRACT: must be a no-op (state unchanged, outs invalid) when the
        message is invalid — ``msg`` is all zeros then, so gating every
        state change and out-VALID lane on the message type being one of
        the model's types suffices. The runtime does NOT mask the result
        (a full-pytree where per inbox slot would dominate the tick cost).
        """
        raise NotImplementedError

    def tick(self, row, node_idx, t, key, cfg: NetConfig, params
             ) -> Tuple[Any, jnp.ndarray]:
        """Per-tick hook (timers, gossip). Default: no-op."""
        return row, jnp.zeros((self.tick_out, cfg.lanes), dtype=jnp.int32)

    # --- fused node-step protocol (opt-in via ``fused_node = True``) ------
    #
    # A fused model splits the node step into independently batchable
    # compartments so the hot per-slot loop carries only the
    # order-dependent state chain: the runtime draws the tick's
    # randomness in one batched site (node_rng), scans the minimal
    # sequential core over the slots (inbox_step, unrolled so the HLO
    # is while-free), assembles all K replies in one scatter/gather
    # pass (assemble_replies), then runs the per-tick hook
    # (fused_tick). CONTRACT: trajectories must be bit-identical to
    # the handle()/tick() formulation — handle/tick stay as the
    # reference oracle (tests/test_node_fusion.py) — max_out must be 1
    # (one reply row per inbox slot), and every emitted row must come
    # out SRC/ORIGIN pre-stamped (the legacy path's masked re-stamp
    # applied after the fact; fused models bake the same values in).

    def node_rng(self, mkeys) -> Tuple[Any, Any]:
        """All random draws for one node's tick from the [K+1] slot
        key stack (slot i = fold_in(node key, i); slot K = the tick
        key). Returns (per-slot draws [K, ...], tick draws)."""
        raise NotImplementedError

    def inbox_step(self, row, node_idx, msg, rng, t, cfg: NetConfig,
                   params) -> Tuple[Any, jnp.ndarray]:
        """One slot of the sequential core: (row', reply row [L]) from
        one message row. Must self-gate on invalid slots like
        handle(); the reply rows come out as scan ys, which the
        unrolled scan materializes as one fused [K, L] batch."""
        raise NotImplementedError

    def fused_tick(self, row, node_idx, t, rng, cfg: NetConfig, params,
                   m_bits=None) -> Tuple[Any, jnp.ndarray]:
        """Per-tick hook for the fused path: like tick(), but takes
        the pre-drawn randomness from node_rng instead of a key.
        ``m_bits`` is the membership lane's target bitmask for this
        tick (``None`` on membership-free runs)."""
        raise NotImplementedError

    # --- crash-restart fault lane (maelstrom_tpu/faults/) -----------------
    #
    # When a fault plan carries a crash lane, the runtime holds each
    # victim in reset: every crashed tick the node's row is rebuilt via
    # restart_row() and selected in under the crash mask, and the
    # snapshot slab captures snapshot_row() of every healthy node on
    # the plan's snapshot stride (1 = write-through durability). The
    # default semantics are COLD restart — total state loss, the right
    # behavior for models without durable storage; models with a
    # durability story (Raft's persisted term/vote/log) override both
    # hooks (models/raft.py).

    def snapshot_row(self, row) -> Any:
        """The durable subset of a node row persisted into the fault
        engine's snapshot slab. Must be pure leaf selection /
        restructuring (no math): it is applied to BATCHED rows in both
        carry layouts. Default: the whole row."""
        return row

    def restart_row(self, n_nodes: int, node_idx, key, params, snap,
                    t) -> Any:
        """Rebuild a node row as of a restart at (node-local) tick
        ``t``, given its last snapshot-slab row ``snap``. Default: the
        init path — a cold boot that forgets everything (``snap``
        ignored). Models with durable state restore it here; absolute
        timers must be re-based on ``t``."""
        del snap, t
        return self.init_row(n_nodes, node_idx, key, params)

    # --- membership fault lane (maelstrom_tpu/faults/ membership) --------
    #
    # When a plan (or fuzzed schedule) carries a membership lane, the
    # runtime parks non-members like crash victims — held at join_row
    # of their snapshot-slab row, recv blocked, sends suppressed — and
    # clients only target members. The tick's member BITMASK also
    # threads into the fused node step (``m_bits``), so a protocol
    # with a real reconfiguration story (Raft joint consensus,
    # models/raft_core.py) drives the change through its log instead
    # of by administrative fiat.

    def join_row(self, n_nodes: int, node_idx, key, params, snap, t,
                 m_bits) -> Any:
        """Rebuild a node row as of a membership JOIN at (node-local)
        tick ``t``: ``m_bits`` is the current member bitmask the node
        is being provisioned into. Default: the crash-restart path
        (slab recovery / cold boot), ignoring the bitmask — right for
        models that keep no cluster-config state."""
        del m_bits
        return self.restart_row(n_nodes, node_idx, key, params, snap,
                                t)

    def boot_config(self, node_state, m_bits) -> Any:
        """Stamp the INITIAL (phase-0) membership bitmask into the
        model's provisioning config at init time. Must be pure leaf
        restructuring — it is applied to BATCHED node state in both
        carry layouts. Default: no-op (no config state)."""
        del m_bits
        return node_state

    def invariants(self, node_state, cfg: NetConfig, params) -> jnp.ndarray:
        """Cheap whole-cluster safety invariants, evaluated on-device every
        tick for EVERY instance (SURVEY §7: vectorized invariants
        everywhere, full checkers on samples). ``node_state`` is the
        instance's full per-node state pytree ([N, ...] leading axis).
        Returns a scalar bool: True = violated this tick."""
        return jnp.bool_(False)

    def summary_step(self, summ, node_state, events, cfg: NetConfig,
                     params) -> jnp.ndarray:
        """Device verdict lane hook (checkers/device_summary.py): fold
        one instance's committed frontier / prefix hash / divergence
        witness into its [N_LANES] summary row — normally one
        ``device_summary.fold_frontier`` call. Evaluated on-device
        every tick for EVERY instance when ``--check-mode device|both``
        is on; a nonzero FLAGS lane routes the instance into the host
        checker farm for full-oracle confirmation. Flags are a screen,
        not a verdict — only tripped ``invariants`` force invalid on
        their own. ``node_state`` is the instance's full per-node state
        pytree ([N, ...] leading axis); ``events`` is its [C, 2,
        2 + ev_vals] event rows for this tick (slot 0 = completions —
        the read-completion witness the CRDT stale screens use).
        Default: identity (no model lane; the runtime still folds the
        availability/net counter twins)."""
        del node_state, events, cfg, params
        return summ

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg: NetConfig, params) -> jnp.ndarray:
        """Draw an op [OP_LANES] (f, a, b, c). ``uniq`` is a monotonically
        increasing per-client int (the op counter) for allocating distinct
        values (e.g. broadcast message ids)."""
        raise NotImplementedError

    def sample_final_op(self, key, uniq, cfg: NetConfig, params
                        ) -> jnp.ndarray:
        """Op drawn during the final (post-heal) phase; workloads with
        final reads override this to return their read op."""
        return self.sample_op(key, uniq, cfg, params)

    def encode_request(self, op, msg_id, client_idx, key, cfg: NetConfig,
                       params) -> jnp.ndarray:
        """Encode an op as a request message row (src/dest/type/body)."""
        raise NotImplementedError

    def decode_reply(self, op, msg, cfg: NetConfig, params
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Given the op and its reply message, return
        (etype in {EV_OK, EV_FAIL, EV_INFO}, value[3] result lanes).
        Used when ``ev_vals == 4``; the completion event records
        ``(op[0], value[0], value[1], value[2])``."""
        raise NotImplementedError

    def decode_reply_wide(self, op, msg, cfg: NetConfig, params
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Wide-payload models (``ev_vals != 4``): return
        (etype, vals[ev_vals]) — the FULL value-lane row recorded for the
        completion event (the invocation records the op row, padded)."""
        raise NotImplementedError


# generic error reply handling: error type code shared by all models
TYPE_ERROR = 127
# error body lane 0 = code; definite codes -> EV_FAIL, else EV_INFO.
# Plain tuple: a module-level jnp.array would initialize the accelerator
# backend at import time.
_DEFINITE_CODES = (1, 10, 11, 12, 14, 20, 21, 22, 30)


def decode_error_reply(msg) -> Tuple[jnp.ndarray, jnp.ndarray]:
    code = msg[wire.BODY]
    definite = jnp.any(jnp.array(_DEFINITE_CODES, dtype=jnp.int32) == code)
    etype = jnp.where(definite, EV_FAIL, EV_INFO)
    return etype, jnp.zeros((3,), dtype=jnp.int32)


# --- client machine -------------------------------------------------------

class ClientState(NamedTuple):
    status: jnp.ndarray        # [C] 0 idle / 1 waiting
    op: jnp.ndarray            # [C, OP_LANES]
    msg_id: jnp.ndarray        # [C] current outstanding msg id
    next_msg_id: jnp.ndarray   # [C]
    invoked: jnp.ndarray       # [C] tick of invocation

    @staticmethod
    def init(C: int, op_lanes: int = OP_LANES):
        return ClientState(
            status=jnp.zeros((C,), jnp.int32),
            op=jnp.zeros((C, op_lanes), jnp.int32),
            msg_id=jnp.full((C,), -1, jnp.int32),
            next_msg_id=jnp.zeros((C,), jnp.int32),
            invoked=jnp.zeros((C,), jnp.int32),
        )


def client_step(model: Model, cs: ClientState, inbox_clients, t, key,
                cfg: NetConfig, ccfg: ClientConfig, params):
    """One tick for all C clients of one instance.

    Returns (cs', requests [C, L], events [C, 2, 2 + model.ev_vals]).
    Event slot 0 = completion, slot 1 = invocation. A client that completes
    this tick goes idle immediately and MAY fire a new op in the same tick;
    the history decoder orders slot 0 before slot 1, so the completion
    always precedes the next invocation.
    """
    C = ccfg.n_clients
    L = cfg.lanes
    V = model.ev_vals
    events = jnp.zeros((C, 2, 2 + V), dtype=jnp.int32)

    def pad_op(op_rows):
        """[C, op_lanes] -> [C, V] (truncate or zero-pad)."""
        if op_rows.shape[1] >= V:
            return op_rows[:, :V]
        return jnp.pad(op_rows, ((0, 0), (0, V - op_rows.shape[1])))

    # --- completions: find a reply matching our outstanding msg_id
    def find_reply(client_idx):
        msgs = inbox_clients[client_idx]            # [K, L]
        match = ((msgs[:, wire.VALID] == 1) &
                 (msgs[:, wire.REPLYTO] == cs.msg_id[client_idx]) &
                 (cs.status[client_idx] == 1))
        has = jnp.any(match)
        idx = jnp.argmax(match)
        return has, msgs[idx]

    has_reply, reply = jax.vmap(find_reply)(jnp.arange(C))

    if V == 4:
        def decode_one(op, msg):
            is_err = msg[wire.TYPE] == TYPE_ERROR
            et_err, val_err = decode_error_reply(msg)
            et_ok, val_ok = model.decode_reply(op, msg, cfg, params)
            etype = jnp.where(is_err, et_err, et_ok)
            value = jnp.where(is_err, val_err, val_ok)
            # completion vals = (f, value lanes)
            return etype, jnp.concatenate([op[0:1], value])
    else:
        def decode_one(op, msg):
            is_err = msg[wire.TYPE] == TYPE_ERROR
            et_err, _ = decode_error_reply(msg)
            et_ok, vals_ok = model.decode_reply_wide(op, msg, cfg, params)
            etype = jnp.where(is_err, et_err, et_ok)
            # errors echo the op row (the invocation's value)
            if op.shape[0] >= V:
                op_pad = op[:V]
            else:
                op_pad = jnp.zeros((V,), jnp.int32).at[:op.shape[0]].set(op)
            vals = jnp.where(is_err, op_pad, vals_ok)
            return etype, vals

    etype_r, value_r = jax.vmap(decode_one)(cs.op, reply)

    # timeouts -> EV_INFO (EV_FAIL when the op's f is idempotent)
    timed_out = ((cs.status == 1) & ~has_reply &
                 (t - cs.invoked >= ccfg.timeout_ticks))
    idem = jnp.zeros((C,), dtype=bool)
    for f in model.idempotent_fs:
        idem = idem | (cs.op[:, 0] == f)
    etype_t = jnp.where(idem, EV_FAIL, EV_INFO)

    completed = has_reply | timed_out
    comp_etype = jnp.where(has_reply, etype_r, etype_t)
    comp_vals = jnp.where(has_reply[:, None], value_r, pad_op(cs.op))
    events = events.at[:, 0, EV_TYPE].set(
        jnp.where(completed, comp_etype, EV_NONE))
    events = events.at[:, 0, 1:1 + V].set(comp_vals)
    events = events.at[:, 0, 1 + V].set(cs.msg_id)

    status = jnp.where(completed, 0, cs.status)

    # --- new invocations from idle clients
    k_rate, k_ops, k_enc = jax.random.split(key, 3)
    idle = status == 0
    fire = idle & (jax.random.uniform(k_rate, (C,)) < ccfg.rate)
    op_keys = jax.random.split(k_ops, C)
    in_final = t >= ccfg.final_start
    # uniq: instance-globally-unique op counter (client-striped), so
    # models can mint distinct values (e.g. unique appended elements)
    uniq = cs.next_msg_id * C + jnp.arange(C, dtype=jnp.int32)
    new_ops = jax.vmap(
        lambda k, u: jnp.where(
            in_final,
            model.sample_final_op(k, u, cfg, params),
            model.sample_op(k, u, cfg, params)))(op_keys, uniq)
    op = jnp.where(fire[:, None], new_ops, cs.op)
    msg_id = jnp.where(fire, cs.next_msg_id, cs.msg_id)
    next_msg_id = jnp.where(fire, cs.next_msg_id + 1, cs.next_msg_id)
    invoked = jnp.where(fire, t, cs.invoked)
    status = jnp.where(fire, 1, status)

    enc_keys = jax.random.split(k_enc, C)
    reqs = jax.vmap(lambda o, m, ci, k: model.encode_request(
        o, m, ci, k, cfg, params))(op, msg_id,
                                   jnp.arange(C, dtype=jnp.int32), enc_keys)
    client_ids = cfg.n_nodes + jnp.arange(C, dtype=jnp.int32)
    reqs = reqs.at[:, wire.VALID].set(jnp.where(fire, 1, 0))
    reqs = reqs.at[:, wire.SRC].set(client_ids)
    reqs = reqs.at[:, wire.ORIGIN].set(client_ids)
    reqs = reqs.at[:, wire.MSGID].set(msg_id)

    events = events.at[:, 1, EV_TYPE].set(
        jnp.where(fire, EV_INVOKE, EV_NONE))
    events = events.at[:, 1, 1:1 + V].set(pad_op(op))
    events = events.at[:, 1, 1 + V].set(msg_id)

    cs = ClientState(status=status, op=op, msg_id=msg_id,
                     next_msg_id=next_msg_id, invoked=invoked)
    return cs, reqs, events


# --- nemesis --------------------------------------------------------------

class NemesisConfig(NamedTuple):
    enabled: bool = False
    interval: int = 50         # ticks between phase flips
    kind: str = "random-halves"
    stop_tick: int = 1 << 30   # final heal: no partitions at/after this
                               # tick (the reference's final-generator heal
                               # + quiesce phase, core.clj:74-80)
    schedule: tuple = ()       # kind="scripted": ((until_tick,
                               # ((dst, src), ...)), ...) phases ordered by
                               # until_tick — deterministic per-tick
                               # partition control for constructed
                               # scenarios (e.g. the Raft Figure-8);
                               # healed after the last phase. Plain nested
                               # tuples so SimConfig stays hashable/static.


def scripted_isolate_groups(until_tick: int, groups, n_nodes: int
                            ) -> tuple:
    """Build one scripted-schedule phase where traffic is allowed only
    WITHIN each group in ``groups``; every cross-group server pair is
    blocked. Returns ``(until_tick, pairs)`` for
    :attr:`NemesisConfig.schedule`."""
    member = {}
    for gi, g in enumerate(groups):
        for node in g:
            member[node] = gi
    pairs = []
    for dst in range(n_nodes):
        for src in range(n_nodes):
            if dst == src:
                continue
            if member.get(dst) is None or member.get(src) is None \
                    or member[dst] != member[src]:
                pairs.append((dst, src))
    return (until_tick, tuple(pairs))


def partition_matrix(nem: NemesisConfig, cfg: NetConfig, t, instance_key
                     ) -> jnp.ndarray:
    """Per-instance partition matrix at tick t: alternating heal/partition
    phases every ``interval`` ticks, a fresh random grudge each phase.
    Clients are never partitioned (grudges cover server nodes only,
    nemesis.clj semantics)."""
    NT = cfg.n_total
    if not nem.enabled:
        return jnp.zeros((NT, NT), dtype=bool)
    n = cfg.n_nodes
    if nem.kind == "scripted":
        # deterministic per-tick schedule: constant per-phase matrices
        # baked into the graph, phase selected by searchsorted on t
        import numpy as np
        P = len(nem.schedule)
        mats = np.zeros((P + 1, NT, NT), dtype=bool)  # last = healed
        untils = np.full((P + 1,), np.iinfo(np.int32).max, dtype=np.int32)
        for i, (until, pairs) in enumerate(nem.schedule):
            untils[i] = until
            for dst, src in pairs:
                mats[i, dst, src] = True
        phase_i = jnp.searchsorted(jnp.asarray(untils), t, side="right")
        blocked = jnp.asarray(mats)[jnp.clip(phase_i, 0, P)]
        server = jnp.arange(NT) < n
        blocked = blocked & server[:, None] & server[None, :]
        return jnp.where(t < nem.stop_tick, blocked, False)
    phase = t // nem.interval
    active = ((phase % 2) == 1) & (t < nem.stop_tick)
    key = jax.random.fold_in(instance_key, phase)
    if nem.kind == "isolated-node":
        victim = jax.random.randint(key, (), 0, n)
        ids = jnp.arange(NT)
        isolated = ids == victim
        blocked = isolated[:, None] ^ isolated[None, :]
    elif nem.kind == "majorities-ring":
        # each node sees a distinct majority around a random ring
        # (nemesis.py grudge_majorities_ring semantics)
        perm = jax.random.permutation(key, n)
        pos = jnp.zeros((NT,), jnp.int32).at[perm].set(jnp.arange(n))
        maj = n // 2 + 1
        dist = jnp.mod(pos[None, :] - pos[:, None], n)   # [dst, src]
        visible = (dist <= maj // 2) | (dist >= n - (maj - 1) // 2)
        blocked = ~visible
    else:  # random-halves
        side = jax.random.bernoulli(key, 0.5, (NT,))
        blocked = side[:, None] != side[None, :]
    server = jnp.arange(NT) < n
    blocked = blocked & server[:, None] & server[None, :]
    return jnp.where(active, blocked, False)


# --- node phase -----------------------------------------------------------

def node_phase(model: Model, node_state, inbox_nodes, t, key,
               cfg: NetConfig, params, t_nodes=None, m_bits=None):
    """All nodes of one instance handle their inboxes then run tick hooks.

    node_state: pytree with leading node axis [N, ...].
    inbox_nodes: [N, K, L]. Returns (state', outs [N*(K*max_out+tick_out), L]).

    Two drivers share this entry: the legacy per-slot scan over
    ``handle()``, and — for ``model.fused_node`` models — the
    compartmentalized step (batched decode -> unrolled minimal
    sequential core -> batched reply assembly -> fused tick hook; see
    the Model fused-protocol docs). Both produce bit-identical
    trajectories; the fused driver exists because its jaxpr is ~2x
    smaller and its HLO is while-free (models/raft_core.py).

    ``t_nodes`` ([N] int32, fault engine's clock-skew lane) substitutes
    each node's LOCAL clock for ``t`` in its timer logic (election
    deadlines, heartbeat cadence); ``None`` — the default and every
    fault-free run — hands every node the global ``t`` through the
    identical closure the pre-fault runtime used. ``m_bits`` (scalar
    int32, membership lane) is the tick's target member bitmask,
    handed to fused models' tick hooks so reconfiguration-aware
    protocols can drive the change; ``None`` on membership-free runs.
    """
    N = cfg.n_nodes
    L = cfg.lanes

    def stamp(outs, node_idx):
        # stamp src = this node on rows whose SRC lane is still the zero
        # default. Contract: models either leave SRC unset (ordinary
        # sends/replies) or copy a CLIENT src (>= n_nodes) when proxying a
        # client request toward the leader. Forwarding a *server*-origin
        # message is not supported — node 0's id collides with the unset
        # sentinel and would be re-stamped.
        outs = outs.at[:, wire.SRC].set(
            jnp.where(outs[:, wire.SRC] == 0, node_idx,
                      outs[:, wire.SRC]))
        # ORIGIN is always the emitting node — the physical link the
        # message leaves on — regardless of any proxied logical src
        return outs.at[:, wire.ORIGIN].set(node_idx)

    if model.fused_node:
        assert model.max_out == 1, "fused node step assumes max_out == 1"

        def per_node(row, inbox_row, nkey, node_idx, tn):
            K = inbox_row.shape[0]
            # [K+1] slot keys in one batched fold: slot i is the legacy
            # per-message fold_in(nkey, i), slot K the legacy tick key —
            # the model batches ALL its draws from these in one site
            mkeys = jax.vmap(lambda i: jax.random.fold_in(nkey, i))(
                jnp.arange(K + 1, dtype=jnp.int32))
            slot_rng, tick_rng = model.node_rng(mkeys)
            row, outs_k = jax.lax.scan(
                lambda r, x: model.inbox_step(r, node_idx, x[0], x[1],
                                              tn, cfg, params),
                row, (inbox_row, slot_rng), unroll=True)
            row, outs_t = model.fused_tick(row, node_idx, tn, tick_rng,
                                           cfg, params, m_bits=m_bits)
            # fused models pre-stamp SRC/ORIGIN on every emitted row
            # (see the fused-protocol contract) — no re-stamp pass
            return row, jnp.concatenate([outs_k, outs_t], axis=0)
    else:
        def per_node(row, inbox_row, nkey, node_idx, tn):
            def step(r, x):
                msg, i = x
                # distinct key per handled message — a shared key would
                # correlate every random draw a model makes within a tick
                mkey = jax.random.fold_in(nkey, i)
                # models self-gate on invalid (all-zero) messages — see
                # the Model.handle contract
                return model.handle(r, node_idx, msg, tn, mkey, cfg,
                                    params)

            k_idx = jnp.arange(inbox_row.shape[0], dtype=jnp.int32)
            row, outs_k = jax.lax.scan(step, row, (inbox_row, k_idx))
            tkey = jax.random.fold_in(nkey, inbox_row.shape[0])
            row, outs_t = model.tick(row, node_idx, tn, tkey, cfg, params)
            outs = jnp.concatenate(
                [outs_k.reshape(-1, L), outs_t.reshape(-1, L)], axis=0)
            return row, stamp(outs, node_idx)

    keys = jax.random.split(key, N)
    idx = jnp.arange(N, dtype=jnp.int32)
    if t_nodes is None:
        # the pre-fault path: every node's clock IS the global t,
        # closed over exactly as before (no per-node clock vector in
        # the graph — bit- and cost-identical to the pre-fault tick)
        return jax.vmap(
            lambda row, ib, k, i: per_node(row, ib, k, i, t))(
            node_state, inbox_nodes, keys, idx)
    return jax.vmap(per_node)(node_state, inbox_nodes, keys, idx,
                              t_nodes)


# --- the scan loop --------------------------------------------------------

class SimConfig(NamedTuple):
    net: NetConfig
    client: ClientConfig
    nemesis: NemesisConfig
    n_instances: int
    n_ticks: int
    record_instances: int
    journal_instances: int = 0   # instances whose raw message traffic is
                                 # streamed back for the per-message
                                 # journal (Lamport diagrams, msgs-per-op
                                 # — net/journal.clj's role device-side)
    layout: str = "minor"        # instance-batch axis position in the
                                 # carry: "minor" = batch-LAST (instances
                                 # on the TPU 128-lane axis — tiling
                                 # without padding), "lead" = batch-first
                                 # (the original layout; kept as the
                                 # bit-compat oracle and for the Pallas
                                 # delivery kernel). Trajectories are
                                 # bit-identical either way; see
                                 # canonical_carry.
    telemetry: TelemetryConfig = TelemetryConfig()
                                 # flight-recorder knobs (telemetry/
                                 # recorder.py); enabled=False removes
                                 # the telemetry leaves from the carry
                                 # entirely (zero-overhead path)
    faults: FaultConfig = FaultConfig()
                                 # compiled fault plan (maelstrom_tpu/
                                 # faults/): crash-restart, link
                                 # degradation, clock skew. The default
                                 # (disabled) config traces EXACTLY the
                                 # pre-fault tick graph
                                 # (doc/guide/10-faults.md)
    check_summary: bool = False  # device verdict lanes (checkers/
                                 # device_summary.py): carry a per-
                                 # instance [N_LANES] int32 summary row
                                 # updated inside the fused tick, so
                                 # the host farm only confirms flagged
                                 # instances (--check-mode device|both).
                                 # False removes the leaf entirely
                                 # (zero-overhead, the telemetry
                                 # precedent)


class TickOutputs(NamedTuple):
    """Per-tick scan outputs: history events for the recorded instances,
    plus (when journal_instances > 0) the raw sent rows and delivered
    inboxes of the journaled instances.

    Fields are ``None`` (an empty pytree — no device buffer, no scan-ys
    stacking, no host fetch) when their instance count is zero: a
    fleet-stats-only run (``record_instances == 0``) materializes no
    event tensor at all, and the journal buffers only exist when
    journaling was requested."""
    events: Optional[jnp.ndarray]        # [R, C, 2, 2 + ev_vals]
    journal_sends: Optional[jnp.ndarray]  # [J, M, L] rows (pre-enqueue)
    journal_recvs: Optional[jnp.ndarray]  # [J, NT, K, L] delivered


class Carry(NamedTuple):
    pool: jnp.ndarray          # [I, S, L]
    node_state: Any            # pytree [I, N, ...]
    client_state: ClientState  # arrays [I, C...]
    stats: NetStats            # scalars (summed over instances)
    violations: jnp.ndarray    # [I] int32: ticks each instance violated
                               # a model invariant (0 = clean)
    key: jnp.ndarray           # the CONSTANT master key (never advanced)
    telemetry: Any = None      # flight recorder (telemetry/recorder.py);
                               # batch-LEADING in BOTH layouts, None when
                               # sim.telemetry.enabled is False
    snapshots: Any = None      # fault-engine snapshot slab: the durable
                               # subset of node_state (Model.snapshot_row
                               # per node, same layout orientation as
                               # node_state), read by crash-restart
                               # recovery (maelstrom_tpu/faults/). None
                               # unless the fault plan has a crash lane
    fault_sched: Any = None    # per-instance randomized fault schedules
                               # (faults/fuzz.py FaultSchedule, batched
                               # like node_state): drawn ONCE at init
                               # from the _RNG_FAULTS purpose, constant
                               # across ticks — riding the carry keeps
                               # checkpoint/resume and triage replay
                               # bit-exact. None unless the run fuzzes
    check_summary: Any = None  # device verdict lanes [I, N_LANES] int32
                               # (checkers/device_summary.py); batch-
                               # LEADING in BOTH layouts like telemetry,
                               # None unless sim.check_summary


# RNG purpose tags. Every random draw in the simulation derives from
# (master key, purpose, [tick,] instance id) via fold_in — no key ever
# chains through the carry. Consequence: an instance's full trajectory
# is a pure function of (seed, its instance id), independent of which
# other instances share the batch — so any subset of instances (e.g.
# the violating ones from a 100k-instance sweep) can be re-simulated
# bit-exactly on its own with recording enabled (SURVEY §7: "full
# checkers on samples + any instance whose invariants trip").
_RNG_INIT = 0
_RNG_NEMESIS = 1
_RNG_NODE = 2
_RNG_CLIENT = 3
_RNG_ENQUEUE = 4
_RNG_RESTART = 5    # crash-restart re-init jitter (faults/ crash lane)
_RNG_FAULTS = faults_fuzz.RNG_PURPOSE   # = 6: the schedule-RNG lane —
                    # per-instance randomized fault schedules
                    # (faults/fuzz.py). Instance-stable (no tick fold),
                    # so an instance's schedule — like its trajectory —
                    # is a pure function of (seed, instance id) and
                    # `maelstrom shrink` rebuilds it from the seed


def _instance_keys(master, purpose: int, instance_ids, t=None):
    k = jax.random.fold_in(master, purpose)
    if t is not None:
        k = jax.random.fold_in(k, t)
    return jax.vmap(lambda i: jax.random.fold_in(k, i))(instance_ids)


def default_instance_ids(sim: SimConfig) -> jnp.ndarray:
    return jnp.arange(sim.n_instances, dtype=jnp.int32)


def init_carry(model: Model, sim: SimConfig, seed: int, params,
               instance_ids=None) -> Carry:
    I = sim.n_instances
    cfg = sim.net
    key = jax.random.PRNGKey(seed)
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)
    minor = sim.layout == "minor"

    def init_instance(ikey):
        nkeys = jax.random.split(ikey, cfg.n_nodes)
        return jax.vmap(
            lambda nk, ni: model.init_row(cfg.n_nodes, ni, nk, params))(
                nkeys, jnp.arange(cfg.n_nodes, dtype=jnp.int32))

    node_state = jax.vmap(init_instance, out_axes=-1 if minor else 0)(
        _instance_keys(key, _RNG_INIT, instance_ids))
    pool_shape = ((cfg.pool_slots, cfg.lanes, I) if minor
                  else (I, cfg.pool_slots, cfg.lanes))
    # membership lane: the INITIAL member set (phase 0 of the plan)
    # provisions the model's boot config — stamped BEFORE the slab
    # seeds so a restart restores the same provisioning. Fuzzed
    # membership always starts from the full cluster, which is
    # init_row's own default.
    if sim.faults.has_members and not sim.faults.has_fuzz:
        bits0 = sum(1 << v for v in sim.faults.members[0])
        node_state = model.boot_config(node_state, bits0)
    # the fault engine's snapshot slab seeds from the init state
    # (snapshot_row is pure leaf selection, so it applies to the
    # batched node_state in either layout orientation; the membership
    # lane needs it too — joins restore from it)
    snapshots = (model.snapshot_row(node_state)
                 if (sim.faults.has_crash or sim.faults.has_members)
                 else None)
    # fuzz runs draw each instance's randomized fault schedule here,
    # once, from the dedicated schedule-RNG purpose — instance-stable,
    # so any subset replays (triage/funnel/shrink) redraw identically
    fault_sched = None
    if sim.faults.has_fuzz:
        fkeys = _instance_keys(key, _RNG_FAULTS, instance_ids)
        fault_sched = jax.vmap(
            lambda fk: faults_fuzz.draw_schedule(fk, sim.faults,
                                                 cfg.n_nodes),
            out_axes=-1 if minor else 0)(fkeys)
    return Carry(
        pool=jnp.zeros(pool_shape, jnp.int32),
        node_state=node_state,
        snapshots=snapshots,
        fault_sched=fault_sched,
        client_state=jax.tree.map(
            (lambda a: jnp.broadcast_to(a[..., None], a.shape + (I,)))
            if minor else
            (lambda a: jnp.broadcast_to(a, (I,) + a.shape)),
            ClientState.init(sim.client.n_clients, model.op_lanes)),
        stats=NetStats.zeros(),
        violations=jnp.zeros((I,), jnp.int32),
        key=key,
        telemetry=flight.init_telemetry(I, sim.telemetry),
        check_summary=(device_summary.init_summary(I)
                       if sim.check_summary else None),
    )


def canonical_carry(carry: Carry, sim: SimConfig) -> Carry:
    """Return the carry with the instance-batch axis LEADING on every
    batched leaf, whatever ``sim.layout`` is — the canonical orientation
    for digests (tools/platform_xval.py) and for crossing shard_map
    boundaries (parallel/mesh.py wire format). Pure transpose: values
    are untouched, so canonical digests are layout-independent."""
    if sim.layout != "minor":
        return carry
    to_lead = lambda x: jnp.moveaxis(x, -1, 0)
    return carry._replace(
        pool=to_lead(carry.pool),
        node_state=jax.tree.map(to_lead, carry.node_state),
        client_state=jax.tree.map(to_lead, carry.client_state),
        snapshots=jax.tree.map(to_lead, carry.snapshots),
        fault_sched=jax.tree.map(to_lead, carry.fault_sched))


def carry_from_canonical(carry: Carry, sim: SimConfig) -> Carry:
    """Inverse of :func:`canonical_carry`."""
    if sim.layout != "minor":
        return carry
    to_minor = lambda x: jnp.moveaxis(x, 0, -1)
    return carry._replace(
        pool=to_minor(carry.pool),
        node_state=jax.tree.map(to_minor, carry.node_state),
        client_state=jax.tree.map(to_minor, carry.client_state),
        snapshots=jax.tree.map(to_minor, carry.snapshots),
        fault_sched=jax.tree.map(to_minor, carry.fault_sched))


def _update_telemetry(tel, sim: SimConfig, t, events, invoked_prev,
                      pool_occ, inbox, deltas, part_active, violated):
    """Fold one tick into the flight recorder (no-op when disabled).

    Every array argument is batch-LEADING regardless of ``sim.layout`` —
    both tick paths hand over canonical-orientation deltas, so the
    recorder's math (and therefore the layout bit-identity the runtime
    guarantees) is shared, not duplicated. ``pool_occ`` is the [I]
    post-enqueue occupied-slot count (each layout sums its own VALID
    lane — int32 sums commute exactly, so the figure is layout-
    identical without transposing the full pool); ``invoked_prev`` the
    pre-tick per-client invocation ticks [I, C]."""
    if tel is None:
        return None
    N = sim.net.n_nodes
    n_sent, n_del, n_dropp, n_lost, n_ovf = deltas
    serv = inbox[:, :N]
    n_del_serv = jnp.sum(
        (serv[..., wire.VALID] == 1) & (serv[..., wire.ORIGIN] < N),
        axis=(1, 2)).astype(jnp.int32)
    return flight.record_tick(
        tel, t, sim.telemetry,
        n_sent=n_sent, n_del=n_del, n_del_serv=n_del_serv,
        n_dropp=n_dropp, n_lost=n_lost, n_ovf=n_ovf,
        pool_occ=pool_occ,
        part_active=part_active, violated=violated,
        ok_mask=events[:, :, 0, EV_TYPE] == EV_OK,
        invoke_mask=events[:, :, 1, EV_TYPE] == EV_INVOKE,
        lat=t - invoked_prev)


def make_tick_fn(model: Model, sim: SimConfig, params,
                 instance_ids=None) -> Callable:
    cfg = sim.net
    ccfg = sim.client
    nem = sim.nemesis
    N = cfg.n_nodes
    I = sim.n_instances
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)

    if sim.layout == "minor":
        from ..ops.delivery import pallas_enabled
        if pallas_enabled():
            import warnings
            warnings.warn(
                "MAELSTROM_TPU_PALLAS is set but the batch-minor carry "
                "layout has no Pallas delivery kernel — running the XLA "
                "path; use layout='lead' to benchmark the Pallas kernel")
        return _make_tick_fn_minor(model, sim, params, instance_ids)

    def tick_fn(carry: Carry, t):
        key = carry.key

        # fault plan: select tick t's planes (static no-op when the
        # plan has no lanes — NO_PLANES keeps every branch below on
        # the pre-fault path). Fuzz runs select PER-INSTANCE planes
        # from the carried randomized schedules instead — every plane
        # below then carries a leading instance axis.
        fx = sim.faults
        fuzz_on = fx.has_fuzz
        with jax.named_scope("faults"):
            if fuzz_on:
                planes = jax.vmap(
                    lambda s: faults_fuzz.schedule_planes(
                        s, fx, cfg, t))(carry.fault_sched)
            else:
                planes = (faults_engine.tick_planes(fx, cfg, t)
                          if fx.active else NO_PLANES)
            node_state_in = carry.node_state
            snapshots = carry.snapshots
            if planes.crash is not None:
                # crash-restart: victims held in reset — rebuilt from
                # their snapshot-slab row (or cold) every crashed tick
                wipe_keys = _instance_keys(key, _RNG_RESTART,
                                           instance_ids, t)
                if fuzz_on and planes.t_nodes is not None:
                    node_state_in = jax.vmap(
                        lambda st, sn, k, cm, tv:
                        faults_engine.wipe_crashed(
                            model, st, sn, cm, tv, k, cfg, params))(
                        node_state_in, snapshots, wipe_keys,
                        planes.crash, planes.t_nodes)
                elif fuzz_on:
                    tvec = jnp.broadcast_to(t, (N,)).astype(jnp.int32)
                    node_state_in = jax.vmap(
                        lambda st, sn, k, cm:
                        faults_engine.wipe_crashed(
                            model, st, sn, cm, tvec, k, cfg, params))(
                        node_state_in, snapshots, wipe_keys,
                        planes.crash)
                else:
                    tvec = (planes.t_nodes
                            if planes.t_nodes is not None
                            else jnp.broadcast_to(t, (N,))
                            .astype(jnp.int32))
                    node_state_in = jax.vmap(
                        lambda st, sn, k: faults_engine.wipe_crashed(
                            model, st, sn, planes.crash, tvec, k, cfg,
                            params))(node_state_in, snapshots,
                                     wipe_keys)
            # membership lane: non-(stable-)members are parked at
            # their join_row (slab recovery + the CURRENT target
            # bitmask) — the park mask covers every non-member tick
            # plus the join edge itself, so a joining node's final
            # rebuild is provisioned with the bitmask including it
            m_bits = None
            if planes.member is not None:
                park_keys = _instance_keys(key, _RNG_RESTART,
                                           instance_ids, t)
                if fuzz_on:
                    m_bits = jax.vmap(faults_engine.member_bits)(
                        planes.member)
                    park = ~(planes.member & planes.member_prev)
                    if planes.t_nodes is not None:
                        node_state_in = jax.vmap(
                            lambda st, sn, k, pm, mb, tv:
                            faults_engine.wipe_parked(
                                model, st, sn, pm, mb, tv, k, cfg,
                                params))(
                            node_state_in, snapshots, park_keys, park,
                            m_bits, planes.t_nodes)
                    else:
                        tvec_m = jnp.broadcast_to(t, (N,)) \
                            .astype(jnp.int32)
                        node_state_in = jax.vmap(
                            lambda st, sn, k, pm, mb:
                            faults_engine.wipe_parked(
                                model, st, sn, pm, mb, tvec_m, k, cfg,
                                params))(
                            node_state_in, snapshots, park_keys, park,
                            m_bits)
                else:
                    m_bits = faults_engine.member_bits(planes.member)
                    park = ~(planes.member & planes.member_prev)
                    tvec_m = (planes.t_nodes
                              if planes.t_nodes is not None
                              else jnp.broadcast_to(t, (N,))
                              .astype(jnp.int32))
                    node_state_in = jax.vmap(
                        lambda st, sn, k: faults_engine.wipe_parked(
                            model, st, sn, park, m_bits, tvec_m, k,
                            cfg, params))(node_state_in, snapshots,
                                          park_keys)

        # nemesis keys are t-INdependent: partition_matrix folds in the
        # phase index itself, so a grudge holds for its whole phase (the
        # reference draws one grudge per nemesis op, nemesis.clj) instead
        # of flapping every tick
        with jax.named_scope("nemesis"):
            ikeys = _instance_keys(key, _RNG_NEMESIS, instance_ids)
            partitions = jax.vmap(
                lambda ik: partition_matrix(nem, cfg, t, ik))(ikeys)
            if planes.block is not None:
                # fault-plan edge blocks (asymmetric links + crashed
                # receivers) fold into the delivery partition plane
                partitions = partitions | (planes.block if fuzz_on
                                           else planes.block[None])

        from ..ops.delivery import _interpret, deliver_pallas, \
            pallas_enabled
        with jax.named_scope("deliver"):
            if pallas_enabled():
                # hand-fused VMEM kernel for the delivery hot op (ops/)
                pool, inbox, n_del_i, n_dropp_i = deliver_pallas(
                    carry.pool, partitions, t, cfg,
                    interpret=_interpret())
                n_del, n_dropp = n_del_i, n_dropp_i
            else:
                pool, inbox, n_del, n_dropp = jax.vmap(
                    lambda p, pa: netsim.deliver(p, pa, t, cfg))(
                        carry.pool, partitions)

        with jax.named_scope("node_phase"):
            node_keys = _instance_keys(key, _RNG_NODE, instance_ids, t)
            fuzz_tn = fuzz_on and planes.t_nodes is not None
            fuzz_mb = fuzz_on and m_bits is not None
            if fuzz_tn and fuzz_mb:
                node_state, node_outs = jax.vmap(
                    lambda st, ib, k, tn, mb: node_phase(
                        model, st, ib, t, k, cfg, params, t_nodes=tn,
                        m_bits=mb))(
                    node_state_in, inbox[:, :N], node_keys,
                    planes.t_nodes, m_bits)
            elif fuzz_tn:
                # per-instance local clocks under the fuzzed skew lane
                node_state, node_outs = jax.vmap(
                    lambda st, ib, k, tn: node_phase(
                        model, st, ib, t, k, cfg, params, t_nodes=tn))(
                    node_state_in, inbox[:, :N], node_keys,
                    planes.t_nodes)
            elif fuzz_mb:
                node_state, node_outs = jax.vmap(
                    lambda st, ib, k, mb: node_phase(
                        model, st, ib, t, k, cfg, params,
                        t_nodes=planes.t_nodes, m_bits=mb))(
                    node_state_in, inbox[:, :N], node_keys, m_bits)
            else:
                node_state, node_outs = jax.vmap(
                    lambda st, ib, k: node_phase(
                        model, st, ib, t, k, cfg, params,
                        t_nodes=planes.t_nodes, m_bits=m_bits))(
                    node_state_in, inbox[:, :N], node_keys)

        invoked_prev = carry.client_state.invoked
        with jax.named_scope("client_step"):
            client_keys = _instance_keys(key, _RNG_CLIENT, instance_ids, t)
            client_state, reqs, events = jax.vmap(
                lambda cs, ib, k: client_step(model, cs, ib, t, k, cfg,
                                              ccfg, params))(
                    carry.client_state, inbox[:, N:], client_keys)

        with jax.named_scope("enqueue"):
            if planes.crash is not None:
                # a dead process sends nothing: invalidate the victims'
                # emitted rows before they reach the wire
                cmask = (~planes.crash).astype(jnp.int32)
                node_outs = node_outs.at[..., wire.VALID].mul(
                    cmask[:, :, None] if fuzz_on
                    else cmask[None, :, None])
            if planes.member is not None:
                # parked non-members send nothing, and clients only
                # target nodes that exist (identity when all-member)
                mmask = planes.member.astype(jnp.int32)
                node_outs = node_outs.at[..., wire.VALID].mul(
                    mmask[:, :, None] if fuzz_on
                    else mmask[None, :, None])
                if fuzz_on:
                    reqs = jax.vmap(faults_engine.retarget_clients)(
                        reqs, planes.member)
                else:
                    reqs = jax.vmap(
                        lambda r: faults_engine.retarget_clients(
                            r, planes.member))(reqs)
            outs = jnp.concatenate(
                [node_outs.reshape(I, -1, cfg.lanes), reqs], axis=1)
            # stamp network-unique message ids (send-time allocation, the
            # role of net.clj:196-201's ID counter): unique per instance.
            # Only journaling formats carry the lane — the narrow default
            # skips the full-row restamp entirely (tpu/wire.py)
            M = outs.shape[1]
            if cfg.netid:
                outs = outs.at[:, :, cfg.netid_lane].set(
                    t * M + jnp.arange(M, dtype=jnp.int32)[None, :])
            enq_keys = _instance_keys(key, _RNG_ENQUEUE, instance_ids, t)
            if fuzz_on and planes.delay is not None:
                # per-instance degraded-edge planes
                pool, n_sent, n_lost, n_ovf = jax.vmap(
                    lambda p, m, k, d, l: netsim.enqueue(
                        p, m, t, k, cfg, edge_delay=d,
                        edge_loss_pm=l))(
                    pool, outs, enq_keys, planes.delay, planes.loss_pm)
            else:
                pool, n_sent, n_lost, n_ovf = jax.vmap(
                    lambda p, m, k: netsim.enqueue(
                        p, m, t, k, cfg, edge_delay=planes.delay,
                        edge_loss_pm=planes.loss_pm))(
                        pool, outs, enq_keys)

        if snapshots is not None:
            with jax.named_scope("faults"):
                # held nodes (crashed or parked) never overwrite their
                # slab row — it keeps the leave-point state the next
                # restart/join restores
                hold = planes.crash
                if planes.member is not None:
                    park = ~(planes.member & planes.member_prev)
                    hold = park if hold is None else (hold | park)
                if fuzz_on:
                    snapshots = jax.vmap(
                        lambda st, sn, cm:
                        faults_engine.update_snapshots(
                            model, st, sn, cm, t, fx.snapshot_every))(
                        node_state, snapshots, hold)
                else:
                    snapshots = jax.vmap(
                        lambda st, sn: faults_engine.update_snapshots(
                            model, st, sn, hold, t,
                            fx.snapshot_every))(node_state, snapshots)

        stats = NetStats(
            sent=carry.stats.sent + jnp.sum(n_sent),
            delivered=carry.stats.delivered + jnp.sum(n_del),
            dropped_partition=carry.stats.dropped_partition
            + jnp.sum(n_dropp),
            dropped_loss=carry.stats.dropped_loss + jnp.sum(n_lost),
            dropped_overflow=carry.stats.dropped_overflow + jnp.sum(n_ovf),
        )
        violated = jax.vmap(
            lambda st: model.invariants(st, cfg, params))(node_state)
        summ = device_summary.update_summary(
            model, carry.check_summary, node_state, events, n_sent,
            n_del, cfg, params, state_axis=0)
        with jax.named_scope("telemetry"):
            tel = _update_telemetry(
                carry.telemetry, sim, t, events, invoked_prev,
                netsim.pool_occupancy(pool),
                inbox, (n_sent, n_del, n_dropp, n_lost, n_ovf),
                jnp.any(partitions, axis=(1, 2)), violated)
        new_carry = Carry(pool=pool, node_state=node_state,
                          client_state=client_state, stats=stats,
                          violations=carry.violations
                          + violated.astype(jnp.int32),
                          key=key, telemetry=tel, snapshots=snapshots,
                          fault_sched=carry.fault_sched,
                          check_summary=summ)
        J = sim.journal_instances
        R = sim.record_instances
        ys = TickOutputs(
            events=events[:R] if R > 0 else None,
            journal_sends=outs[:J] if J > 0 else None,
            journal_recvs=inbox[:J] if J > 0 else None,
        )
        return new_carry, ys

    return tick_fn


def _make_tick_fn_minor(model: Model, sim: SimConfig, params,
                        instance_ids) -> Callable:
    """The batch-LAST tick: one composite per-instance tick function,
    vmapped once with ``in_axes/out_axes=-1`` on every state array, so
    the instance axis is minormost everywhere.

    Why: on TPU, arrays tile on their last two dims in (8, 128) blocks.
    The lead layout's per-instance trailing dims are tiny (lanes ~15,
    pool slots ~16), so every HBM round-trip of pool/state/intermediates
    pads the 128-lane axis ~8x. With instances minormost the lane axis
    is the (large, 128-divisible) batch — no padding, and the whole tick
    fuses into instance-parallel vector code. Per-instance math is the
    SAME traced code as the lead path (same phases, same RNG fold
    order), so trajectories are bit-identical; tests/test_layouts.py and
    tools/platform_xval.py hold both paths to that.
    """
    cfg = sim.net
    ccfg = sim.client
    nem = sim.nemesis
    N = cfg.n_nodes

    fx = sim.faults
    fuzz_on = fx.has_fuzz

    def tick_one(pool, node_row, client_row, snap_row, sched_row,
                 instance_id, master, t):
        """One instance's full tick. pool [S, L]; returns the new
        per-instance state plus this tick's outputs and stat deltas."""
        with jax.named_scope("faults"):
            # deterministic-plan planes depend only on t (shared plan),
            # so under the instance vmap they stay unbatched — computed
            # once; fuzz planes select from THIS instance's carried
            # randomized schedule, so they batch with the state
            if fuzz_on:
                planes = faults_fuzz.schedule_planes(sched_row, fx,
                                                     cfg, t)
            else:
                planes = (faults_engine.tick_planes(fx, cfg, t)
                          if fx.active else NO_PLANES)
            if planes.crash is not None:
                tvec = (planes.t_nodes if planes.t_nodes is not None
                        else jnp.broadcast_to(t, (N,)).astype(jnp.int32))
                wipe_key = jax.random.fold_in(jax.random.fold_in(
                    jax.random.fold_in(master, _RNG_RESTART), t),
                    instance_id)
                node_row = faults_engine.wipe_crashed(
                    model, node_row, snap_row, planes.crash, tvec,
                    wipe_key, cfg, params)
            m_bits = None
            if planes.member is not None:
                m_bits = faults_engine.member_bits(planes.member)
                park = ~(planes.member & planes.member_prev)
                tvec_m = (planes.t_nodes
                          if planes.t_nodes is not None
                          else jnp.broadcast_to(t, (N,))
                          .astype(jnp.int32))
                park_key = jax.random.fold_in(jax.random.fold_in(
                    jax.random.fold_in(master, _RNG_RESTART), t),
                    instance_id)
                node_row = faults_engine.wipe_parked(
                    model, node_row, snap_row, park, m_bits, tvec_m,
                    park_key, cfg, params)
        with jax.named_scope("nemesis"):
            nem_key = jax.random.fold_in(
                jax.random.fold_in(master, _RNG_NEMESIS), instance_id)
            partitions = partition_matrix(nem, cfg, t, nem_key)
            if planes.block is not None:
                partitions = partitions | planes.block
        with jax.named_scope("deliver"):
            pool, inbox, n_del, n_dropp = netsim.deliver(pool, partitions,
                                                         t, cfg)

        with jax.named_scope("node_phase"):
            node_key = jax.random.fold_in(jax.random.fold_in(
                jax.random.fold_in(master, _RNG_NODE), t), instance_id)
            node_row, node_outs = node_phase(model, node_row, inbox[:N], t,
                                             node_key, cfg, params,
                                             t_nodes=planes.t_nodes,
                                             m_bits=m_bits)

        with jax.named_scope("client_step"):
            client_key = jax.random.fold_in(jax.random.fold_in(
                jax.random.fold_in(master, _RNG_CLIENT), t), instance_id)
            client_row, reqs, events = client_step(model, client_row,
                                                   inbox[N:], t,
                                                   client_key, cfg, ccfg,
                                                   params)

        with jax.named_scope("enqueue"):
            if planes.crash is not None:
                node_outs = node_outs.at[..., wire.VALID].mul(
                    (~planes.crash).astype(jnp.int32)[:, None])
            if planes.member is not None:
                node_outs = node_outs.at[..., wire.VALID].mul(
                    planes.member.astype(jnp.int32)[:, None])
                reqs = faults_engine.retarget_clients(reqs,
                                                      planes.member)
            outs = jnp.concatenate(
                [node_outs.reshape(-1, cfg.lanes), reqs], axis=0)
            M = outs.shape[0]
            if cfg.netid:
                outs = outs.at[:, cfg.netid_lane].set(
                    t * M + jnp.arange(M, dtype=jnp.int32))
            enq_key = jax.random.fold_in(jax.random.fold_in(
                jax.random.fold_in(master, _RNG_ENQUEUE), t), instance_id)
            pool, n_sent, n_lost, n_ovf = netsim.enqueue(
                pool, outs, t, enq_key, cfg, edge_delay=planes.delay,
                edge_loss_pm=planes.loss_pm)
        if snap_row is not None:
            with jax.named_scope("faults"):
                hold = planes.crash
                if planes.member is not None:
                    park = ~(planes.member & planes.member_prev)
                    hold = park if hold is None else (hold | park)
                snap_row = faults_engine.update_snapshots(
                    model, node_row, snap_row, hold, t,
                    fx.snapshot_every)
        violated = model.invariants(node_row, cfg, params)
        return (pool, node_row, client_row, snap_row,
                (n_sent, n_del, n_dropp, n_lost, n_ovf),
                violated, jnp.any(partitions), events, outs, inbox)

    # state rides at axis -1; per-tick outputs (events/journal rows,
    # stat deltas, violations) come out batch-LEADING so the downstream
    # record slices ([:R], [:J]) and [I]-shaped accumulators are
    # identical to the lead path's
    batched = jax.vmap(
        tick_one,
        in_axes=(-1, -1, -1, -1, -1, 0, None, None),
        out_axes=(-1, -1, -1, -1, 0, 0, 0, 0, 0, 0))

    def tick_fn(carry: Carry, t):
        invoked_prev = jnp.moveaxis(carry.client_state.invoked, -1, 0)
        (pool, node_state, client_state, snapshots, deltas, violated,
         part_active, events, outs, inbox) = batched(
             carry.pool, carry.node_state, carry.client_state,
             carry.snapshots, carry.fault_sched, instance_ids,
             carry.key, t)
        n_sent, n_del, n_dropp, n_lost, n_ovf = deltas
        stats = NetStats(
            sent=carry.stats.sent + jnp.sum(n_sent),
            delivered=carry.stats.delivered + jnp.sum(n_del),
            dropped_partition=carry.stats.dropped_partition
            + jnp.sum(n_dropp),
            dropped_loss=carry.stats.dropped_loss + jnp.sum(n_lost),
            dropped_overflow=carry.stats.dropped_overflow + jnp.sum(n_ovf),
        )
        with jax.named_scope("telemetry"):
            # occupancy from the minor pool [S, L, I] directly — the
            # old full-pool moveaxis materialized an [I, S, L] copy
            # every tick just to sum one lane
            tel = _update_telemetry(
                carry.telemetry, sim, t, events, invoked_prev,
                jnp.sum(pool[:, wire.VALID, :] & 1, axis=0
                        ).astype(jnp.int32),
                inbox, deltas, part_active, violated)
        # summary lanes: node_state is batch-LAST here; the per-instance
        # summary_step trace is shared with the lead path via the
        # state_axis vmap spec, so lanes stay layout bit-identical
        summ = device_summary.update_summary(
            model, carry.check_summary, node_state, events, n_sent,
            n_del, cfg, params, state_axis=-1)
        new_carry = Carry(pool=pool, node_state=node_state,
                          client_state=client_state, stats=stats,
                          violations=carry.violations
                          + violated.astype(jnp.int32),
                          key=carry.key, telemetry=tel,
                          snapshots=snapshots,
                          fault_sched=carry.fault_sched,
                          check_summary=summ)
        J = sim.journal_instances
        R = sim.record_instances
        ys = TickOutputs(
            events=events[:R] if R > 0 else None,
            journal_sends=outs[:J] if J > 0 else None,
            journal_recvs=inbox[:J] if J > 0 else None,
        )
        return new_carry, ys

    return tick_fn


def simulate(model: Model, sim: SimConfig, seed, params=None,
             instance_ids=None) -> Tuple[Carry, TickOutputs]:
    """Traceable simulation body (used directly inside shard_map);
    returns (final carry, TickOutputs with a leading T axis — events
    [T, R, C, 2, 2 + model.ev_vals], journal sends/recvs for the first
    ``journal_instances`` instances).

    ``instance_ids`` ([sim.n_instances] int32, default ``arange``) names
    the instances being simulated: instance ``i``'s trajectory depends
    only on (seed, ``instance_ids[i]``), so passing the violating ids
    from a big sweep replays exactly those clusters bit-for-bit."""
    carry = init_carry(model, sim, seed, params, instance_ids)
    tick_fn = make_tick_fn(model, sim, params, instance_ids)
    return jax.lax.scan(tick_fn, carry,
                        jnp.arange(sim.n_ticks, dtype=jnp.int32))


@partial(jax.jit, static_argnames=("model", "sim"))
def run_sim(model: Model, sim: SimConfig, seed: int, params=None,
            instance_ids=None) -> Tuple[Carry, TickOutputs]:
    """Jitted single-device entry point around :func:`simulate`."""
    return simulate(model, sim, seed, params, instance_ids)
