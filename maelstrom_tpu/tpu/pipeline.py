"""Chunked, donated, double-buffered executor for the device tick loop.

The monolithic path (:func:`..tpu.runtime.run_sim`) issues the whole
horizon as ONE device dispatch and fetches a dense per-tick event tensor
``[T, R, C, 2, 2 + ev_vals]`` afterwards — the host checker pipeline
then runs strictly *after* ``block_until_ready``, and the dense tensor
is almost entirely empty rows (at the default 100 ops/s fewer than 1%
of ticks carry an event). This module replaces that with the
production dispatch pattern:

- the scan is issued in ~``chunk_ticks``-tick chunks, jitted once per
  chunk length with ``donate_argnums`` on the carry, so the carry
  buffers are reused in place and never round-trip the host;
- chunk *k + 1* is dispatched **before** chunk *k*'s outputs are
  fetched — JAX dispatch is asynchronous, so the host's fetch + decode
  + check work on chunk *k* overlaps the device compute of chunk
  *k + 1* (the decoupling/pipelining move of Compartmentalized MultiPaxos,
  arXiv:2012.15762, applied to the simulator's own dispatch loop);
- instead of the dense event tensor, each chunk emits a fixed-capacity
  **compacted** event buffer: one ``[cap, 3 + ev_vals]`` int32 block of
  ``(tick, loc, etype, vals...)`` rows plus a row count, built on
  device by a mask prefix-sum scatter (``loc`` packs the dense
  ``(r, c, slot)`` coordinates). Device scan-ys memory and host fetch
  bytes drop by the event sparsity (~10x at default record/rate
  settings), which is what raises the max ticks x instances per chip.
  Overflow (more events in a chunk than ``cap``) is *flagged*, never
  silent: the row count keeps counting past the capacity.

Trajectories are bit-identical to the monolithic scan by construction:
the tick function depends only on ``(carry, t)``, every carry leaf is
int32/uint32 (no float accumulators), and compaction only *reads* the
tick's event output. ``tests/test_pipeline.py`` holds both carry
layouts to that, plus compacted-vs-dense equality and donation safety.

The chunk *driver* (:func:`run_chunked`) is shared with
``parallel/mesh.py``'s sharded runner so single-device, mesh, and bench
paths all use one donation-correct loop.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .runtime import (Carry, EV_NONE, Model, SimConfig, TickOutputs,
                      default_instance_ids, init_carry, make_tick_fn)

# --- chunk planning -------------------------------------------------------


def plan_chunks(n_ticks: int, chunk: int) -> List[Tuple[int, int]]:
    """Split ``n_ticks`` into ``(t0, length)`` dispatch plans.

    A trailing partial chunk would force a SECOND full compile of the
    chunk function (scan length is static), so when ``chunk`` does not
    divide the horizon a nearby divisor (down to ``chunk // 2``) is
    preferred; failing that, the tail chunk pays one extra compile.
    """
    chunk = max(1, min(chunk, n_ticks))
    if n_ticks % chunk:
        for c in range(chunk, max(chunk // 2, 1), -1):
            if n_ticks % c == 0:
                chunk = c
                break
    plans = []
    t = 0
    while t < n_ticks:
        use = min(chunk, n_ticks - t)
        plans.append((t, use))
        t += use
    return plans


def run_chunked(state0: Any, plans: List[Tuple[int, int]],
                dispatch: Callable[[Any, int, int], Tuple[Any, Any]],
                consume: Optional[Callable[[Any, int, int], None]] = None,
                should_stop: Optional[Callable[[], bool]] = None,
                checkpoint: Optional[Callable[[Any, int, int],
                                              None]] = None,
                checkpoint_every: int = 0,
                ) -> Tuple[Any, Dict[str, float]]:
    """The double-buffered chunk loop shared by every chunked runner.

    ``dispatch(state, t0, length) -> (state, payload)`` issues one
    (asynchronous) device chunk; ``consume(payload, t0, length)``
    fetches/decodes chunk *k*'s payload and is called AFTER chunk
    *k + 1* has been dispatched, so host-side consumption overlaps
    device compute. Returns the final state and wall-clock stats:
    ``first-dispatch-s`` (compile-inclusive), ``dispatch-s`` (steady
    issue time), ``consume-s`` (host fetch + decode).

    ``should_stop`` is polled after each consume (i.e. after chunk *k*'s
    payload has been inspected, with chunk *k + 1* already in flight):
    returning True stops further dispatches — at most ONE chunk runs
    past the one whose payload raised the stop (the ``--fail-fast``
    contract). The already-dispatched chunk is still consumed, so its
    heartbeat/events are not lost. Stats then carry
    ``stopped-early: True`` and ``ticks-dispatched`` reports the ticks
    actually issued.

    ``checkpoint(state, ticks_dispatched, chunks_done)`` is called every
    ``checkpoint_every`` chunks (campaign/checkpoint.py's durable-resume
    sink). At a checkpoint the in-flight chunk is consumed FIRST — the
    host-side accumulators must cover exactly ``ticks_dispatched`` ticks
    for the snapshot to be a consistent cut — so a checkpoint chunk
    forgoes its fetch/compute overlap; amortized over K chunks. Stats
    carry ``checkpoints`` and ``checkpoint-s``.
    """
    stats: Dict[str, Any] = {"chunks": len(plans),
                             "first-dispatch-s": 0.0,
                             "dispatch-s": 0.0, "consume-s": 0.0}
    st = state0
    pending: Optional[Tuple[Any, int, int]] = None
    ticks_dispatched = plans[0][0] if plans else 0
    stopped = False
    n_ckpt = 0
    ckpt_s = 0.0
    for i, (t0, length) in enumerate(plans):
        tick0 = time.monotonic()
        st, payload = dispatch(st, t0, length)
        ticks_dispatched = t0 + length
        stats["chunks"] = i + 1
        dt = time.monotonic() - tick0
        stats["first-dispatch-s" if i == 0 else "dispatch-s"] += dt
        if pending is not None and consume is not None:
            tick0 = time.monotonic()
            consume(*pending)
            stats["consume-s"] += time.monotonic() - tick0
        pending = (payload, t0, length)
        if checkpoint is not None and checkpoint_every > 0 \
                and (i + 1) % checkpoint_every == 0 \
                and i + 1 < len(plans):
            # consistent cut: drain the in-flight payload so the host
            # accumulators match the carry's tick frontier, then save
            if consume is not None:
                tick0 = time.monotonic()
                consume(*pending)
                stats["consume-s"] += time.monotonic() - tick0
            pending = None
            tick0 = time.monotonic()
            checkpoint(st, ticks_dispatched, i + 1)
            ckpt_s += time.monotonic() - tick0
            n_ckpt += 1
        if should_stop is not None and should_stop():
            stopped = True
            break
    if pending is not None and consume is not None:
        tick0 = time.monotonic()
        consume(*pending)
        stats["consume-s"] += time.monotonic() - tick0
    stats["ticks-dispatched"] = ticks_dispatched
    if n_ckpt:
        stats["checkpoints"] = n_ckpt
        stats["checkpoint-s"] = ckpt_s
    if stopped:
        stats["stopped-early"] = True
    return st, stats


class ResumeState(NamedTuple):
    """A restored mid-run cut to continue dispatch from (built by
    ``campaign/checkpoint.py`` from an on-disk checkpoint).

    ``carry`` is the restored device pytree (single-device ``Carry``
    here; the sharded driver passes its wire carry), ``ticks`` the tick
    frontier it represents, ``chunks`` the absolute consumed-chunk
    cursor, and ``compact``/``journal``/``events`` the host-side
    accumulators covering ticks ``[0, ticks)`` — so the resumed run's
    decoded outputs span the FULL horizon, bit-identical to an
    uninterrupted run."""
    carry: Any
    ticks: int
    chunks: int = 0
    compact: Tuple[Tuple[np.ndarray, int], ...] = ()
    journal: Tuple[Tuple[np.ndarray, np.ndarray], ...] = ()
    events: Tuple[np.ndarray, ...] = ()


def resume_plans(n_ticks: int, chunk: int,
                 resume: Optional["ResumeState"]
                 ) -> List[Tuple[int, int]]:
    """The dispatch plan of a (possibly resumed) run: the full-horizon
    chunk plan, minus the prefix a resume already covers. Checkpoints
    are taken at chunk boundaries of the SAME plan, so the remainder is
    an exact suffix; a frontier off every boundary means the chunk plan
    changed between run and resume — refused (the concatenated segments
    could not be chunk-aligned, and chunk length is a compiled
    constant)."""
    plans = plan_chunks(n_ticks, chunk)
    if resume is None:
        return plans
    rest = [p for p in plans if p[0] >= resume.ticks]
    if resume.ticks >= n_ticks:
        return []
    if not rest or rest[0][0] != resume.ticks:
        raise ValueError(
            f"checkpoint tick frontier {resume.ticks} is not a chunk "
            f"boundary of plan_chunks({n_ticks}, {chunk}) — resume "
            f"with the original --chunk-ticks")
    return rest


# --- device-side first-violation scan -------------------------------------

# per-chunk top-K violation lanes reported by the heartbeat scan; the
# CLI's --scan-top-k overrides it (K=1 degenerates to the PR-4 argmin)
DEFAULT_SCAN_TOP_K = 8


def violation_scan(violations, telemetry, instance_ids,
                   k: int = 1) -> jnp.ndarray:
    """Reduce the fleet's invariant state to a ``[k, 3]`` int32 block —
    row *i* = ``[n_violating, tick_i, instance_i]`` for the *i*-th
    earliest violating instance — entirely on device, so the per-chunk
    heartbeat learns *where* a 100k-instance sweep went wrong without
    fetching any per-instance buffer.

    The cheap per-workload invariant lanes (``Model.invariants``: echo
    has none, g-set/raft carry lost-add / stale-read / commit-agreement
    witnesses) already accumulate into ``carry.violations`` every tick;
    with the flight recorder on, ``telemetry.first_violation`` holds
    each instance's first-trip tick and the scan sorts on it — row 0 is
    the EARLIEST tripper (ties break toward the lowest instance id, the
    stable-sort order), exactly the PR-4 argmin. Without telemetry the
    tick lane is -1 (violation known, tick unknown) and rows are the
    lowest-id trippers. Every row carries the fleet-wide count in lane
    0; rows past the number of trippers pad with ``instance = -1``.
    Traced; the result is a fresh (detached) array, safe to fetch after
    the carry is donated away."""
    tripped = violations > 0
    n = jnp.sum(tripped).astype(jnp.int32)
    ids = jnp.asarray(instance_ids, jnp.int32)
    big = jnp.int32(np.iinfo(np.int32).max)
    k = max(1, min(int(k), int(ids.shape[0])))
    if telemetry is not None:
        ft = telemetry.first_violation
        key = jnp.where(ft >= 0, ft, big)
    else:
        ft = None
        key = jnp.where(tripped, ids, big)
    order = jnp.argsort(key, stable=True)[:k]
    valid = jnp.arange(k, dtype=jnp.int32) < n
    ticks = (jnp.where(valid, ft[order], -1) if ft is not None
             else jnp.full((k,), -1, jnp.int32))
    insts = jnp.where(valid, ids[order], -1)
    return jnp.stack([jnp.full((k,), n, jnp.int32),
                      ticks.astype(jnp.int32),
                      insts.astype(jnp.int32)], axis=1)


# --- device-side event compaction ----------------------------------------


class CompactEvents(NamedTuple):
    """One chunk's compacted event stream (device side).

    ``rows[i] = (tick, loc, etype, vals[ev_vals])`` for the i-th
    nonempty event of the chunk, ``loc = (r * C + c) * 2 + slot`` (the
    flattened dense coordinates). ``count`` keeps counting past ``cap``
    — ``count > rows.shape[0]`` IS the overflow flag; overflowing rows
    are dropped by the scatter, never written out of bounds.
    """
    rows: Any       # [cap, 3 + ev_vals] int32
    count: Any      # [] int32 — total events seen (may exceed cap)


def compact_lanes(model: Model) -> int:
    return 3 + model.ev_vals


def event_capacity(sim: SimConfig, model: Model, chunk: int) -> int:
    """Auto-size the per-chunk compacted buffer from the client rate.

    Expected nonempty rows per chunk = 2 events (invoke + completion)
    per fired op; ops fire per client per tick with probability
    ``sim.client.rate``. 1.5x the expectation (floor 128, rounded up to
    64) is >8 sigma of the binomial at default settings; overflow is
    flagged, not silent, so a pathological config degrades loudly.
    Clamped to the dense row count — compaction can never need more.
    """
    R = sim.record_instances
    C = sim.client.n_clients
    dense_rows = chunk * R * C * 2
    expected = 2.0 * chunk * R * C * sim.client.rate
    cap = max(128, int(-(-1.5 * expected // 64)) * 64)
    return max(1, min(cap, dense_rows))


def _compact_tick(buf: CompactEvents, t, events, V: int) -> CompactEvents:
    """Fold one tick's dense events ``[R, C, 2, 2 + V]`` into the
    compacted buffer: mask prefix-sum assigns each nonempty event its
    output row; rows past capacity (and masked-out rows) scatter with
    ``mode='drop'``. Traced; int32 throughout."""
    cap = buf.rows.shape[0]
    flat = events.reshape(-1, events.shape[-1])          # [E, 2 + V]
    E = flat.shape[0]
    mask = flat[:, 0] != EV_NONE
    pos = buf.count + jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask, pos, cap)                      # cap -> dropped
    loc = jnp.arange(E, dtype=jnp.int32)
    new_rows = jnp.concatenate(
        [jnp.broadcast_to(t, (E,)).astype(jnp.int32)[:, None],
         loc[:, None], flat[:, 0:1], flat[:, 1:1 + V]], axis=1)
    rows = buf.rows.at[idx].set(new_rows, mode="drop")
    return CompactEvents(rows=rows,
                         count=buf.count
                         + jnp.sum(mask).astype(jnp.int32))


def fetch_compact_payload(buf: CompactEvents
                          ) -> Tuple[np.ndarray, int, bool]:
    """Host-fetch one chunk's compacted buffer: returns ``(rows, count,
    overflowed)``. The single place that knows the overflow convention
    (``count`` keeps counting past the capacity) — the harness executor
    and bench.py both account through it."""
    rows = np.asarray(buf.rows)
    n = int(buf.count)
    return rows, n, n > rows.shape[0]


def compact_payload_bytes(rows: np.ndarray) -> int:
    """Fetched bytes of one compacted chunk (rows + the count scalar +
    the detached stats vector ride in the same transfer class)."""
    return rows.nbytes + 8


def expand_compact_events(model: Model, sim: SimConfig,
                          chunks: List[Tuple[np.ndarray, int]],
                          n_ticks: Optional[int] = None,
                          instances: Optional[List[int]] = None
                          ) -> np.ndarray:
    """Host-side inverse of the compaction: rebuild the dense
    ``[T, R, C, 2, 2 + ev_vals]`` tensor from per-chunk compacted rows
    (``(rows, count)`` pairs in dispatch order). The msg-id lane is not
    carried by the compact stream and comes back zero — the history
    decoder never reads it (``events_to_histories`` drops ``ev[-1]``),
    so decoded histories are identical to the dense path's.

    ``instances`` selects a SUBSET of the recorded instances (by record
    index, in the order given): only their rows are expanded, into a
    ``[T, len(instances), C, 2, ...]`` tensor — ``maelstrom triage``
    rebuilds one flagged instance's history without materializing the
    fleet's full dense tensor."""
    T = sim.n_ticks if n_ticks is None else n_ticks
    R, C, V = sim.record_instances, sim.client.n_clients, model.ev_vals
    if instances is not None:
        remap = np.full((R,), -1, dtype=np.int64)
        for pos, r_idx in enumerate(instances):
            remap[int(r_idx)] = pos
        R_out = len(instances)
    else:
        remap = None
        R_out = R
    dense = np.zeros((T, R_out, C, 2, 2 + V), dtype=np.int32)
    for rows, count in chunks:
        n = min(int(count), rows.shape[0])
        used = np.asarray(rows[:n])
        if n == 0:
            continue
        t = used[:, 0]
        loc = used[:, 1]
        r, rem = np.divmod(loc, C * 2)
        c, slot = np.divmod(rem, 2)
        if remap is not None:
            r = remap[r]
            keep = r >= 0
            if not keep.all():
                t, r, c, slot = t[keep], r[keep], c[keep], slot[keep]
                used = used[keep]
        dense[t, r, c, slot, 0] = used[:, 2]
        dense[t, r, c, slot, 1:1 + V] = used[:, 3:3 + V]
    return dense


# --- the pipelined single-device executor ---------------------------------


class PipelineResult(NamedTuple):
    """Host-side outcome of :func:`run_sim_pipelined`.

    On a fail-fast stop the tick-axis arrays cover only the DISPATCHED
    prefix (``perf["ticks-dispatched"]`` ticks); the carry is the state
    after that prefix."""
    carry: Carry
    events: Optional[np.ndarray]  # dense [T, R, C, 2, 2 + ev_vals]
                                  # (None with dense_events=False —
                                  # the compact stream was consumed
                                  # directly, e.g. by the vectorized
                                  # decoder in tpu/decode.py)
    journal_sends: np.ndarray    # [T, J, M, L] (zero-size when J == 0)
    journal_recvs: np.ndarray    # [T, J, NT, K, L]
    perf: Dict[str, Any]         # chunk/overlap/fetch-byte stats
    scan: Optional[np.ndarray] = None   # final violation scan [K, 3]
                                        # (stream.SCAN_LANES per row)
    compact: Optional[List[Tuple[np.ndarray, int]]] = None
                                 # per-chunk compacted (rows, count),
                                 # kept only with keep_compact=True
                                 # (triage's instance-subset expansion)


@partial(jax.jit, static_argnames=("model", "sim"))
def _init_pipelined(model: Model, sim: SimConfig, seed, params,
                    instance_ids) -> Carry:
    return init_carry(model, sim, seed, params, instance_ids)


def make_chunk_fn(model: Model, sim: SimConfig, params, instance_ids,
                  cap: Optional[int], unroll: int,
                  scan_k: int = DEFAULT_SCAN_TOP_K):
    """Build the jitted, carry-donating chunk dispatch. The traced body
    wraps the runtime tick function: per tick the dense event block is
    folded into the compacted buffer instead of being stacked into the
    scan ys (events ys are skipped entirely when nothing is recorded).
    ``cap=None`` sizes the buffer per (static) chunk length via
    :func:`event_capacity` — right for callers whose dispatch length
    adapts at run time (bench.py). ``scan_k`` is the violation scan's
    top-K width.

    Public because it IS the production dispatch step: the IR/cost
    analyzer (``analysis/ir_lint.py``) lowers and compiles this exact
    callable to verify donation aliasing (JXP403) on the executable the
    fleet actually runs — not a re-lowered copy.
    """
    V = model.ev_vals
    R = sim.record_instances
    J = sim.journal_instances
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)
    tick = make_tick_fn(model, sim, params, instance_ids)

    @partial(jax.jit, static_argnames=("length",), donate_argnums=(0,))
    def chunk_fn(carry, t0, length):
        use_cap = cap if cap else event_capacity(sim, model, length)
        buf = CompactEvents(
            rows=jnp.zeros((use_cap, 3 + V), jnp.int32),
            count=jnp.int32(0)) if R > 0 else None

        def body(c_and_buf, t):
            c, b = c_and_buf
            c, ys = tick(c, t)
            if b is not None:
                b = _compact_tick(b, t, ys.events, V)
            outs = TickOutputs(events=None,
                               journal_sends=ys.journal_sends,
                               journal_recvs=ys.journal_recvs)
            return (c, b), outs

        (carry, buf), ys = jax.lax.scan(
            body, (carry, buf),
            t0 + jnp.arange(length, dtype=jnp.int32), unroll=unroll)
        journal = (ys.journal_sends, ys.journal_recvs) if J > 0 else None
        # detached NetStats snapshot ([5] int32, NetStats field order)
        # and top-K violation scan ([scan_k, 3] int32, stream.SCAN_LANES
        # per row): progress reporting / the run heartbeat can read them
        # without touching the carry the NEXT dispatch donates away
        # (bench.py's overlapped metric loop, telemetry/stream.py)
        stats_vec = jnp.stack(list(carry.stats))
        # with device verdict lanes on, the scan counts FLAGGED
        # instances (invariant trips OR summary flags): the heartbeat's
        # per-chunk count becomes the farm's prospective workload and
        # fail-fast trips on any device-detected suspicion — the
        # summary reduce rides the existing top-K machinery
        viol_src = carry.violations
        if carry.check_summary is not None:
            from ..checkers import device_summary
            viol_src = viol_src + (
                carry.check_summary[:, device_summary.L_FLAGS]
                != 0).astype(jnp.int32)
        scan_vec = violation_scan(viol_src, carry.telemetry,
                                  jnp.asarray(instance_ids, jnp.int32),
                                  k=scan_k)
        return carry, stats_vec, scan_vec, buf, journal

    return chunk_fn


# pre-rename alias (bench.py and older callers imported the underscore
# name before the IR analyzer made the builder part of the public API)
_make_chunk_fn = make_chunk_fn


def run_sim_pipelined(model: Model, sim: SimConfig, seed: int,
                      params=None, instance_ids=None,
                      chunk: int = 100, event_cap: Optional[int] = None,
                      unroll: int = 1, heartbeat=None,
                      fail_fast: bool = False,
                      keep_compact: bool = False,
                      scan_k: int = DEFAULT_SCAN_TOP_K,
                      checkpoint_cb=None, checkpoint_every: int = 0,
                      resume: Optional[ResumeState] = None,
                      event_sink=None, dense_events: bool = True,
                      check_mode: Optional[str] = None,
                      profiler=None, aot_store: Optional[str] = None
                      ) -> PipelineResult:
    """Chunked, donated, double-buffered replacement for
    :func:`..tpu.runtime.run_sim` + the dense event fetch.

    Dispatches the horizon in ``chunk``-tick pieces with the carry
    donated between dispatches; while chunk *k + 1* runs on device the
    host fetches chunk *k*'s compacted events. Returns the final carry,
    the reconstructed dense event tensor (bit-identical decode), the
    journal streams, and per-chunk dispatch/fetch/decode overlap stats
    including the fetched-vs-dense event byte counts.

    ``heartbeat`` (a :class:`..telemetry.stream.HeartbeatWriter`)
    receives one record per consumed chunk — cumulative NetStats, the
    device-computed first-violation scan, and the overflow flag; purely
    observational, trajectories are bit-identical with or without it.
    ``fail_fast`` stops dispatching once a consumed chunk's scan shows
    a tripped invariant (at most one further chunk is issued — it was
    already in flight); the returned tick-axis arrays then cover only
    ``perf["ticks-dispatched"]`` ticks and ``perf["stopped-early"]`` is
    set. ``keep_compact`` retains the per-chunk compacted rows on the
    result for instance-subset re-expansion (``maelstrom triage``).
    ``scan_k`` widens the per-chunk violation scan to the top-K earliest
    trippers (heartbeat ``violations`` lanes; K=1 is the argmin-only
    scan).

    ``checkpoint_cb(carry, ticks, host)`` receives, every
    ``checkpoint_every`` chunks, the carry at a consistent cut plus the
    host accumulators (``{"compact", "journal", "chunks"}``) — the
    campaign checkpoint sink (campaign/checkpoint.py). ``resume``
    continues a checkpointed run: dispatch starts at its tick frontier
    (the exact plan suffix, :func:`resume_plans`) and the returned
    events/journal cover the FULL horizon, bit-identical to an
    uninterrupted run.

    ``event_sink(rows, count, t0, length)`` receives each consumed
    chunk's fetched compact payload (the streaming host verdict
    pipeline — ``checkers/pool.py`` — decodes and checks chunk *k*
    while chunk *k + 1* computes; purely observational here, the
    executor keeps its own accumulators). ``dense_events=False`` skips
    the end-of-run dense-tensor reconstruction (``result.events`` is
    then None) for callers that consume the compact stream directly —
    the vectorized decoder never needs the dense form.

    ``check_mode`` (observational, heartbeat-only): with
    ``sim.check_summary`` on, each chunk record gains a ``check`` lane
    — the mode string plus the device-flagged instance count the
    per-chunk scan already carries (``maelstrom watch`` renders it as
    ``check[device flagged 3/100k]``).

    ``profiler`` (a :class:`..telemetry.profiler.DeviceProfiler`,
    observational): captured chunks dispatch under device-time
    measurement and their heartbeat records gain the ``device-ms``
    per-phase lane + ``device-s``; uncaptured chunks dispatch
    untouched. The capture's trace window is torn down on the
    exception path too (try/finally inside
    :meth:`~..telemetry.profiler.DeviceProfiler.capture`), so a
    mid-run checker blow-up never leaves the process-wide trace open.
    Trajectories are bit-identical with profiling on or off.

    ``aot_store`` (a directory, or None): consult the certified AOT
    executable store (``tpu/aot_store.py``) before dispatching — a hit
    deserializes the stored chunk executable and skips trace+compile
    entirely, a miss AOT-compiles and populates the entry. The store
    outcome lands under ``perf["aot"]`` ({hit, load-s, fingerprint});
    trajectories are bit-identical with the store on, off, warm, or
    cold.
    """
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if instance_ids is None:
        instance_ids = default_instance_ids(sim)
    R, C, V = sim.record_instances, sim.client.n_clients, model.ev_vals
    full_plans = plan_chunks(sim.n_ticks, chunk)
    plans = resume_plans(sim.n_ticks, chunk, resume)
    cap = (event_capacity(sim, model, full_plans[0][1])
           if not event_cap else int(event_cap))
    chunk_fn = make_chunk_fn(model, sim, params, instance_ids, cap,
                             unroll, scan_k=scan_k)
    aot_rec = None
    if aot_store is not None:
        from .aot_store import wrap_pipelined
        wrapped, aot_rec = wrap_pipelined(
            chunk_fn, model=model, sim=sim, params=params,
            instance_ids=instance_ids, cap=cap, unroll=unroll,
            scan_k=scan_k, store_dir=aot_store)
        if wrapped is not None:
            chunk_fn = wrapped

    t_init = time.monotonic()
    if resume is not None:
        # the restored cut — campaign/checkpoint.restore_carry already
        # copied each leaf into its own donation-safe buffer
        st = resume.carry
    else:
        # donation needs each leaf to own its buffer; init_carry
        # broadcasts shared zero blocks across leaves, so copy before
        # the first donate
        st = _init_pipelined(model, sim, jnp.int32(seed), params,
                             jnp.asarray(instance_ids, jnp.int32))
        st = jax.tree.map(lambda x: x.copy(), st)
    init_s = time.monotonic() - t_init

    compact_chunks: List[Tuple[np.ndarray, int]] = (
        [(np.asarray(r), int(n)) for r, n in resume.compact]
        if resume else [])
    journal_chunks: List[Tuple[np.ndarray, np.ndarray]] = (
        [(np.asarray(a), np.asarray(b)) for a, b in resume.journal]
        if resume else [])
    fetched_bytes = [0]
    fetch_s = [0.0]
    # prior segments' overflow flags persist (count > cap is the flag)
    overflowed = [sum(1 for r, n in compact_chunks
                      if n > r.shape[0])]
    chunk_idx = [resume.chunks if resume else 0]
    last_scan: List[Optional[np.ndarray]] = [None]
    tripped = [False]
    # fuzz runs: one host-side re-draw of the fleet's fault windows
    # feeds the heartbeat's per-chunk fault-fuzz lane — schedules are
    # pure functions of (seed, instance id), zero mid-run device
    # traffic (faults/fuzz.py)
    fuzz_windows = None
    if heartbeat is not None and sim.faults.has_fuzz:
        from ..faults import fuzz as faults_fuzz
        fuzz_windows = faults_fuzz.fleet_windows(
            sim.faults, sim.net.n_nodes, seed, instance_ids)

    # profiler state: the dispatch-side chunk cursor (consume's
    # chunk_idx lags one chunk behind) and the previous dispatch's
    # detached stats block — syncing on it before a captured dispatch
    # empties the device queue so the measurement covers only the
    # captured chunk (uncaptured chunks keep the fetch/compute overlap)
    dispatch_idx = [resume.chunks if resume else 0]
    sync_ref = [None]

    def dispatch(carry_st, t0, length):
        idx = dispatch_idx[0]
        dispatch_idx[0] += 1
        prof_rec = None
        if profiler is not None and profiler.should_capture(idx):
            (c, svec, scan, buf, journal), prof_rec = profiler.capture(
                chunk_fn, (carry_st, jnp.int32(t0), length), length,
                sync=sync_ref[0])
        else:
            c, svec, scan, buf, journal = chunk_fn(carry_st,
                                                   jnp.int32(t0),
                                                   length)
        sync_ref[0] = svec
        return c, (svec, scan, buf, journal, prof_rec)

    def consume(payload, t0, length):
        svec, scan, buf, journal, prof_rec = payload
        t_f = time.monotonic()
        ovf = False
        if buf is not None:
            # device fetch — overlaps the next chunk's compute
            rows, n, ovf = fetch_compact_payload(buf)
            fetched_bytes[0] += compact_payload_bytes(rows)
            overflowed[0] += int(ovf)
            compact_chunks.append((rows, n))
            if event_sink is not None:
                event_sink(rows, n, t0, length)
        if journal is not None:
            journal_chunks.append((np.asarray(journal[0]),
                                   np.asarray(journal[1])))
        scan_np = np.asarray(scan).reshape(-1, 3)
        last_scan[0] = scan_np
        if int(scan_np[0, 0]) > 0:
            tripped[0] = True
        if heartbeat is not None:
            from ..telemetry.stream import (scan_to_violation,
                                            scan_to_violations,
                                            stats_vec_to_net)
            extra = None
            if fuzz_windows is not None:
                # randomized schedules: how many instances' drawn fault
                # windows overlap this chunk, per lane
                from ..faults import fuzz as faults_fuzz
                extra = {"fault-fuzz": faults_fuzz.span_counters(
                    fuzz_windows, t0, length)}
            elif sim.faults.active:
                # the plan is deterministic and host-known: the chunk's
                # fault epoch costs no device traffic
                from ..faults.engine import span_summary
                extra = {"fault": span_summary(sim.faults, t0, length)}
            if sim.check_summary and check_mode:
                # the scan already counts flagged instances (summary
                # flags fold into its source) — no extra device traffic
                extra = dict(extra or {})
                extra["check"] = {"mode": check_mode,
                                  "flagged": int(scan_np[0, 0]),
                                  "of": sim.n_instances}
            if prof_rec is not None:
                # the device-time lane (telemetry/profiler.py): per-
                # phase ms for THIS chunk; `maelstrom watch` renders
                # it as dev[node 0.41 net 0.22 ...]
                extra = dict(extra or {})
                extra["device-ms"] = prof_rec["per-phase-ms"]
                extra["device-source"] = prof_rec["source"]
            heartbeat.record_chunk(
                chunk=chunk_idx[0], t0=t0, ticks=length,
                net=stats_vec_to_net(svec),
                violation=scan_to_violation(scan_np),
                violations=scan_to_violations(scan_np),
                overflowed=bool(ovf),
                device_s=(prof_rec["device-s"] if prof_rec is not None
                          else None),
                extra=extra)
        chunk_idx[0] += 1
        fetch_s[0] += time.monotonic() - t_f

    should_stop = (lambda: tripped[0]) if fail_fast else None
    checkpoint = None
    if checkpoint_cb is not None and checkpoint_every > 0:
        def checkpoint(carry_st, ticks, _chunks):
            checkpoint_cb(carry_st, ticks,
                          {"compact": list(compact_chunks),
                           "journal": list(journal_chunks),
                           "chunks": chunk_idx[0]})
    if plans:
        st, stats = run_chunked(st, plans, dispatch, consume,
                                should_stop, checkpoint=checkpoint,
                                checkpoint_every=checkpoint_every)
    else:
        # resume of an already-complete horizon: nothing to dispatch
        stats = {"chunks": 0, "first-dispatch-s": 0.0, "dispatch-s": 0.0,
                 "consume-s": 0.0,
                 "ticks-dispatched": resume.ticks if resume else 0}
    carry = jax.block_until_ready(st)
    ticks_done = stats["ticks-dispatched"]

    t_dec = time.monotonic()
    events = (expand_compact_events(model, sim, compact_chunks,
                                    n_ticks=ticks_done)
              if dense_events else None)
    decode_s = time.monotonic() - t_dec
    if journal_chunks:
        j_sends = np.concatenate([a for a, _ in journal_chunks], axis=0)
        j_recvs = np.concatenate([b for _, b in journal_chunks], axis=0)
    else:
        cfg = sim.net
        M = 0
        j_sends = np.zeros((ticks_done, 0, M, cfg.lanes), np.int32)
        j_recvs = np.zeros((ticks_done, 0, cfg.n_total, cfg.inbox_k,
                            cfg.lanes), np.int32)

    dense_bytes = ticks_done * R * C * 2 * (2 + V) * 4
    perf = {
        "chunk-ticks": full_plans[0][1],
        "event-capacity": cap,
        "init-s": round(init_s, 4),
        # fetch-s: device-to-host payload transfers, overlapped with
        # the next chunk's compute; decode-s: the host-side dense
        # reconstruction after the loop
        "fetch-s": round(fetch_s[0], 4),
        "decode-s": round(decode_s, 4),
        "event-bytes-fetched": fetched_bytes[0],
        "event-bytes-dense": dense_bytes,
        "fetch-reduction-x": round(dense_bytes / fetched_bytes[0], 1)
        if fetched_bytes[0] else None,
        "overflowed-chunks": overflowed[0],
        # the device-time roll-up (telemetry/profiler.py): per-phase
        # ms/tick over the captured chunks; the harness mirrors it to
        # results.perf.phases.device
        **({"device": profiler.summary()}
           if profiler is not None and profiler.records else {}),
        # the certified-store outcome (tpu/aot_store.py): hit means the
        # dispatched executable was deserialized, never traced/compiled
        **({"aot": dict(aot_rec,
                        **{"load-s": round(aot_rec["load-s"], 4)})}
           if aot_rec is not None else {}),
        **({"resumed-from-ticks": resume.ticks} if resume else {}),
        **{k: round(v, 4) if isinstance(v, float) else v
           for k, v in stats.items() if k != "consume-s"},
    }
    return PipelineResult(carry=carry, events=events,
                          journal_sends=j_sends, journal_recvs=j_recvs,
                          perf=perf, scan=last_scan[0],
                          compact=compact_chunks if keep_compact
                          else None)
