"""Results browser: the ``serve`` command's web UI.

Renders the store directory as browsable test results instead of a raw
listing — test runs grouped per workload, colored by verdict (blue =
valid, orange = unknown, pink = invalid — the color scheme of reference
doc/results.md:66-69), with per-run pages linking results.json,
histories, node logs, and rendered SVG artifacts inline.

Parity: reference ``serve`` (src/maelstrom/core.clj:273, backed by
jepsen's web UI per doc/results.md:7-9).
"""

from __future__ import annotations

import html
import http.server
import json
import os
from typing import Optional
from urllib.parse import unquote

STYLE = """
body { font-family: sans-serif; margin: 2em auto; max-width: 72em; }
a { text-decoration: none; }
table { border-collapse: collapse; }
td, th { padding: .3em .8em; text-align: left; }
tr:nth-child(even) { background: #f6f6f6; }
.valid { background: #cfe0f5; }
.unknown { background: #f5e0c0; }
.invalid { background: #f5c8d0; }
.badge { padding: .1em .5em; border-radius: .4em; font-size: .85em; }
pre { background: #f4f4f4; padding: 1em; overflow-x: auto; }
img { max-width: 100%; border: 1px solid #ddd; }
"""


def _verdict(run_dir: str) -> Optional[object]:
    for name in ("results.json",):
        p = os.path.join(run_dir, name)
        if os.path.exists(p):
            try:
                with open(p) as f:
                    return json.load(f).get("valid?")
            except (OSError, json.JSONDecodeError):
                return None
    return None


def _cls(verdict) -> str:
    if verdict is True:
        return "valid"
    if verdict == "unknown":
        return "unknown"
    if verdict is False:
        return "invalid"
    return ""


def _page(title: str, body: str) -> bytes:
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>{STYLE}</style>"
            f"</head><body><h1>{html.escape(title)}</h1>{body}"
            f"</body></html>").encode()


def _index(store: str) -> bytes:
    rows = []
    for wl in sorted(os.listdir(store)):
        wl_dir = os.path.join(store, wl)
        if not os.path.isdir(wl_dir):
            continue
        runs = sorted((r for r in os.listdir(wl_dir)
                       if r != "latest"
                       and os.path.isdir(os.path.join(wl_dir, r))),
                      reverse=True)
        for run in runs:
            v = _verdict(os.path.join(wl_dir, run))
            label = ("valid" if v is True else
                     "unknown" if v == "unknown" else
                     "invalid" if v is False else "?")
            rows.append(
                f"<tr class='{_cls(v)}'><td><a href='/{wl}/{run}/'>"
                f"{html.escape(wl)}</a></td>"
                f"<td><a href='/{wl}/{run}/'>{html.escape(run)}</a></td>"
                f"<td><span class='badge'>{label}</span></td></tr>")
    body = ("<table><tr><th>workload</th><th>run</th><th>valid?</th></tr>"
            + "".join(rows) + "</table>") if rows else "<p>No runs yet.</p>"
    return _page("maelstrom-tpu results", body)


def _run_page(store: str, wl: str, run: str) -> bytes:
    d = os.path.join(store, wl, run)
    v = _verdict(d)
    parts = [f"<p>verdict: <span class='badge {_cls(v)}'>{v}</span> "
             f"&middot; <a href='/'>&larr; all runs</a></p>"]
    files = sorted(os.listdir(d))
    svgs = [f for f in files if f.endswith(".svg")]
    others = [f for f in files if not f.endswith(".svg")]
    if others:
        parts.append("<h2>Artifacts</h2><ul>")
        for f in others:
            parts.append(f"<li><a href='/{wl}/{run}/{f}'>"
                         f"{html.escape(f)}</a></li>")
        parts.append("</ul>")
    rp = os.path.join(d, "results.json")
    if os.path.exists(rp):
        with open(rp) as f:
            try:
                content = json.dumps(json.load(f), indent=2)[:20000]
            except json.JSONDecodeError:
                content = "(unreadable)"
        parts.append(f"<h2>results.json</h2><pre>"
                     f"{html.escape(content)}</pre>")
    for f in svgs:
        parts.append(f"<h2>{html.escape(f)}</h2>"
                     f"<img src='/{wl}/{run}/{f}'>")
    return _page(f"{wl} / {run}", "".join(parts))


class ResultsHandler(http.server.SimpleHTTPRequestHandler):
    """Routes: / -> index; /<wl>/<run>/ -> run page; deeper paths serve
    raw files from the store directory."""

    def do_GET(self):  # noqa: N802 (stdlib naming)
        store = self.directory
        path = unquote(self.path.split("?", 1)[0])
        parts = [p for p in path.split("/") if p]
        if any(p in ("..", ".") or os.sep in p for p in parts):
            self.send_error(404)   # no escaping the store directory
            return
        if not parts:
            return self._send(_index(store))
        if len(parts) == 2:
            d = os.path.join(store, *parts)
            if os.path.isdir(d):
                return self._send(_run_page(store, parts[0], parts[1]))
        return super().do_GET()

    def _send(self, payload: bytes):
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *args):
        pass
