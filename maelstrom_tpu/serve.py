"""Results browser: the ``serve`` command's web UI.

Renders the store directory as browsable test results instead of a raw
listing — test runs grouped per workload, colored by verdict (blue =
valid, orange = unknown, pink = invalid — the color scheme of reference
doc/results.md:66-69), with per-run pages linking results.json,
histories, node logs, and rendered SVG artifacts inline.

Parity: reference ``serve`` (src/maelstrom/core.clj:273, backed by
jepsen's web UI per doc/results.md:7-9).
"""

from __future__ import annotations

import html
import http.server
import json
import os
from typing import Optional
from urllib.parse import unquote

STYLE = """
body { font-family: sans-serif; margin: 2em auto; max-width: 72em; }
a { text-decoration: none; }
table { border-collapse: collapse; }
td, th { padding: .3em .8em; text-align: left; }
tr:nth-child(even) { background: #f6f6f6; }
.valid { background: #cfe0f5; }
.unknown { background: #f5e0c0; }
.invalid { background: #f5c8d0; }
.badge { padding: .1em .5em; border-radius: .4em; font-size: .85em; }
pre { background: #f4f4f4; padding: 1em; overflow-x: auto; }
img { max-width: 100%; border: 1px solid #ddd; }
"""


def _verdict(run_dir: str) -> Optional[object]:
    # campaign dirs carry their verdict in the trend summary
    # (campaign/report.py); plain runs in results.json
    for name in ("results.json", "summary.json"):
        p = os.path.join(run_dir, name)
        if os.path.exists(p):
            try:
                with open(p) as f:
                    return json.load(f).get("valid?")
            except (OSError, json.JSONDecodeError):
                return None
    return None


def _cls(verdict) -> str:
    if verdict is True:
        return "valid"
    if verdict == "unknown":
        return "unknown"
    if verdict is False:
        return "invalid"
    return ""


def _page(title: str, body: str) -> bytes:
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>{STYLE}</style>"
            f"</head><body><h1>{html.escape(title)}</h1>{body}"
            f"</body></html>").encode()


def _index(store: str) -> bytes:
    rows = []
    for wl in sorted(os.listdir(store)):
        wl_dir = os.path.join(store, wl)
        if not os.path.isdir(wl_dir):
            continue
        runs = sorted((r for r in os.listdir(wl_dir)
                       if r != "latest"
                       and os.path.isdir(os.path.join(wl_dir, r))),
                      reverse=True)
        for run in runs:
            v = _verdict(os.path.join(wl_dir, run))
            label = ("valid" if v is True else
                     "unknown" if v == "unknown" else
                     "invalid" if v is False else "?")
            rows.append(
                f"<tr class='{_cls(v)}'><td><a href='/{wl}/{run}/'>"
                f"{html.escape(wl)}</a></td>"
                f"<td><a href='/{wl}/{run}/'>{html.escape(run)}</a></td>"
                f"<td><span class='badge'>{label}</span></td></tr>")
    body = ("<table><tr><th>workload</th><th>run</th><th>valid?</th></tr>"
            + "".join(rows) + "</table>") if rows else "<p>No runs yet.</p>"
    return _page("maelstrom-tpu results", body)


def _device_phases_cell(t: dict) -> str:
    """Per-phase device ms/tick trend, hottest first, for one trend row
    (empty when the campaign ran unprofiled)."""
    devp = t.get("device-phases-mean")
    if not devp:
        return "-"
    return " ".join(f"{ph} {ms:.4f}" for ph, ms in
                    sorted(devp.items(), key=lambda kv: -kv[1]))


def _campaign_tables(d: str) -> str:
    """The trend-store view of a campaign dir: per-item rows + the
    per-workload trend aggregation from summary.json (written by
    ``maelstrom campaign report``)."""
    try:
        with open(os.path.join(d, "summary.json")) as f:
            s = json.load(f)
    except (OSError, json.JSONDecodeError):
        return ("<p>(no summary.json yet — run "
                "<code>maelstrom campaign report</code>)</p>")
    parts = ["<h2>Items</h2><table><tr><th>item</th><th>workload</th>"
             "<th>seed</th><th>status</th><th>valid?</th><th>viol</th>"
             "<th>msgs/s</th><th>ir bytes/tick</th><th>dev ms/tick</th>"
             "<th>resumed</th><th>run</th></tr>"]
    for r in s.get("items", ()):
        v = r.get("valid?")
        run_dir = r.get("run-dir") or ""
        # run dirs live in the same store the server roots at; link
        # relatively when they do
        store_root = os.path.realpath(os.path.dirname(
            os.path.dirname(d)))
        rel = (os.path.relpath(os.path.realpath(run_dir), store_root)
               if run_dir else "")
        link = (f"<a href='/{html.escape(rel)}/'>"
                f"{html.escape(os.path.basename(run_dir))}</a>"
                if run_dir and not rel.startswith("..") else "")
        parts.append(
            f"<tr class='{_cls(v)}'><td>{r.get('id')}</td>"
            f"<td>{html.escape(str(r.get('workload')))}</td>"
            f"<td>{r.get('seed')}</td><td>{r.get('status')}</td>"
            f"<td>{v}</td><td>{r.get('violating-instances') or 0}</td>"
            f"<td>{r.get('msgs-per-sec') or '-'}</td>"
            f"<td>{r.get('ir-bytes-est') or '-'}</td>"
            f"<td>{r.get('device-ms-per-tick') or '-'}</td>"
            f"<td>{'yes' if r.get('resumed') else '-'}</td>"
            f"<td>{link}</td></tr>")
    parts.append("</table><h2>Trends (per workload)</h2><table>"
                 "<tr><th>workload</th><th>runs</th><th>done</th>"
                 "<th>valid</th><th>invalid</th><th>failed</th>"
                 "<th>viol</th><th>msgs/s mean</th><th>msgs/s max</th>"
                 "<th>ir bytes/tick</th><th>dev ms/tick</th>"
                 "<th>device phases</th></tr>")
    for wl in sorted(s.get("trends", {})):
        t = s["trends"][wl]
        cls = ("valid" if t["invalid"] == 0 and t["failed"] == 0
               and t["done"] == t["runs"] else
               "invalid" if t["invalid"] or t["failed"] else "")
        parts.append(
            f"<tr class='{cls}'><td>{html.escape(wl)}</td>"
            f"<td>{t['runs']}</td><td>{t['done']}</td>"
            f"<td>{t['valid']}</td><td>{t['invalid']}</td>"
            f"<td>{t['failed']}</td><td>{t['violating-instances']}</td>"
            f"<td>{t['msgs-per-sec-mean']}</td>"
            f"<td>{t['msgs-per-sec-max']}</td>"
            f"<td>{t.get('ir-bytes-est') or '-'}</td>"
            f"<td>{t.get('device-ms-per-tick-mean') or '-'}</td>"
            f"<td>{html.escape(_device_phases_cell(t))}</td></tr>")
    parts.append("</table>")
    return "".join(parts)


def _run_page(store: str, wl: str, run: str) -> bytes:
    d = os.path.join(store, wl, run)
    v = _verdict(d)
    parts = [f"<p>verdict: <span class='badge {_cls(v)}'>{v}</span> "
             f"&middot; <a href='/'>&larr; all runs</a></p>"]
    if os.path.exists(os.path.join(d, "campaign.json")):
        parts.append(_campaign_tables(d))
    files = sorted(os.listdir(d))
    svgs = [f for f in files if f.endswith(".svg")]
    others = [f for f in files if not f.endswith(".svg")]
    if others:
        parts.append("<h2>Artifacts</h2><ul>")
        for f in others:
            parts.append(f"<li><a href='/{wl}/{run}/{f}'>"
                         f"{html.escape(f)}</a></li>")
        parts.append("</ul>")
    rp = os.path.join(d, "results.json")
    if os.path.exists(rp):
        with open(rp) as f:
            try:
                content = json.dumps(json.load(f), indent=2)[:20000]
            except json.JSONDecodeError:
                content = "(unreadable)"
        parts.append(f"<h2>results.json</h2><pre>"
                     f"{html.escape(content)}</pre>")
    for f in svgs:
        parts.append(f"<h2>{html.escape(f)}</h2>"
                     f"<img src='/{wl}/{run}/{f}'>")
    return _page(f"{wl} / {run}", "".join(parts))


class ResultsHandler(http.server.SimpleHTTPRequestHandler):
    """Routes: / -> index; /<wl>/<run>/ -> run page; deeper paths serve
    raw files from the store directory."""

    def do_GET(self):  # noqa: N802 (stdlib naming)
        store = self.directory
        path = unquote(self.path.split("?", 1)[0])
        parts = [p for p in path.split("/") if p]
        if any(p in ("..", ".") or os.sep in p for p in parts):
            self.send_error(404)   # no escaping the store directory
            return
        if not parts:
            return self._send(_index(store))
        if len(parts) == 2:
            d = os.path.join(store, *parts)
            if os.path.isdir(d):
                return self._send(_run_page(store, parts[0], parts[1]))
        return super().do_GET()

    def _send(self, payload: bytes):
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *args):
        pass
