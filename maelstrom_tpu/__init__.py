"""maelstrom_tpu: a TPU-native distributed-systems testing workbench.

Two runtimes behind one workload boundary:

- **process runtime**: spawns user nodes as child processes speaking
  newline-delimited JSON over STDIN/STDOUT against an in-process simulated
  network with latency, loss, and partition fault injection.
- **TPU runtime**: workload protocol instances vectorized as rows of
  device-resident JAX state tensors; message delivery is a batched masked
  exchange inside a ``lax.scan``, sharded over chips with ``shard_map``.

See SURVEY.md for the structural map of the reference system
(jepsen-io/maelstrom) this framework reproduces.
"""

__version__ = "0.1.0"

from .runner import run_test  # noqa: F401
