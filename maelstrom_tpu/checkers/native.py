"""ctypes binding for the native WGL linearizability core (cpp/checker).

Builds ``libwgl.so`` on first use when a C++ toolchain is present (no
pybind11 in the image — plain C ABI via ctypes); every caller falls back
to the pure-Python search when the library is unavailable or reports an
unsupported shape, so the native path is a pure accelerator, never a
requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "cpp", "checker")
_LIB_PATH = os.path.join(_DIR, "libwgl.so")

_lib = None
_lib_tried = False

F_CODES = {"read": 1, "write": 2, "cas": 3}


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("MAELSTROM_TPU_NO_NATIVE") == "1":
        return None
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _DIR, "libwgl.so"],
                           capture_output=True, timeout=120, check=True)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        lib.wgl_check.restype = ctypes.c_int64
        lib.wgl_check.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int64, ctypes.c_int64,
                                  ctypes.c_int64]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def check_register_history_native(ops, budget_states: int
                                  ) -> Optional[object]:
    """Run one key's WGL check natively.

    ``ops`` is the Python checker's ``_Op`` list. Returns True / False /
    "unknown", or None when the native path can't handle it (library
    missing, non-int values, oversized segment) — the caller then uses
    the Python search.
    """
    lib = _load()
    if lib is None:
        return None

    # densify values to non-negative ints; nil -> -1
    table = {}

    def vid(v) -> Optional[int]:
        if v is None:
            return -1
        if v not in table:
            table[v] = len(table)
        return table[v]

    flat: List[int] = []
    try:
        for o in ops:
            f = F_CODES[o.f]
            if o.f == "cas":
                a, b = vid(o.args[0]), vid(o.args[1])
                ret = -1
            elif o.f == "write":
                a, b, ret = vid(o.args), -1, -1
            else:
                a, b = -1, -1
                ret = vid(o.ret) if o.required else -1
            end = -1 if o.end == float("inf") else int(o.end)
            flat += [f, a, b, ret, int(o.inv), end, 1 if o.required else 0]
    except (TypeError, KeyError):
        return None   # unhashable/odd values: Python handles those

    arr = (ctypes.c_int64 * len(flat))(*flat)
    rc = lib.wgl_check(arr, len(ops), -1, budget_states)
    if rc == 1:
        return True
    if rc == 0:
        return False
    if rc == -1:
        return "unknown"
    return None   # -2: unsupported shape
