"""Violation forensics: from a flagged run dir to per-instance evidence.

A checker violation among 100k device-resident instances used to end at
a number in results.json — no path back to the offending instance's
message history. ``maelstrom triage <run-dir>`` closes that loop:

1. **Select** the flagged instances — results.json's
   ``invariants.violating-instance-ids`` when the run completed, else
   ALL instances the streaming heartbeat's device-computed top-K
   violation scans named (telemetry/stream.py ``flagged_instances`` —
   up to ``--scan-top-k`` per chunk), so a run killed mid-horizon (or
   stopped by ``--fail-fast``) is still triageable.
2. **Replay** exactly those instances bit-exactly (the instance-stable
   RNG of tpu/runtime.py: a trajectory depends only on
   ``(seed, instance_id)``) with full event recording AND per-message
   journaling enabled, over exactly the ticks the original run
   dispatched — through the chunked executor, whose compacted event
   stream is re-expanded per instance via
   ``expand_compact_events(..., instances=[k])`` (the subset path: one
   instance's dense block at a time, never the whole fleet's).
3. **Render** each instance's evidence bundle under
   ``<run-dir>/triage/instance-<id>/``: ``messages.svg`` (the Lamport
   spacetime diagram of its actual message traffic, net/viz.py),
   ``journal.edn`` (the raw send/recv journal in Jepsen-compatible
   EDN), ``history.jsonl`` (the decoded op history), and
   ``repro.json`` (everything needed to replay this one instance —
   workload, seed, opts, instance id, and the equivalent API call).

The replay self-checks: each replayed instance's on-device invariants
must trip again (``replayed-violating`` in summary.json) — a mismatch
would mean the replay was not bit-exact, and is reported loudly.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

TRIAGE_DIR = "triage"
SUMMARY_FILE = "summary.json"


class TriageError(ValueError):
    """A run dir that cannot be triaged (missing or unusable inputs)."""


def load_run_info(run_dir: str) -> Dict[str, Any]:
    """Collect what the run dir knows about itself: results.json (when
    the run completed) and the heartbeat prefix (always present on
    heartbeat-enabled runs, even killed ones). Returns ``{run_dir,
    results, heartbeat, workload, opts, seed, ticks, chunk_ticks,
    flagged}`` — ``flagged`` ordered results-first (complete list),
    heartbeat-first-seen otherwise."""
    from ..telemetry.stream import (HEARTBEAT_FILE, flagged_instances,
                                    read_heartbeat)

    run_dir = os.path.realpath(run_dir)
    if not os.path.isdir(run_dir):
        raise TriageError(f"not a run directory: {run_dir}")
    results = None
    try:
        with open(os.path.join(run_dir, "results.json")) as f:
            results = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass   # partial run: triage proceeds from the heartbeat alone
    hb = None
    hb_path = os.path.join(run_dir, HEARTBEAT_FILE)
    if os.path.exists(hb_path):
        hb = read_heartbeat(hb_path)
    header = (hb or {}).get("header") or {}
    opts = header.get("opts")
    if opts is None:
        raise TriageError(
            f"{run_dir} has no heartbeat run-start record with repro "
            f"opts (heartbeat.jsonl missing or truncated before the "
            f"first line); triage needs it to replay the run — re-run "
            f"with the heartbeat enabled (the default for stored runs)")
    workload = header.get("workload")
    if not workload:
        raise TriageError(f"{run_dir}: heartbeat header names no "
                          f"workload")

    flagged: List[int] = []
    if results:
        flagged = list(results.get("invariants", {})
                       .get("violating-instance-ids", []))
        # device verdict lanes (--check-mode device/both) flag
        # instances beyond the invariant trips — union them in so
        # triage replays every device-suspect instance too
        for i in (results.get("check", {})
                  .get("flagged-instance-ids", [])):
            if i not in flagged:
                flagged.append(i)
    if not flagged and hb:
        flagged = flagged_instances(hb)

    # ticks the run actually dispatched: fail-fast / killed runs cover
    # a prefix; the replay must cover the same prefix, no more
    ticks = header.get("ticks")
    if hb and hb.get("chunks"):
        ticks = max(rec.get("t0", 0) + rec.get("ticks", 0)
                    for rec in hb["chunks"])
    if hb and hb.get("end") and hb["end"].get("ticks"):
        ticks = hb["end"]["ticks"]
    if results:
        ff = results.get("fail-fast")
        if ff and ff.get("ticks-dispatched"):
            ticks = ff["ticks-dispatched"]
        elif not ff:
            ticks = results.get("perf", {}).get("ticks", ticks)
    return {
        "run-dir": run_dir,
        "results": results,
        "heartbeat": hb,
        "workload": workload,
        "opts": dict(opts),
        "model-config": header.get("model-config") or {},
        "seed": int(header.get("seed", opts.get("seed", 0) or 0)),
        "ticks": int(ticks) if ticks else None,
        "chunk-ticks": int(header.get("chunk-ticks") or 100),
        "flagged": [int(i) for i in flagged],
    }


def resolve_model(info: Dict[str, Any]):
    """Rebuild the run's model: registry lookup by workload name, then
    restore the recorded scalar knobs — the original may have been
    constructed with non-default kwargs (log_cap, heartbeat, n_keys...)
    and the bit-exact replay needs the identical automaton. Shared with
    the campaign runner (``campaign/runner.py``), whose resumed runs
    rest on the same model-identity contract."""
    from ..models import get_model
    opts = info["opts"]
    model = get_model(info["workload"], int(opts.get("node_count", 1)),
                      opts.get("topology") or "grid", opts=opts)
    for k, v in info.get("model-config", {}).items():
        if hasattr(model, k):
            setattr(model, k, v)
    return model


_resolve_model = resolve_model   # pre-rename internal alias


def _journal_edn_lines(journal):
    """The instance's raw message journal as line-delimited EDN maps
    (``{:time .. :type :send|:recv :message {:id .. :src ..}}`` — the
    shape net/journal.clj streams), so stock Clojure tooling can consume
    the forensics bundle like a reference net journal."""
    from ..utils.edn import Keyword, dumps

    def kw(d):
        return {Keyword(k.replace("_", "-")): v for k, v in d.items()}

    for ev in journal.events():
        m = ev["message"]
        rec = {
            Keyword("time"): ev["time"],
            Keyword("type"): Keyword(ev["type"]),
            Keyword("message"): kw({
                "id": m["id"], "src": m["src"], "dest": m["dest"],
                "body": kw(m["body"]),
            }),
        }
        yield dumps(rec)


def triage_run(run_dir: str, ids: Optional[List[int]] = None,
               max_instances: int = 8, out_root: Optional[str] = None,
               max_svg_events: int = 1500) -> Dict[str, Any]:
    """Replay a run's flagged instances and write their evidence
    bundles. Returns the summary dict (also written to
    ``triage/summary.json``). ``ids`` overrides the flagged set (any
    instance can be replayed, flagged or not — useful for comparing a
    violating instance against a clean neighbor)."""
    from ..net.viz import plot_lamport
    from ..tpu.harness import events_to_histories, make_sim_config
    from ..tpu.journal import TpuJournal
    from ..tpu.pipeline import expand_compact_events, run_sim_pipelined

    info = load_run_info(run_dir)
    targets = [int(i) for i in (ids if ids else info["flagged"])]
    dropped = max(0, len(targets) - int(max_instances))
    targets = targets[:int(max_instances)]
    out_dir = out_root or os.path.join(info["run-dir"], TRIAGE_DIR)
    summary: Dict[str, Any] = {
        "run-dir": info["run-dir"],
        "workload": info["workload"],
        "flagged": info["flagged"],
        "triaged": [],
        "dropped": dropped,
        "out-dir": out_dir,
    }
    if not targets:
        summary["note"] = ("no flagged instances (run is clean or the "
                           "heartbeat saw no violation scan hits)")
        return summary

    model = _resolve_model(info)
    # certified-store drift gate: the replay contract is bit-identity
    # with the original dispatch. If the run-start record carries an
    # executable fingerprint and the one current source keys to
    # differs, the traced code moved since the run — a replay would
    # "explain" a trajectory the original executable never produced.
    # Refuse by name (EXE901); MAELSTROM_AOT=0 skips the gate.
    recorded = ((info.get("heartbeat") or {}).get("header") or {}
                ).get("aot-fingerprint")
    if recorded:
        from ..tpu.harness import aot_fingerprint_for
        current = aot_fingerprint_for(model, info["opts"])
        if current is not None and current != recorded:
            raise TriageError(
                f"EXE901: executable fingerprint drifted since this "
                f"run (recorded {recorded}, current {current}) — the "
                f"traced sources or run config changed, so a replay "
                f"would not be bit-identical to the original "
                f"dispatch. Triage from the matching checkout (and "
                f"re-certify with `maelstrom lint --aot "
                f"--update-aot`), or set MAELSTROM_AOT=0 to replay "
                f"anyway")
    K = len(targets)
    sub_opts = {**info["opts"], "n_instances": K, "record_instances": K,
                "journal_instances": K}
    ms_per_tick = float(sub_opts.get("ms_per_tick", 1) or 1)
    sim = make_sim_config(model, sub_opts)
    # fault-fuzz runs: each flagged instance's RANDOMIZED schedule is a
    # pure function of (seed, instance id) — reconstruct it into the
    # bundle as a deterministic plan (`maelstrom shrink` minimizes it).
    # sim.faults IS the original run's compiled config: sub_opts only
    # changes instance/record counts, which the fault compile ignores.
    fuzz_fx = sim.faults if info["opts"].get("fault_fuzz") else None
    if info["ticks"] and info["ticks"] < sim.n_ticks:
        # a fail-fast/killed run dispatched only a prefix; replay
        # exactly those ticks (trajectories are prefix-stable)
        sim = sim._replace(n_ticks=info["ticks"])
    params = model.make_params(sim.net.n_nodes)
    res = run_sim_pipelined(
        model, sim, info["seed"], params,
        instance_ids=np.asarray(targets, np.int32),
        chunk=info["chunk-ticks"], keep_compact=True)
    replay_viol = np.asarray(res.carry.violations)
    first_viol = (np.asarray(res.carry.telemetry.first_violation)
                  if res.carry.telemetry is not None else None)
    summary["replayed-violating"] = int((replay_viol > 0).sum())
    summary["ticks"] = int(sim.n_ticks)
    checker = model.checker()

    os.makedirs(out_dir, exist_ok=True)
    for k, gid in enumerate(targets):
        inst_dir = os.path.join(out_dir, f"instance-{gid}")
        os.makedirs(inst_dir, exist_ok=True)
        # the instance-subset expansion: only THIS instance's compacted
        # rows become dense — [T, 1, C, 2, 2 + ev_vals]
        dense = expand_compact_events(model, sim, res.compact or [],
                                      n_ticks=sim.n_ticks,
                                      instances=[k])
        history = events_to_histories(
            model, dense, final_start=sim.client.final_start,
            ms_per_tick=ms_per_tick)[0]
        try:
            verdict = checker(history, sub_opts)
        except Exception as e:
            # structured blow-up verdict (instance id + checker name +
            # truncated traceback) — same contract as the harness's
            # verdict pipeline (checkers/pool.py)
            from . import checker_failure
            from .pool import checker_name
            verdict = checker_failure(e, checker=checker_name(model),
                                      instance=gid)
        journal = TpuJournal(model, sim.net, res.journal_sends,
                             res.journal_recvs, instance=k,
                             ms_per_tick=ms_per_tick)
        svg_path = os.path.join(inst_dir, "messages.svg")
        plot_lamport(journal, svg_path, max_events=max_svg_events)
        with open(os.path.join(inst_dir, "journal.edn"), "w") as f:
            for line in _journal_edn_lines(journal):
                f.write(line + "\n")
        with open(os.path.join(inst_dir, "history.jsonl"), "w") as f:
            for rec in history:
                f.write(json.dumps(rec) + "\n")
        entry = {
            "instance": gid,
            "dir": inst_dir,
            "valid?": verdict.get("valid?"),
            "violation-ticks": int(replay_viol[k]),
            "first-violation-tick": (int(first_viol[k])
                                     if first_viol is not None
                                     else None),
            "ops": sum(1 for r in history if r["type"] == "invoke"),
            "journal-events": sum(1 for _ in journal.events()),
        }
        repro = {
            "workload": info["workload"],
            "instance": gid,
            "seed": info["seed"],
            "ticks": int(sim.n_ticks),
            "opts": info["opts"],
            "verdict": verdict,
            "violation-ticks": entry["violation-ticks"],
            "first-violation-tick": entry["first-violation-tick"],
            # the bit-exact single-instance replay, as an API call
            "replay": {
                "call": "maelstrom_tpu.tpu.harness.replay_instances",
                "args": {"workload": info["workload"],
                         "opts": info["opts"],
                         "instance_ids": [gid]},
            },
            "command": (f"python -m maelstrom_tpu triage "
                        f"{info['run-dir']} --instance {gid}"),
        }
        if fuzz_fx is not None:
            from ..faults.fuzz import reconstruct_plan
            plan = reconstruct_plan(fuzz_fx, sim.net.n_nodes,
                                    info["seed"], gid)
            with open(os.path.join(inst_dir, "schedule.json"),
                      "w") as f:
                json.dump(plan, f, indent=2)
            repro["fault-schedule"] = plan
            repro["shrink-command"] = (
                f"python -m maelstrom_tpu shrink {info['run-dir']} "
                f"--instance {gid}")
        with open(os.path.join(inst_dir, "repro.json"), "w") as f:
            json.dump(repro, f, indent=2, default=repr)
        summary["triaged"].append(entry)

    with open(os.path.join(out_dir, SUMMARY_FILE), "w") as f:
        json.dump(summary, f, indent=2, default=repr)
    return summary


def render_triage_report(summary: Dict[str, Any]) -> str:
    lines = [f"triage: {summary['workload']} run at "
             f"{summary['run-dir']}"]
    flagged = summary.get("flagged", [])
    if not summary.get("triaged"):
        lines.append(summary.get("note", "nothing triaged"))
        return "\n".join(lines)
    lines.append(
        f"flagged instances: {flagged}"
        + (f" (+{summary['dropped']} beyond --max-instances)"
           if summary.get("dropped") else ""))
    lines.append(f"replayed {len(summary['triaged'])} instance(s) over "
                 f"{summary.get('ticks', '?')} ticks; "
                 f"{summary.get('replayed-violating', '?')} re-tripped "
                 f"on-device invariants")
    if summary.get("replayed-violating", 0) < sum(
            1 for _ in summary["triaged"]):
        lines.append("WARNING: some replayed instances did NOT re-trip "
                     "— replay may not match the original run's config")
    for e in summary["triaged"]:
        ft = e.get("first-violation-tick")
        lines.append(
            f"  instance {e['instance']}: valid? {e['valid?']}, "
            f"{e['violation-ticks']} violation tick(s)"
            + (f" (first at {ft})" if ft is not None and ft >= 0 else "")
            + f", {e['ops']} ops, {e['journal-events']} journal events"
            + f" -> {e['dir']}")
    return "\n".join(lines)
