"""Set checker: were acknowledged adds eventually visible in reads?

For a grow-only set workload (g-set, broadcast): ``add`` ops insert
elements, ``read`` ops return the full set. An acknowledged add is *lost* if
it is absent from every read that began after the add completed (and at
least one such read exists). An element is *stable* once it appears in every
subsequent read; *stable latency* is the delay from add-completion to the
start of stability. Indeterminate (info) adds may or may not appear; they are
never lost.

Parity: jepsen.checker/set-full as used by g_set.clj:62 and
broadcast.clj:216-228 (lost/stable/stale counts + stable-latency
quantiles).
"""

from __future__ import annotations

from typing import Dict, List


def _quantiles(xs: List[float], qs=(0, 0.5, 0.95, 0.99, 1.0)):
    if not xs:
        return None
    xs = sorted(xs)
    return {str(q): xs[min(len(xs) - 1, int(q * len(xs)))] for q in qs}


def set_full_checker(history, add_f: str = "add", read_f: str = "read"
                     ) -> dict:
    from ..gen.history import pairs
    adds_ok = []      # (element, completion-time)
    adds_info = []
    reads = []        # (invoke-time, completion-time, set(values))
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis":
            continue
        if inv["f"] == add_f:
            if comp is None or comp["type"] == "info":
                adds_info.append(inv["value"])
            elif comp["type"] == "ok":
                adds_ok.append((inv["value"], comp["time"]))
        elif inv["f"] == read_f and comp is not None \
                and comp["type"] == "ok" and comp["value"] is not None:
            reads.append((inv["time"], comp["time"], set(comp["value"])))
    reads.sort(key=lambda r: r[0])

    lost, stable, stale = [], [], []
    stable_latencies = []
    never_read = []
    for element, t_add in adds_ok:
        later = [r for r in reads if r[0] >= t_add]
        if not later:
            never_read.append(element)
            continue
        present = [element in r[2] for r in later]
        if not present[-1]:
            # absent from the most recent read: either never seen (plain
            # lost) or seen and then permanently vanished (also lost)
            lost.append(element)
            continue
        # start of the trailing run of reads that all contain the element
        stable_from = len(later) - 1
        while stable_from > 0 and present[stable_from - 1]:
            stable_from -= 1
        stable.append(element)
        stable_latencies.append((later[stable_from][0] - t_add) / 1e6)
        if stable_from > 0:
            stale.append(element)   # was missing from some earlier read
    valid = not lost
    return {
        "valid?": valid if reads else "unknown",
        "attempt-count": len(adds_ok) + len(adds_info),
        "acknowledged-count": len(adds_ok),
        "read-count": len(reads),
        "lost-count": len(lost),
        "lost": sorted(lost, key=repr)[:32],
        "stable-count": len(stable),
        "stale-count": len(stale),
        "never-read-count": len(never_read),
        "stable-latencies-ms": _quantiles(stable_latencies),
    }
