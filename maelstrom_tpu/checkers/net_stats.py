"""Network statistics checker: message counts from the journal, split by
all/clients/servers, plus msgs-per-op (server messages per client
invocation) — the headline efficiency number in the broadcast guide.

Parity: reference src/maelstrom/net/checker.clj:28-70.
"""

from __future__ import annotations

from ..gen.history import client_invokes


def net_stats_checker(journal, history) -> dict:
    stats = journal.stats()
    ops = len(client_invokes(history))
    servers_msgs = stats["servers"]["msg-count"]
    return {
        "valid?": True,
        "stats": stats,
        "msgs-per-op": (servers_msgs / ops) if ops else None,
    }
