"""Network statistics checker: message counts from the journal, split by
all/clients/servers, plus msgs-per-op (server messages per client
invocation) — the headline efficiency number in the broadcast guide —
and the network's drop counters (partition / loss / overflow), keyed
like the TPU runtime's netsim.NetStats so journal stats and device
fleet metrics (doc/observability.md) agree on vocabulary.

Parity: reference src/maelstrom/net/checker.clj:28-70.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..gen.history import client_invokes


def net_stats_checker(journal, history,
                      drops: Optional[Dict[str, int]] = None) -> dict:
    """``journal`` is any object with a ``stats()`` split (the host
    Journal or a TpuJournal); ``drops`` is an optional drop-counter dict
    (host ``Net.drop_stats()`` or the device net block). msgs-per-op is
    0.0 — never null — when the history holds no client invokes, so
    downstream arithmetic on the number can't TypeError."""
    stats = journal.stats()
    ops = len(client_invokes(history))
    servers_msgs = stats["servers"]["msg-count"]
    out = {
        "valid?": True,
        "stats": stats,
        "msgs-per-op": (servers_msgs / ops) if ops else 0.0,
    }
    if drops is not None:
        out["drops"] = dict(drops)
    return out
