"""History checkers (history -> verdict maps with a three-valued
``valid?``: True / False / "unknown", reference doc/results.md:58-64)."""


def compose_valid(verdicts) -> object:
    """Combine sub-checker verdicts: False dominates, then "unknown",
    then True — the composition rule of the reference's composed checker
    (jepsen checker/compose semantics)."""
    out = True
    for v in verdicts:
        if v is False:
            return False
        if v == "unknown":
            out = "unknown"
    return out


def checker_failure(exc, checker=None, instance=None,
                    tb_limit: int = 1200) -> dict:
    """A checker blow-up as a structured failing verdict: instance id,
    checker name, and a truncated traceback ride in the result dict —
    an exception is a *reason the analysis is invalid*, never a bare
    string (and never a crash of the surrounding run). ``compose_valid``
    counts it as a definite False.

    The formatted traceback DROPS its first frame — the harness/pool
    call site invoking the checker — so a pooled verdict and the serial
    oracle's verdict for the same blow-up are byte-identical (the
    call-site frame is the one thing that legitimately differs between
    a farm worker and the in-process loop)."""
    import traceback
    out = {"valid?": False, "error": repr(exc)}
    if checker is not None:
        out["checker"] = checker
    if instance is not None:
        out["instance"] = int(instance)
    tb = exc.__traceback__
    tb = tb.tb_next if tb is not None and tb.tb_next is not None else tb
    text = "".join(traceback.format_exception(type(exc), exc, tb))
    out["traceback"] = text[-tb_limit:]
    return out


def check_history(history, opts, checker, extra=None, name=None):
    """Compose the standard checkers over one recorded history.

    Shared by the live runner and the offline ``check`` command so the
    two can never diverge (same sub-checkers, same exception handling,
    same composition). ``extra`` merges additional pre-computed results
    (e.g. the live runner's journal-based net stats) into the composed
    map before the verdict is taken. A workload checker that raises
    becomes a failing result with the error attached, not a crash;
    ``name`` labels the blow-up verdict's ``checker`` field (falls back
    to the generic "workload")."""
    import traceback

    from .availability import availability_checker
    from .perf import perf_checker, stats_checker

    results = {
        "perf": perf_checker(history),
        "stats": stats_checker(history),
        "availability": availability_checker(
            history, opts["availability"]),
    }
    if extra:
        results.update(extra)
    if checker is not None:
        try:
            results["workload"] = checker(history, opts)
        except Exception as e:
            traceback.print_exc()
            results["workload"] = checker_failure(
                e, checker=name or "workload")
    results["valid?"] = compose_valid(
        r.get("valid?", True)
        for r in results.values() if isinstance(r, dict))
    return results
