"""History checkers (history -> verdict maps with a three-valued
``valid?``: True / False / "unknown", reference doc/results.md:58-64)."""


def compose_valid(verdicts) -> object:
    """Combine sub-checker verdicts: False dominates, then "unknown",
    then True — the composition rule of the reference's composed checker
    (jepsen checker/compose semantics)."""
    out = True
    for v in verdicts:
        if v is False:
            return False
        if v == "unknown":
            out = "unknown"
    return out


def check_history(history, opts, checker, extra=None):
    """Compose the standard checkers over one recorded history.

    Shared by the live runner and the offline ``check`` command so the
    two can never diverge (same sub-checkers, same exception handling,
    same composition). ``extra`` merges additional pre-computed results
    (e.g. the live runner's journal-based net stats) into the composed
    map before the verdict is taken. A workload checker that raises
    becomes a failing result with the error attached, not a crash."""
    import traceback

    from .availability import availability_checker
    from .perf import perf_checker, stats_checker

    results = {
        "perf": perf_checker(history),
        "stats": stats_checker(history),
        "availability": availability_checker(
            history, opts["availability"]),
    }
    if extra:
        results.update(extra)
    if checker is not None:
        try:
            results["workload"] = checker(history, opts)
        except Exception as e:
            traceback.print_exc()
            results["workload"] = {"valid?": False, "error": repr(e)}
    results["valid?"] = compose_valid(
        r.get("valid?", True)
        for r in results.values() if isinstance(r, dict))
    return results
