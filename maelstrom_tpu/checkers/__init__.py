"""History checkers (history -> verdict maps with a three-valued
``valid?``: True / False / "unknown", reference doc/results.md:58-64)."""


def compose_valid(verdicts) -> object:
    """Combine sub-checker verdicts: False dominates, then "unknown",
    then True — the composition rule of the reference's composed checker
    (jepsen checker/compose semantics)."""
    out = True
    for v in verdicts:
        if v is False:
            return False
        if v == "unknown":
            out = "unknown"
    return out
