"""Device verdict lanes: O(chips) screening for the host checker farm.

The host verdict stage — even farmed out over worker processes
(checkers/pool.py) — does O(recorded instances) Python work: decode,
dict build, check. At the fleet sizes the runtime simulates, a CLEAN
sweep spends its whole check budget proving nobody misbehaved. This
module moves that proof on device, the DrJAX map-reduce idiom applied
to checking: every instance carries a fixed-shape int32 summary row
(``Carry.check_summary``, [I, N_LANES], batch-LEADING in both carry
layouts like the telemetry leaves) updated inside the fused tick, and
the host only ever routes instances whose summary FLAGS lane is nonzero
(or whose invariants tripped) into the full-oracle farm. Host cost then
scales with violations found, not instances simulated.

Lane family (all int32; cumulative counters wrap, which is fine — they
are screening state, not reported figures):

- ``L_FLAGS``    bitmask of device-detected suspicion (``FLAG_*``).
                 Nonzero = route this instance to the host farm for
                 full-oracle confirmation. A flag is a *screen*, never
                 a verdict: false positives cost farm work, and the
                 committed-prefix / monotonicity lanes are constructed
                 so the batch anomalies the full checkers catch leave a
                 device-visible trace.
- ``L_HASH``     committed-prefix rolling hash — the model's
                 ``summary_step`` folds an order-sensitive hash of the
                 reference node's committed prefix, so prefix rewrites
                 show up as hash churn on a frontier that did not move.
- ``L_FRONTIER`` the committed watermark (max commit index / committed
                 offsets / CRDT element count — model-defined), monotone
                 non-decreasing on every correct trajectory.
- ``L_READ_FRONTIER`` monotonic max of every frontier observed — the
                 WGL/stale-read witness: a frontier BELOW it means
                 committed state regressed.
- ``L_STALE``    count of regression ticks (forensics: how long the
                 regression persisted).
- ``L_OK``/``L_FAIL``/``L_INFO`` availability counter twins folded from
                 the per-tick event tensor's completion slot — the
                 prefix-summary counters ROADMAP item 2 names, now per
                 instance instead of fleet-scalar.
- ``L_SENT``/``L_DELIVERED`` net-stats counter twins (per-instance send
                 and delivery deltas summed over the run).
- ``L_SCRATCH``  model-private scratch state — e.g. the CRDT family's
                 unsettled-window shift register (reads served while a
                 replica lagged the acknowledged floor are the
                 interval-checker anomalies, and the register covers
                 the reply-flight ticks between serve and completion).

``Model.summary_step`` (tpu/runtime.py) is the per-model hook: given
one instance's [N_LANES] row, its full per-node state pytree, and its
[C, 2, 2+V] event rows for the tick, fold this tick's
frontier/hash/divergence via :func:`fold_frontier`. The
default is identity — models without a summary lane still get the
event/net twins, and their flags stay 0 (a clean sweep reports
``farm_load_fraction=0``).

Everything here is pure per-instance elementwise int32 math: no
cross-instance (and no cross-shard) communication, so the tick hot
loop stays ICI-silent under ``maelstrom lint --shard`` and the lanes
ride the shard_map wire as ordinary instance-sharded leaves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# lane indices -------------------------------------------------------------

N_LANES = 11
(L_FLAGS, L_HASH, L_FRONTIER, L_READ_FRONTIER, L_STALE,
 L_OK, L_FAIL, L_INFO, L_SENT, L_DELIVERED, L_SCRATCH) = range(N_LANES)

# L_FLAGS bits
FLAG_DIVERGED = 1    # committed-prefix divergence (model summary_step)
FLAG_REGRESSION = 2  # frontier fell below the read frontier (WGL witness)
FLAG_MODEL = 4       # model-specific extra condition (e.g. kafka
                     # committed-past-log, counter views above source)

# event wire constants — the stable history-event encoding
# (tpu/runtime.py EV_*; mirrored here like native/engine.py does, so
# this module never imports the runtime it is imported by)
_EV_TYPE = 0
_EV_OK, _EV_FAIL, _EV_INFO = 2, 3, 4

# odd multipliers for the rolling hash (int32 wraparound is the
# intended modulus; constants chosen to fit int32)
HASH_C1 = jnp.int32(40503)
HASH_C2 = jnp.int32(999983)


def init_summary(n_instances: int) -> jnp.ndarray:
    """Fresh [I, N_LANES] summary block (batch-LEADING, both layouts)."""
    return jnp.zeros((n_instances, N_LANES), jnp.int32)


def prefix_hash(terms, bodies, in_prefix) -> jnp.ndarray:
    """Order-sensitive int32 hash of a masked log prefix: ``terms``
    [LOGN], ``bodies`` [LOGN, E], ``in_prefix`` [LOGN] bool. Position
    enters through a per-slot odd multiplier, so swapped entries (same
    multiset, different order) hash differently."""
    pos = jnp.arange(terms.shape[0], dtype=jnp.int32)
    contrib = (terms * HASH_C1
               + jnp.sum(bodies, axis=-1, dtype=jnp.int32) * HASH_C2
               + pos)
    return jnp.sum(jnp.where(in_prefix, contrib * ((pos << 1) | 1), 0),
                   dtype=jnp.int32)


def fold_frontier(summ, frontier, hash_val, diverged=None,
                  model_flag=None) -> jnp.ndarray:
    """Fold one tick's (frontier, hash[, divergence]) into one
    instance's [N_LANES] row — the shared lane bookkeeping every model
    ``summary_step`` delegates to: store the watermark + hash, advance
    the monotonic read frontier, and raise the regression flag when the
    watermark fell below anything previously observed."""
    frontier = jnp.asarray(frontier, jnp.int32)
    read_f = summ[L_READ_FRONTIER]
    regressed = frontier < read_f
    flags = summ[L_FLAGS] | jnp.where(regressed, FLAG_REGRESSION, 0)
    if diverged is not None:
        flags = flags | jnp.where(diverged, FLAG_DIVERGED, 0)
    if model_flag is not None:
        flags = flags | jnp.where(model_flag, FLAG_MODEL, 0)
    summ = summ.at[L_FLAGS].set(flags)
    summ = summ.at[L_HASH].set(jnp.asarray(hash_val, jnp.int32))
    summ = summ.at[L_FRONTIER].set(frontier)
    summ = summ.at[L_READ_FRONTIER].set(jnp.maximum(read_f, frontier))
    summ = summ.at[L_STALE].add(regressed.astype(jnp.int32))
    return summ


def update_summary(model, summ, node_state, events, n_sent, n_del,
                   cfg, params, state_axis: int = 0) -> jnp.ndarray:
    """One tick of the whole fleet's summary block ([I, N_LANES]):
    vmap the model's per-instance ``summary_step`` over the batch, then
    fold the availability + net-stat counter twins from tensors both
    tick paths already produce batch-LEADING (the full-fleet event
    tensor pre-``[:R]`` slice and the per-instance stat deltas).

    ``state_axis`` is the instance axis of ``node_state`` leaves: 0 on
    the lead layout, -1 on minor. The per-instance trace is the same
    function either way, so summary lanes are bit-identical across
    layouts exactly like the trajectories they summarize."""
    if summ is None:
        return None
    with jax.named_scope("check_summary"):
        summ = jax.vmap(
            lambda s, st, ev: model.summary_step(s, st, ev, cfg,
                                                 params),
            in_axes=(0, state_axis, 0))(summ, node_state, events)
        # completion-slot event types [I, C]: slot 0 is the completion
        # row; invocations (slot 1) are not availability outcomes
        et = events[:, :, 0, _EV_TYPE]
        counts = jnp.stack(
            [jnp.sum(et == _EV_OK, axis=1, dtype=jnp.int32),
             jnp.sum(et == _EV_FAIL, axis=1, dtype=jnp.int32),
             jnp.sum(et == _EV_INFO, axis=1, dtype=jnp.int32),
             n_sent.astype(jnp.int32),
             n_del.astype(jnp.int32)], axis=1)
        return summ.at[:, L_OK:L_SCRATCH].add(counts)


def stale_read_window(summ, events, unsettled, read_f):
    """CRDT stale-read screen. ``unsettled`` is this tick's "some
    replica lags the acknowledged floor" witness; shift it into the
    L_SCRATCH window register (31 ticks) and return ``(summ', stale)``
    where ``stale`` is True when a read completed this tick with any
    unsettled tick inside the window. The window covers the reply
    flight between the serve tick (where the stale value was read) and
    the completion tick (where the event is recorded) — if every
    replica held the full acknowledged state at serve time, the read
    value lands inside the interval checker's acceptable set, so this
    screens the CRDT family's stale/lost-element anomalies with no
    false negatives up to the window length."""
    win = (((summ[L_SCRATCH] << 1) | unsettled.astype(jnp.int32))
           & 0x7FFFFFFF)
    read_done = jnp.any((events[:, 0, _EV_TYPE] == _EV_OK)
                        & (events[:, 0, 1] == read_f))
    return summ.at[L_SCRATCH].set(win), read_done & (win != 0)


def flagged_mask(violations, check_summary) -> jnp.ndarray:
    """[I] bool — instances needing host confirmation: on-device
    invariants tripped OR any summary flag raised. Works on device
    (chunk scans) and on fetched numpy arrays (harness routing)."""
    flagged = violations > 0
    if check_summary is not None:
        flagged = flagged | (check_summary[:, L_FLAGS] != 0)
    return flagged


def summary_bytes_per_tick(n_instances: int) -> int:
    """HBM traffic the lane family adds per tick (read + write of the
    block counted once — the reporting convention bench.py uses)."""
    return int(n_instances) * N_LANES * 4
