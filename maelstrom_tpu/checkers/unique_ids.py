"""Unique-ID checker: every acknowledged generate op must return a
globally distinct id. Parity: jepsen.checker/unique-ids, used by
workload/unique_ids.clj:72."""

from __future__ import annotations

from collections import Counter


def unique_ids_checker(history, f: str = "generate") -> dict:
    ids = [r["value"] for r in history
           if r["type"] == "ok" and r["f"] == f]
    counts = Counter(map(repr, ids))
    dups = {k: v for k, v in counts.items() if v > 1}
    return {
        "valid?": not dups,
        "attempted-count": sum(1 for r in history
                               if r["type"] == "invoke" and r["f"] == f),
        "acknowledged-count": len(ids),
        "duplicated-count": len(dups),
        "duplicated": dict(list(dups.items())[:32]),
        "range": ([min(ids, key=repr), max(ids, key=repr)] if ids else None),
    }
