"""Kafka-style log checker.

Verifies per-key append-only log semantics over send / poll /
commit_offsets / list_committed_offsets histories:

- **duplicate offsets** — two acknowledged sends share (key, offset)
- **inconsistent offsets** — two polls disagree about the value at
  (key, offset)
- **internal nonmonotonic** — offsets within one poll op for a key go
  backwards
- **external nonmonotonic** — a process's successive polls of a key go
  backwards (it re-reads earlier offsets without a reassignment)
- **lost write** — an acknowledged send whose offset is below some
  later-polled offset for its key but which never appears in any poll
- **commit regression** — committed offsets for a key move backwards
- **aborted read** — a poll observed a value whose send DEFINITIVELY
  failed (a ``fail``-typed send or atomic txn): the G1a of the log
  world, and the tell of a broken transaction — a txn that errored
  after making some of its sends durable. Ops tagged ``non-atomic``
  (the sequential per-mop fallback for nodes without a txn RPC) are
  exempt, since partial prefixes are their documented semantics.

Histories may mix single-mop ops (``send`` / ``poll``) with multi-mop
``txn`` ops (``--txn`` mode: completion value = list of completed mops
``["send", k, [offset, v]]`` / ``["poll", {k: [[offset, v], ...]}]``);
txn mops run through the identical per-mop anomaly machinery.

Parity: the anomaly families of jepsen.tests.kafka's checker as used by
reference src/maelstrom/workload/kafka.clj (docstring :1-71); txn mode
mirrors jepsen.tests.kafka's :txn? op shape, which the reference harness
itself leaves disabled (kafka.clj:294).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List


def _hashable(v):
    """Message values are ints in practice, but the protocol allows any
    JSON value; fold unhashables to a stable repr for set membership."""
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


def mark_reassigned_after_crashes(history):
    """Crash-clients mode (device/TPU runtime): tag each process's
    first ok poll AFTER a crash completion as ``reassigned``.

    The native engine writes the flag onto its own records at reopen
    time; device clients are stateless rows, so the flag is derived
    host-side from the history order instead — sound because a client
    runs one op at a time, so any poll completed after its crash
    completed was necessarily served from the broker's already-reset
    cursor. Returns a new history (records are copied before
    mutation)."""
    crashed = set()
    out = []
    for r in history:
        f = r.get("f")
        proc = r.get("process")
        if f == "crash" and r.get("type") not in (None, "invoke"):
            crashed.add(proc)
        elif (f == "poll" and r.get("type") == "ok"
              and proc in crashed):
            r = dict(r, reassigned=True)
            crashed.discard(proc)
        out.append(r)
    return out


def kafka_checker(history) -> dict:
    from ..gen.history import pairs
    anomalies: Dict[str, List[Any]] = defaultdict(list)

    acked = defaultdict(dict)       # key -> offset -> value
    polled = defaultdict(dict)      # key -> offset -> value
    failed_sends = defaultdict(set)  # key -> values of definite-fail sends
    max_polled = defaultdict(lambda: -1)
    last_poll_pos = defaultdict(lambda: -1)   # (process, key) -> offset
    commits = defaultdict(lambda: -1)         # (process, key) -> offset
    # key -> (max reported offset, completion index of that report)
    server_commits = defaultdict(lambda: (-1, -1))

    def handle_send(k, v, off):
        if off in acked[k] and acked[k][off] != v:
            anomalies["duplicate-offset"].append(
                {"key": k, "offset": off, "values": [acked[k][off], v]})
        acked[k][off] = v

    def handle_poll(value, process, reassigned):
        # value: {key: [[offset, value], ...]}
        for k, msgs in (value or {}).items():
            prev = -1
            for off, v in msgs:
                if off <= prev:
                    anomalies["internal-nonmonotonic"].append(
                        {"key": k, "offsets": [prev, off]})
                prev = off
                if off in polled[k] and polled[k][off] != v:
                    anomalies["inconsistent-offset"].append(
                        {"key": k, "offset": off,
                         "values": [polled[k][off], v]})
                polled[k][off] = v
                max_polled[k] = max(max_polled[k], off)
            if msgs:
                pk = (process, k)
                if msgs[0][0] <= last_poll_pos[pk] and not reassigned:
                    anomalies["external-nonmonotonic"].append(
                        {"key": k, "process": process,
                         "offsets": [last_poll_pos[pk], msgs[0][0]]})
                last_poll_pos[pk] = msgs[-1][0]

    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis":
            continue
        f = inv["f"]
        if comp is not None and comp["type"] == "fail":
            # definite failure: none of its sends may ever be observed
            # (non-atomic sequential fallbacks are exempt — their
            # documented semantics allow a durable prefix)
            non_atomic = inv.get("non-atomic") or comp.get("non-atomic")
            if f == "send":
                failed_sends[inv["value"][0]].add(_hashable(
                    inv["value"][1]))
            elif f == "txn" and not non_atomic:
                for mop in (inv["value"] or []):
                    if mop[0] == "send":
                        failed_sends[mop[1]].add(_hashable(mop[2]))
        if comp is None or comp["type"] != "ok":
            continue
        # a reassigned consumer (fresh client resuming from committed
        # offsets after a crash) may legally jump backwards; the flag
        # can ride either record
        reassigned = inv.get("reassigned") or comp.get("reassigned")
        if f == "send":
            handle_send(comp["value"][0], comp["value"][1],
                        comp["value"][2])
        elif f == "poll":
            handle_poll(comp["value"], inv["process"], reassigned)
        elif f == "txn":
            # multi-mop transaction: completion value is the list of
            # completed mops, ["send", k, [off, v]] / ["poll", msgs].
            # Each mop feeds the same per-mop anomaly machinery; within
            # one txn only the first poll may ride the reassignment.
            for mop in (comp["value"] or []):
                if mop[0] == "send":
                    k, (off, v) = mop[1], mop[2]
                    handle_send(k, v, off)
                elif mop[0] == "poll":
                    handle_poll(mop[1], inv["process"], reassigned)
                    reassigned = False
        elif f == "commit_offsets":
            # the client fills the committed offsets on the completion
            # record (the invoke value is a placeholder). A lagging
            # *other* client may legitimately commit lower offsets, so
            # monotonicity is judged per process...
            for k, off in (comp["value"] or {}).items():
                pk = (inv["process"], k)
                if off < commits[pk]:
                    anomalies["commit-regression"].append(
                        {"key": k, "process": inv["process"],
                         "offsets": [commits[pk], off]})
                commits[pk] = max(commits[pk], off)
        elif f == "list_committed_offsets":
            # ...and globally on what the SERVER reports back — but only
            # between non-overlapping ops: a query that overlapped an
            # earlier one in real time may legally have read first
            for k, off in (comp["value"] or {}).items():
                prev_off, prev_end = server_commits[k]
                if off < prev_off and inv["index"] > prev_end:
                    anomalies["commit-regression"].append(
                        {"key": k, "server-reported": True,
                         "offsets": [prev_off, off]})
                if off > prev_off:
                    server_commits[k] = (off, comp["index"])

    # lost writes: acked offset below the key's max polled offset but
    # never observed by any poll
    for k, offs in acked.items():
        for off, v in offs.items():
            if off < max_polled[k] and off not in polled[k]:
                anomalies["lost-write"].append(
                    {"key": k, "offset": off, "value": v})

    # aborted reads: polled values whose send definitively failed
    for k, offs in polled.items():
        for off, v in offs.items():
            if _hashable(v) in failed_sends[k]:
                anomalies["aborted-read"].append(
                    {"key": k, "offset": off, "value": v})

    return {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies),
        "anomalies": {k: v[:8] for k, v in anomalies.items()},
        "send-count": sum(len(v) for v in acked.values()),
        "poll-count": sum(len(v) for v in polled.values()),
    }
