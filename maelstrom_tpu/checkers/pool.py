"""Persistent multiprocess checker farm: parallel, streaming verdicts.

The host verdict stage — decode the recorded instances' events, run the
per-workload checker on each history — was a serial Python loop at the
end of every run, so verdict wall-clock grew linearly with recorded
instances while the device side kept getting faster. This module fans
the per-instance work out over a pool of worker processes:

- the pool is spawned ONCE per run (``CheckerPool``), each worker
  rebuilds the run's model from its registry name + recorded scalar
  config (the same model-identity contract ``maelstrom triage`` uses)
  and constructs the workload checker locally — nothing unpicklable
  ever crosses the process boundary;
- instances are assigned deterministically (``instance % workers``) and
  per-instance **column slabs** (``tpu/decode.py``) stream to the
  owning worker as the pipelined executor fetches each chunk, so dict
  materialization and checking overlap device compute;
- workers materialize the Jepsen dict records with the SAME
  ``decode.materialize_records`` the in-process path uses, then run the
  checker at finalize — or incrementally, for checkers registered in
  ``INCREMENTAL_CHECKERS`` (they consume records per chunk and drop
  them, bounding worker memory);
- results are assembled in instance order regardless of completion
  order, so pooled verdicts are byte-identical to the serial path **by
  construction** (``tests/test_check_pool.py`` pins every registered
  workload in both carry layouts);
- ``--check-workers 0`` forces the serial path, and ANY pool failure
  (worker death, timeout, unpicklable config) falls back to the serial
  path automatically — a broken pool can change wall-clock, never a
  verdict.

``VerdictPipeline`` is the harness-facing bundle: streaming decoder +
pool + serial fallback + the ``perf.phases.check`` timing record.
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Dict, List, Optional

from . import checker_failure

# worker -> parent message tags
_READY, _DONE, _FAILED = "ready", "done", "error"


def resolve_check_workers(value, n_check: int) -> int:
    """Resolve the ``check_workers`` opt: explicit ints win (0 =
    serial); ``None``/"auto" uses a pool only when there is enough
    per-instance work to amortize it (>= 16 recorded instances) and
    the host has cores to spread over."""
    if value is not None and value != "auto":
        return max(0, int(value))
    cpus = os.cpu_count() or 1
    if cpus < 2 or n_check < 16:
        return 0
    return min(4, cpus)


def checker_name(model) -> str:
    """The human-facing name of a model's workload checker (blow-up
    reports name the checker, not a ``<lambda>``)."""
    return getattr(model, "checker_name", None) or f"{model.name}-checker"


def pool_spec(model, opts: Dict[str, Any], final_start: int,
              ms_per_tick: float) -> Dict[str, Any]:
    """Everything a worker needs to rebuild the model + checker:
    registry workload name, scalar model knobs (non-default log_cap /
    n_keys / mutant flags ride here), and the checker's ``opts`` dict
    (filtered to picklable entries; checkers read plain scalars like
    ``consistency_models``)."""
    import pickle
    clean_opts = {}
    for k, v in opts.items():
        try:
            pickle.dumps(v)
        except Exception:
            continue
        clean_opts[k] = v
    return {
        "workload": model.name,
        "node-count": int(opts.get("node_count", 1)),
        "topology": opts.get("topology") or "grid",
        "model-config": {k: v for k, v in vars(model).items()
                        if isinstance(v, (bool, int, float, str))},
        "opts": clean_opts,
        "final-start": int(final_start),
        "ms-per-tick": ms_per_tick,
    }


def _rebuild_model(spec: Dict[str, Any]):
    """Worker-side model reconstruction — the triage/campaign
    model-identity move: registry lookup by name, then restore the
    recorded scalar knobs so host-side decode + checker construction
    match the parent's model exactly."""
    from ..models import get_model
    model = get_model(spec["workload"], spec["node-count"],
                      spec["topology"], opts=spec["opts"])
    for k, v in spec.get("model-config", {}).items():
        if hasattr(model, k):
            setattr(model, k, v)
    return model


# --- incremental checkers --------------------------------------------------
#
# A checker that can fold records chunk-by-chunk registers a streaming
# twin here; its worker consumes each chunk's records on arrival and
# DROPS them (bounded memory however long the run), producing the exact
# dict the batch checker would. Checkers without a twin accumulate the
# full history and run once at finalize — still parallel across
# instances, just not incremental within one.


class _IncrementalUniqueIds:
    """Streaming twin of ``checkers.unique_ids.unique_ids_checker`` —
    field-for-field identical output (first-seen Counter order, repr
    min/max tie-breaks) without retaining the history."""

    def __init__(self, model, opts):
        from collections import Counter
        del opts
        self._f = "generate"
        self._counts = Counter()
        self._attempted = 0
        self._min_id = self._max_id = None
        self._have_ids = False

    def feed(self, records: List[dict]) -> None:
        for rec in records:
            if rec["f"] != self._f:
                continue
            if rec["type"] == "invoke":
                self._attempted += 1
            elif rec["type"] == "ok":
                value = rec["value"]
                self._counts[repr(value)] += 1
                if not self._have_ids:
                    self._min_id = self._max_id = value
                    self._have_ids = True
                else:
                    # strict comparisons keep the batch checker's
                    # first-occurrence tie-breaks (min/max return the
                    # first extremal element)
                    if repr(value) < repr(self._min_id):
                        self._min_id = value
                    if repr(value) > repr(self._max_id):
                        self._max_id = value

    def result(self) -> dict:
        dups = {k: v for k, v in self._counts.items() if v > 1}
        return {
            "valid?": not dups,
            "attempted-count": self._attempted,
            "acknowledged-count": sum(self._counts.values()),
            "duplicated-count": len(dups),
            "duplicated": dict(list(dups.items())[:32]),
            "range": ([self._min_id, self._max_id]
                      if self._have_ids else None),
        }


INCREMENTAL_CHECKERS = {"unique-ids": _IncrementalUniqueIds}


# --- the worker ------------------------------------------------------------


def _worker_main(widx: int, spec: Dict[str, Any], task_q,
                 result_q) -> None:
    """One checker-farm worker: rebuild model + checker, accumulate
    (or incrementally fold) streamed slabs per owned instance, check at
    finalize, report ``{instance: verdict}``. A checker exception is a
    per-instance failing verdict (``checker_failure``), never a worker
    death; anything structural reports ``error`` and the parent falls
    back to the serial path."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    native = spec.get("engine") == "native"
    try:
        if native:
            # native-engine farm: histories arrive pre-decoded as
            # "records" tasks, so there is no model/decode machinery —
            # just the harness's single-arg per-workload checker
            from ..native.harness import _checker_for
            materialize_records = model = None
            checker = _checker_for(spec["workload"],
                                   spec.get("consistency"))
            final_start, mpt, check_opts, inc_cls = 0, 1.0, {}, None
        else:
            from ..tpu.decode import materialize_records
            model = _rebuild_model(spec)
            checker = model.checker()
            name = checker_name(model)
            final_start = spec["final-start"]
            mpt = spec["ms-per-tick"]
            check_opts = spec["opts"]
            inc_cls = INCREMENTAL_CHECKERS.get(spec["workload"])
        result_q.put((_READY, widx, None))
    except BaseException:
        result_q.put((_FAILED, widx, traceback.format_exc()[-2000:]))
        return
    histories: Dict[int, List[dict]] = {}
    counts: Dict[int, int] = {}
    incremental: Dict[int, Any] = {}
    try:
        while True:
            task = task_q.get()
            kind = task[0]
            if kind == "chunk":
                for inst, slab in task[1].items():
                    base = counts.get(inst, 0)
                    records = materialize_records(model, slab,
                                                  final_start, mpt,
                                                  index_base=base)
                    counts[inst] = base + len(records)
                    if inc_cls is not None:
                        if inst not in incremental:
                            incremental[inst] = inc_cls(model,
                                                        check_opts)
                        incremental[inst].feed(records)
                    else:
                        histories.setdefault(inst, []).extend(records)
            elif kind == "records":
                # native-engine twin of "chunk": already-materialized
                # dict records, appended verbatim
                for inst, records in task[1].items():
                    histories.setdefault(inst, []).extend(records)
            elif kind == "finalize":
                verdicts: Dict[int, dict] = {}
                for inst in task[1]:
                    if native:
                        try:
                            verdicts[inst] = checker(
                                histories.get(inst, []))
                        except Exception as e:
                            # the native harness's error shape — a
                            # checker blow-up is a failing verdict
                            verdicts[inst] = {"valid?": False,
                                              "error": repr(e)}
                        continue
                    try:
                        if inc_cls is not None:
                            acc = incremental.get(inst)
                            if acc is None:
                                acc = inc_cls(model, check_opts)
                            verdicts[inst] = acc.result()
                        else:
                            verdicts[inst] = checker(
                                histories.get(inst, []), check_opts)
                    except Exception as e:
                        verdicts[inst] = checker_failure(
                            e, checker=name, instance=inst)
                result_q.put((_DONE, widx, verdicts))
            elif kind == "stop":
                return
    except BaseException:
        try:
            result_q.put((_FAILED, widx, traceback.format_exc()[-2000:]))
        except Exception:
            pass


# --- the parent-side farm --------------------------------------------------


def _main_importable() -> bool:
    """Can spawn-semantics children re-import ``__main__``? True for
    real script/`-m` entry points (the CLI, pytest, campaign workers);
    False for REPLs and stdin scripts, whose `__main__` has no
    importable source."""
    import sys
    main = sys.modules.get("__main__")
    if main is None:
        return False
    spec = getattr(main, "__spec__", None)
    if spec is not None and getattr(spec, "name", None):
        return True                      # python -m entry
    path = getattr(main, "__file__", None)
    return bool(path) and os.path.exists(path)


class CheckerPool:
    """A spawn-once farm of ``_worker_main`` processes with
    deterministic instance ownership. All methods degrade instead of
    raising: a dead worker or full queue marks the pool ``broken`` and
    the caller (``VerdictPipeline``) falls back to the serial path."""

    def __init__(self, spec: Dict[str, Any], workers: int,
                 ctx_name: Optional[str] = None):
        import multiprocessing as mp
        ctx_name = ctx_name or os.environ.get("MAELSTROM_POOL_CTX")
        if not ctx_name:
            # forkserver by default: children fork from a clean server
            # process that has never initialized an XLA backend — no
            # inherited JAX threads/locks (plain fork risks deadlock
            # under a live jit dispatch), and after the server's one
            # warm-up import every later pool spawn is a cheap fork
            # (spawn would re-import jax per worker per run)
            ctx_name = ("forkserver"
                        if "forkserver" in mp.get_all_start_methods()
                        else "spawn")
        self.workers = max(1, int(workers))
        self.broken = False
        self.feed_s = 0.0
        self.processes = []
        if ctx_name in ("forkserver", "spawn") and not _main_importable():
            # spawn-semantics children re-import __main__; a REPL /
            # stdin script has none to import — the workers would die
            # in multiprocessing's preparation with noisy tracebacks.
            # Skip the spawn entirely; the caller's serial path is the
            # oracle anyway.
            self.broken = True
            return
        try:
            ctx = mp.get_context(ctx_name)
            if ctx_name == "forkserver":
                try:
                    ctx.set_forkserver_preload(
                        ["maelstrom_tpu.checkers.pool"])
                except Exception:
                    pass
            self._result_q = ctx.Queue()
            self._task_qs = [ctx.Queue() for _ in range(self.workers)]
            self.processes = [
                ctx.Process(target=_worker_main,
                            args=(w, spec, self._task_qs[w],
                                  self._result_q),
                            daemon=True)
                for w in range(self.workers)]
            for proc in self.processes:
                proc.start()
        except Exception:
            self.broken = True
            self.processes = []

    def owner(self, inst: int) -> int:
        return inst % self.workers

    def feed(self, slabs: Dict[int, Any]) -> None:
        """Route one chunk's per-instance slabs to their owners."""
        if self.broken:
            return
        t0 = time.monotonic()
        per_worker: Dict[int, Dict[int, Any]] = {}
        for inst, slab in slabs.items():
            per_worker.setdefault(self.owner(inst), {})[inst] = slab
        try:
            for w, batch in per_worker.items():
                self._task_qs[w].put(("chunk", batch))
        except Exception:
            self.broken = True
        self.feed_s += time.monotonic() - t0

    def feed_records(self, records_by_inst: Dict[int, List[dict]]
                     ) -> None:
        """Native-engine twin of :meth:`feed`: route already-decoded
        dict records (whole or partial histories) to their owners."""
        if self.broken:
            return
        t0 = time.monotonic()
        per_worker: Dict[int, Dict[int, Any]] = {}
        for inst, records in records_by_inst.items():
            per_worker.setdefault(self.owner(inst), {})[inst] = records
        try:
            for w, batch in per_worker.items():
                self._task_qs[w].put(("records", batch))
        except Exception:
            self.broken = True
        self.feed_s += time.monotonic() - t0

    def finalize(self, instances: List[int],
                 timeout: float = 600.0) -> Optional[Dict[int, dict]]:
        """Ask every worker for its owned verdicts; assemble in
        instance order. Returns None — caller falls back serial — on
        any worker death, structural error, or timeout."""
        if self.broken:
            return None
        per_worker: Dict[int, List[int]] = {w: []
                                            for w in range(self.workers)}
        for inst in instances:
            per_worker[self.owner(inst)].append(inst)
        try:
            for w, owned in per_worker.items():
                self._task_qs[w].put(("finalize", owned))
        except Exception:
            self.broken = True
            return None
        import queue as queue_mod
        verdicts: Dict[int, dict] = {}
        done = set()
        deadline = time.monotonic() + timeout
        while len(done) < self.workers:
            try:
                tag, w, payload = self._result_q.get(timeout=0.5)
            except queue_mod.Empty:
                if time.monotonic() > deadline:
                    self.broken = True
                    return None
                if any(not proc.is_alive()
                       for i, proc in enumerate(self.processes)
                       if i not in done):
                    self.broken = True
                    return None
                continue
            if tag == _READY:
                continue
            if tag == _FAILED:
                self.broken = True
                return None
            verdicts.update(payload)
            done.add(w)
        if set(instances) - set(verdicts):
            self.broken = True
            return None
        return verdicts

    def close(self) -> None:
        try:
            for task_q in self._task_qs:
                task_q.put(("stop",))
        except Exception:
            pass
        for proc in self.processes:
            proc.join(timeout=2.0)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2.0)
        for q in getattr(self, "_task_qs", []) + (
                [self._result_q] if hasattr(self, "_result_q") else []):
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:
                pass

    def kill(self) -> None:
        """Test hook: SIGKILL every worker (the pool-death fallback
        proof in tests/test_check_pool.py and the mid-run resilience
        story — verdicts must still come back, serially)."""
        for proc in self.processes:
            if proc.is_alive():
                proc.kill()
        for proc in self.processes:
            proc.join(timeout=5.0)


# --- harness-facing orchestration -----------------------------------------


class VerdictPipeline:
    """Streaming decode + pooled check + serial fallback, timed.

    Construct BEFORE dispatching the run (worker startup overlaps the
    device compile), feed chunk payloads (or one dense tensor) as they
    arrive, then :meth:`finish` for ``(verdicts, histories, record)``
    where ``record`` is the ``perf.phases.check`` block. Verdicts are
    identical to the serial loop whatever happens to the pool."""

    def __init__(self, model, n_clients: int, record_instances: int,
                 final_start: int, ms_per_tick: float,
                 opts: Dict[str, Any], workers: int):
        from ..tpu.decode import StreamDecoder
        self._model = model
        self._opts = opts
        self._R = int(record_instances)
        self.workers = int(workers) if self._R > 0 else 0
        self.pool: Optional[CheckerPool] = None
        if self.workers > 0:
            self.pool = CheckerPool(
                pool_spec(model, opts, final_start, ms_per_tick),
                self.workers)
            if self.pool.broken:
                self.pool = None
        self.decoder = StreamDecoder(
            model, n_clients, self._R, final_start, ms_per_tick,
            on_slabs=(self.pool.feed if self.pool is not None else None))
        self.feed_chunk = self.decoder.feed
        self.feed_dense = self.decoder.feed_dense

    def finish(self, flagged=None):
        """``flagged=None`` checks every recorded instance (farm mode).
        A list routes ONLY those recorded indices through the farm —
        the device-verdict screen (``--check-mode device``): unflagged
        instances were proven clean on device and get a synthesized
        ``{"valid?": True, "checked-by": "device-summary"}`` verdict
        without any host checker work. A flagged instance's verdict is
        byte-identical to farm mode's by construction — same fed
        slabs, same owner worker, same checker call."""
        histories = self.decoder.finish()
        if flagged is None:
            checked = list(range(self._R))
        else:
            checked = sorted({int(i) for i in flagged
                              if 0 <= int(i) < self._R})
        mode = "serial"
        verdicts_map = None
        t0 = time.monotonic()
        if self.pool is not None:
            verdicts_map = self.pool.finalize(checked) if checked else {}
            mode = ("pooled" if verdicts_map is not None
                    else "pooled-fallback-serial")
        if verdicts_map is None:
            name = checker_name(self._model)
            checker = self._model.checker()
            verdicts_map = {}
            for inst in checked:
                try:
                    verdicts_map[inst] = checker(histories[inst],
                                                 self._opts)
                except Exception as e:
                    verdicts_map[inst] = checker_failure(
                        e, checker=name, instance=inst)
        check_s = time.monotonic() - t0
        if flagged is None:
            verdicts = [verdicts_map[inst] for inst in checked]
        else:
            verdicts = [verdicts_map[inst] if inst in verdicts_map
                        else {"valid?": True,
                              "checked-by": "device-summary"}
                        for inst in range(self._R)]
        record = {
            "mode": mode,
            "workers": self.workers if mode == "pooled" else 0,
            "instances": self._R,
            "farm-instances": len(checked),
            "decode-s": round(self.decoder.decode_s, 4),
            "check-s": round(check_s, 4),
            "verdicts-per-s": (round(len(checked) / check_s, 1)
                               if check_s > 0 else None),
        }
        if self.pool is not None:
            record["feed-s"] = round(self.pool.feed_s, 4)
        self.close()
        return verdicts, histories, record

    def close(self) -> None:
        if self.pool is not None:
            self.pool.close()


def check_native_histories(workload: str, histories,
                           consistency: Optional[str] = None,
                           workers: int = 0) -> List[dict]:
    """The native engine's serial check loop, farmed: fan the
    per-instance verdict work of ``native/harness.py`` over the checker
    pool. Histories arrive already decoded (plain dict records straight
    from the C++ engine), so workers receive them verbatim via
    ``"records"`` tasks and run the harness's single-arg per-workload
    checker. Assembly is instance-ordered, and ANY pool failure falls
    back to the serial loop — verdicts are byte-identical either way,
    including the error shape ``{"valid?": False, "error": repr(e)}``
    for a checker blow-up."""
    n = len(histories)
    if workers > 0 and n > 0:
        pool = CheckerPool({"engine": "native", "workload": workload,
                            "consistency": consistency}, workers)
        try:
            if not pool.broken:
                pool.feed_records(dict(enumerate(histories)))
                verdicts = pool.finalize(list(range(n)))
                if verdicts is not None:
                    return [verdicts[i] for i in range(n)]
        finally:
            pool.close()
    from ..native.harness import _checker_for
    checker = _checker_for(workload, consistency)
    out = []
    for h in histories:
        try:
            v = checker(h)
        except Exception as e:
            v = {"valid?": False, "error": repr(e)}
        out.append(v)
    return out


def check_instances(model, histories, opts: Dict[str, Any],
                    workers: int = 0,
                    final_start: int = 1 << 30,
                    ms_per_tick: float = 1) -> List[dict]:
    """Run the workload checker over already-decoded histories —
    pooled when ``workers > 0`` (dict records are re-derived worker-
    side from slabs when available), serial otherwise. The shared
    convenience for the funnel/triage callers; per-instance blow-ups
    come back as ``checker_failure`` dicts either way."""
    from ..tpu.decode import LazyHistories
    slabs = None
    if isinstance(histories, LazyHistories) and workers > 0:
        slabs = {inst: histories.slab(inst)
                 for inst in range(len(histories))
                 if histories.slab(inst) is not None}
    if slabs is not None:
        pool = CheckerPool(pool_spec(model, opts, final_start,
                                     ms_per_tick), workers)
        try:
            if not pool.broken:
                pool.feed(slabs)
                verdicts = pool.finalize(list(range(len(histories))))
                if verdicts is not None:
                    return [verdicts[inst]
                            for inst in range(len(histories))]
        finally:
            pool.close()
    checker = model.checker()
    name = checker_name(model)
    out = []
    for inst, history in enumerate(histories):
        try:
            out.append(checker(history, opts))
        except Exception as e:
            out.append(checker_failure(e, checker=name, instance=inst))
    return out
