"""Availability checker: what fraction of client invocations completed ok?

``mode`` is None (always valid), "total" (every op must be ok), or a float
fraction. Parity: reference src/maelstrom/checker.clj:6-39.
"""

from __future__ import annotations


def availability_checker(history, mode=None) -> dict:
    invokes = ok = 0
    for r in history:
        if r.get("process") == "nemesis":
            continue
        if r["type"] == "invoke":
            invokes += 1
        elif r["type"] == "ok":
            ok += 1
    frac = (ok / invokes) if invokes else None
    if mode is None:
        valid = True
    elif mode == "total":
        valid = invokes == ok
    else:
        valid = frac is not None and frac >= float(mode)
    return {"valid?": valid, "ok-fraction": frac,
            "ok-count": ok, "count": invokes}
