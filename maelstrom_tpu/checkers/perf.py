"""Performance + stats checkers over the op history.

``stats_checker`` mirrors jepsen's checker/stats (ok/fail/info counts,
overall and per-:f, valid iff every :f has at least one ok).
``perf_checker`` computes latency quantiles and throughput;
``plot_perf`` renders latency-raw / latency-quantiles / rate SVGs into the
store dir (the reference shells out to gnuplot via jepsen's perf checker,
core.clj:92-93).
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Dict, List

from ..gen.history import pairs
from ..utils import svg


def _quantiles(xs: List[float], qs=(0.5, 0.95, 0.99, 1.0)) -> Dict[str, float]:
    if not xs:
        return {}
    xs = sorted(xs)
    out = {}
    for q in qs:
        i = min(len(xs) - 1, int(q * len(xs)))
        out[str(q)] = xs[i]
    return out


def stats_checker(history) -> dict:
    counts = defaultdict(lambda: defaultdict(int))
    totals = defaultdict(int)
    for r in history:
        if r.get("process") == "nemesis":
            continue
        t = r["type"]
        if t in ("ok", "fail", "info", "invoke"):
            counts[r["f"]][t] += 1
            totals[t] += 1
    by_f = {}
    for f, c in counts.items():
        # crash ops never complete ok by design (crash-client mode);
        # exempt them, like the reference's kafka stats-checker wrapper
        # (jepsen.tests.kafka stats-checker over kafka.clj:296)
        by_f[f] = {"count": c["invoke"], "ok-count": c["ok"],
                   "fail-count": c["fail"], "info-count": c["info"],
                   "valid?": (c["ok"] > 0) or f == "crash"}
    return {"valid?": all(v["valid?"] for v in by_f.values()) if by_f
            else True,
            "count": totals["invoke"], "ok-count": totals["ok"],
            "fail-count": totals["fail"], "info-count": totals["info"],
            "by-f": by_f}


def perf_checker(history) -> dict:
    lat_by_f = defaultdict(list)
    all_lat = []
    t_min, t_max = None, None
    ok_count = 0
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis":
            continue
        t = inv["time"]
        t_min = t if t_min is None else min(t_min, t)
        t_max = t if t_max is None else max(t_max, t)
        if comp is None:
            continue
        lat_ms = (comp["time"] - inv["time"]) / 1e6
        lat_by_f[inv["f"]].append(lat_ms)
        all_lat.append(lat_ms)
        if comp["type"] == "ok":
            ok_count += 1
    duration_s = ((t_max - t_min) / 1e9) if (t_min is not None
                                             and t_max > t_min) else 0.0
    return {
        "valid?": True,
        "latency-ms": _quantiles(all_lat),
        "latency-ms-by-f": {f: _quantiles(v) for f, v in lat_by_f.items()},
        "duration-s": duration_s,
        "ok-throughput-ops-per-s": (ok_count / duration_s
                                    if duration_s > 0 else 0.0),
    }


_TYPE_COLOR = {"ok": "#33aa33", "fail": "#dd2222", "info": "#ff9900"}


def plot_perf(history, store_dir: str):
    """latency-raw.svg (scatter of per-op latency over time, colored by
    outcome, log y) and rate.svg (ops/sec over 1s windows, per :f)."""
    points_by_type = defaultdict(list)
    rate_counts = defaultdict(lambda: defaultdict(int))  # f -> sec -> n
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis" or comp is None:
            continue
        t_s = inv["time"] / 1e9
        lat_ms = max((comp["time"] - inv["time"]) / 1e6, 1e-3)
        points_by_type[comp["type"]].append((t_s, lat_ms))
        rate_counts[inv["f"]][int(t_s)] += 1
    series = [svg.Series(name=t, points=pts, color=_TYPE_COLOR.get(t, "#888"))
              for t, pts in sorted(points_by_type.items())]
    svg.scatter_plot(series, title="latency (ms)", xlabel="time (s)",
                     ylabel="latency (ms)", log_y=True,
                     path=os.path.join(store_dir, "latency-raw.svg"))

    # latency-quantiles.svg: p50/p95/p99/max per 1s window over all
    # completed ops (the reference's latency-quantiles.png); windows
    # with no completed ops break the polyline instead of interpolating
    lat_by_sec = defaultdict(list)
    for pts in points_by_type.values():
        for t_s, lat_ms in pts:
            lat_by_sec[int(t_s)].append(lat_ms)
    window_qs = {sec: _quantiles(xs) for sec, xs in lat_by_sec.items()}
    q_styles = [("0.5", "p50", "#4477aa"), ("0.95", "p95", "#228833"),
                ("0.99", "p99", "#ff9900"), ("1.0", "max", "#dd2222")]
    q_series = []
    secs = sorted(lat_by_sec)
    for q_key, label, color in q_styles:
        pts, prev = [], None
        for sec in secs:
            if prev is not None and sec != prev + 1:
                pts.append(None)
            pts.append((sec + 0.5, window_qs[sec][q_key]))
            prev = sec
        if pts:
            q_series.append(svg.Series(name=label, points=pts,
                                       color=color))
    svg.line_plot(q_series, title="latency quantiles (ms)",
                  xlabel="time (s)", ylabel="latency (ms)", log_y=True,
                  path=os.path.join(store_dir, "latency-quantiles.svg"))
    palette = ["#4477aa", "#ee6677", "#228833", "#ccbb44", "#66ccee",
               "#aa3377"]
    rate_series = []
    for i, (f, buckets) in enumerate(sorted(rate_counts.items())):
        if not buckets:
            continue
        lo, hi = min(buckets), max(buckets)
        pts = [(s + 0.5, buckets.get(s, 0)) for s in range(lo, hi + 1)]
        rate_series.append(svg.Series(name=f, points=pts,
                                      color=palette[i % len(palette)]))
    svg.line_plot(rate_series, title="throughput (ops/s)",
                  xlabel="time (s)", ylabel="ops/s",
                  path=os.path.join(store_dir, "rate.svg"))
