"""Linearizability checker for register histories (read / write / cas).

Implements the Wing & Gong / Lowe (WGL) algorithm with memoization: search
for an order of linearization points, one per completed operation, that (a)
lies within each op's real-time interval and (b) is legal for a sequential
register. Indeterminate (``info``) ops may take effect at any point after
their invocation *or never*; failed ops are assumed not to have happened
(they carry definite errors).

This fills the role Knossos plays for the reference's lin-kv workload
(src/maelstrom/workload/lin_kv.clj via jepsen.tests.linearizable-register).
Histories are checked *per key*; a register op's value is ``[k, v]`` for
read/write and ``[k, [from, to]]`` for cas, matching the reference's op
encoding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

INF = float("inf")


@dataclass
class _Op:
    idx: int          # dense index for bitmask
    f: str            # read / write / cas
    args: Any         # read: None; write: v; cas: (frm, to)
    ret: Any          # read: observed value; others: None
    inv: float        # invocation time
    end: float        # completion time (INF for info ops)
    required: bool    # must be linearized (ok) vs optional (info)


def _apply(state, op: _Op) -> Tuple[bool, Any]:
    """Sequential register semantics. Returns (legal, new_state)."""
    if op.f == "read":
        if op.required:
            return (op.ret == state), state
        return True, state  # info read: any return possible
    if op.f == "write":
        return True, op.args
    if op.f == "cas":
        frm, to = op.args
        if state == frm:
            return True, to
        # cas that returned ok must have matched; an info cas may simply
        # have failed server-side -> also allow "no effect" via skip branch
        return False, state
    raise ValueError(f"unknown register op {op.f}")


def check_register_history(ops: List[_Op], init_state=None) -> bool:
    """WGL search. True iff linearizable."""
    n = len(ops)
    required_mask = 0
    for o in ops:
        if o.required:
            required_mask |= 1 << o.idx
    full = (1 << n) - 1
    seen = set()

    def min_end(linearized: int) -> float:
        m = INF
        for o in ops:
            if not (linearized >> o.idx) & 1:
                if o.end < m:
                    m = o.end
        return m

    # iterative DFS over (linearized_mask, state)
    stack = [(0, init_state)]
    while stack:
        linearized, state = stack.pop()
        if (linearized & required_mask) == required_mask:
            return True
        key = (linearized, state)
        if key in seen:
            continue
        seen.add(key)
        bound = min_end(linearized)
        for o in ops:
            if (linearized >> o.idx) & 1:
                continue
            if o.inv > bound:
                continue  # real-time order violated
            legal, new_state = _apply(state, o)
            if legal:
                stack.append((linearized | (1 << o.idx), new_state))
    return False


def _collect_ops(history, key) -> Optional[List[_Op]]:
    """Build per-key op list from invoke/complete pairs."""
    from ..gen.history import pairs
    ops: List[_Op] = []
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis":
            continue
        v = inv["value"]
        if not (isinstance(v, (list, tuple)) and len(v) == 2):
            continue
        k, arg = v
        if k != key:
            continue
        f = inv["f"]
        ctype = comp["type"] if comp is not None else "info"
        if ctype == "fail":
            continue  # definitely didn't happen
        required = ctype == "ok"
        end = comp["time"] if required else INF
        if f == "read":
            ret = comp["value"][1] if (required and
                                       isinstance(comp["value"],
                                                  (list, tuple))) else None
            ops.append(_Op(0, "read", None, ret, inv["time"], end, required))
        elif f == "write":
            ops.append(_Op(0, "write", arg, None, inv["time"], end,
                           required))
        elif f == "cas":
            ops.append(_Op(0, "cas", tuple(arg), None, inv["time"], end,
                           required))
    for i, o in enumerate(ops):
        o.idx = i
    return ops


def linearizable_kv_checker(history, max_ops_per_key: int = 400) -> dict:
    """Check a multi-key register history key by key."""
    keys = set()
    for r in history:
        if r["type"] == "invoke" and isinstance(r.get("value"),
                                                (list, tuple)) \
                and len(r["value"]) == 2:
            keys.add(r["value"][0])
    bad_keys = []
    skipped = []
    for key in sorted(keys, key=repr):
        ops = _collect_ops(history, key)
        if len(ops) > max_ops_per_key:
            skipped.append(key)
            continue
        if not check_register_history(ops):
            bad_keys.append(key)
    return {
        "valid?": not bad_keys,
        "key-count": len(keys),
        "bad-keys": bad_keys,
        "skipped-keys": skipped,
    }
