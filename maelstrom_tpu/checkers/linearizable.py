"""Linearizability checker for register histories (read / write / cas).

Implements the Wing & Gong / Lowe (WGL) algorithm with memoization: search
for an order of linearization points, one per completed operation, that (a)
lies within each op's real-time interval and (b) is legal for a sequential
register. Indeterminate (``info``) ops may take effect at any point after
their invocation *or never*; failed ops are assumed not to have happened
(they carry definite errors).

Scalability (beyond per-key P-compositionality):

* **Quiescent-cut segmentation** — at any instant where every earlier op
  has completed and no pending (info) op spans it, every linearization
  puts all earlier ops before all later ones, so the history splits into
  independent segments. Each segment is checked with the full WGL search
  but propagates the *set* of reachable final register states into the
  next segment (bounded by the number of distinct written values), which
  keeps the search sound and complete while making cost roughly linear
  in segment count for well-behaved histories.
* **Explicit search budget** — the DFS counts visited states; a key that
  exhausts the budget yields ``"unknown"`` rather than a silent pass.
  Likewise histories previously skipped by the op-count guard now make
  the whole result ``"unknown"`` (never ``valid? true``), matching
  Knossos's behavior of reporting indeterminate analyses
  (reference src/maelstrom/workload/lin_kv.clj:78-85 via
  jepsen.tests.linearizable-register).

Histories are checked *per key*; a register op's value is ``[k, v]`` for
read/write and ``[k, [from, to]]`` for cas, matching the reference's op
encoding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Set, Tuple

INF = float("inf")

# Sentinel for "budget exhausted / can't tell".
UNKNOWN = "unknown"


@dataclass
class _Op:
    idx: int          # dense index for bitmask (within its segment)
    f: str            # read / write / cas
    args: Any         # read: None; write: v; cas: (frm, to)
    ret: Any          # read: observed value; others: None
    inv: float        # invocation time
    end: float        # completion time (INF for info ops)
    required: bool    # must be linearized (ok) vs optional (info)


def _apply(state, op: _Op) -> Tuple[bool, Any]:
    """Sequential register semantics. Returns (legal, new_state)."""
    if op.f == "read":
        if op.required:
            return (op.ret == state), state
        return True, state  # info read: any return possible
    if op.f == "write":
        return True, op.args
    if op.f == "cas":
        frm, to = op.args
        if state == frm:
            return True, to
        # cas that returned ok must have matched; an info cas may simply
        # have failed server-side -> also allow "no effect" via skip branch
        return False, state
    raise ValueError(f"unknown register op {op.f}")


def _final_states(ops: List[_Op], init_states: Set[Any],
                  budget: List[int]) -> Optional[Set[Any]]:
    """WGL search over one segment from each possible initial state.

    Returns the set of register states reachable at the end of a
    complete linearization (all required ops placed; pending info ops
    optionally placed) — empty set means the segment is NOT
    linearizable from any given initial state. ``None`` means the
    search budget ran out (indeterminate). ``budget`` is a one-element
    mutable cell of remaining visited-state credits shared across
    segments of a key.
    """
    required_mask = 0
    for o in ops:
        if o.required:
            required_mask |= 1 << o.idx

    def min_end(linearized: int) -> float:
        m = INF
        for o in ops:
            if not (linearized >> o.idx) & 1 and o.end < m:
                m = o.end
        return m

    out: Set[Any] = set()
    seen = set()
    # iterative DFS over (linearized_mask, state)
    for init in init_states:
        stack = [(0, init)]
        while stack:
            linearized, state = stack.pop()
            key = (linearized, state)
            if key in seen:
                continue
            seen.add(key)
            # budget counts WORK (successor scans ~ n per state), not
            # just states, so a wide segment can't run for hours before
            # yielding unknown
            budget[0] -= max(1, len(ops))
            if budget[0] <= 0:
                return None
            if (linearized & required_mask) == required_mask:
                # complete linearization: pending info ops may or may
                # not have taken effect, but writes/cas among them can
                # still change the final state. Record this state; the
                # DFS will also explore placing remaining info ops.
                out.add(state)
            bound = min_end(linearized)
            for o in ops:
                if (linearized >> o.idx) & 1:
                    continue
                if o.inv > bound:
                    continue  # real-time order violated
                legal, new_state = _apply(state, o)
                if legal:
                    stack.append((linearized | (1 << o.idx), new_state))
    return out


def _segments(ops: List[_Op]) -> List[List[_Op]]:
    """Split ops at quiescent cuts: boundaries T where every op invoked
    before T completed before T (pending/info ops bar all later cuts)."""
    ops = sorted(ops, key=lambda o: o.inv)
    segs: List[List[_Op]] = []
    cur: List[_Op] = []
    frontier = -INF  # max completion time of ops in current segment
    for o in ops:
        if cur and frontier < o.inv:
            segs.append(cur)
            cur = []
        cur.append(o)
        frontier = max(frontier, o.end)
    if cur:
        segs.append(cur)
    # reindex per segment for compact bitmasks
    for seg in segs:
        for i, o in enumerate(seg):
            o.idx = i
    return segs


def check_register_history(ops: List[_Op], init_state=None,
                           budget_states: int = 2_000_000):
    """Segmented WGL search. True / False / UNKNOWN (budget exhausted)."""
    budget = [budget_states]
    states: Set[Any] = {init_state}
    for seg in _segments(ops):
        nxt = _final_states(seg, states, budget)
        if nxt is None:
            return UNKNOWN
        if not nxt:
            return False
        states = nxt
    return True


def _collect_ops(history, key) -> List[_Op]:
    """Build per-key op list from invoke/complete pairs."""
    from ..gen.history import pairs
    ops: List[_Op] = []
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis":
            continue
        v = inv["value"]
        if not (isinstance(v, (list, tuple)) and len(v) == 2):
            continue
        k, arg = v
        if k != key:
            continue
        f = inv["f"]
        ctype = comp["type"] if comp is not None else "info"
        if ctype == "fail":
            continue  # definitely didn't happen
        required = ctype == "ok"
        end = comp["time"] if required else INF
        if f == "read":
            ret = comp["value"][1] if (required and
                                       isinstance(comp["value"],
                                                  (list, tuple))) else None
            ops.append(_Op(0, "read", None, ret, inv["time"], end, required))
        elif f == "write":
            ops.append(_Op(0, "write", arg, None, inv["time"], end,
                           required))
        elif f == "cas":
            ops.append(_Op(0, "cas", tuple(arg), None, inv["time"], end,
                           required))
    for i, o in enumerate(ops):
        o.idx = i
    return ops


def linearizable_kv_checker(history, max_ops_per_key: int = 10_000,
                            budget_states: int = 2_000_000) -> dict:
    """Check a multi-key register history key by key.

    Verdict: ``False`` if any key is non-linearizable; ``"unknown"`` if
    none is but some key was indeterminate (over the op cap or out of
    search budget); ``True`` only when every key fully checked clean.
    """
    keys = set()
    for r in history:
        if r["type"] == "invoke" and isinstance(r.get("value"),
                                                (list, tuple)) \
                and len(r["value"]) == 2:
            keys.add(r["value"][0])
    from .native import check_register_history_native
    bad_keys = []
    unknown_keys = []
    for key in sorted(keys, key=repr):
        ops = _collect_ops(history, key)
        if len(ops) > max_ops_per_key:
            unknown_keys.append(key)
            continue
        # native WGL core first (cpp/checker); its per-unit cost is ~10x
        # cheaper than the Python search, so it gets 10x the work budget
        # for the same wall-clock ceiling. None = unavailable/unsupported
        # -> Python fallback
        verdict = check_register_history_native(ops, budget_states * 10)
        if verdict is None:
            verdict = check_register_history(ops,
                                             budget_states=budget_states)
        if verdict is False:
            bad_keys.append(key)
        elif verdict == UNKNOWN:   # == not is: native.py returns its own
            unknown_keys.append(key)   # "unknown" literal
    valid: Any
    if bad_keys:
        valid = False
    elif unknown_keys:
        valid = UNKNOWN
    else:
        valid = True
    return {
        "valid?": valid,
        "key-count": len(keys),
        "bad-keys": bad_keys,
        "unknown-keys": unknown_keys,
    }
