"""Transactional anomaly detection — an Elle-style checker.

Fills the role jepsen's Elle plays for the reference's txn workloads
(src/maelstrom/workload/txn_list_append.clj via jepsen.tests.cycle.append,
txn_rw_register.clj via cycle.wr): infer per-key version orders from
observed reads, build a transaction dependency graph, and hunt for
anomalies.

Implemented (the core of Elle's catalogue for these workloads):

- **G1a aborted read** — a committed read observes a value from a failed
  transaction.
- **G1b intermediate read** — a read observes a non-final state of a key
  written multiple times by one transaction.
- **duplicate / reorder inconsistencies** in list-append reads (two reads
  of a key disagree on the order of their common prefix, or an element
  appears twice) — these invalidate the version-order inference and are
  reported as ``incompatible-order``.
- **lost append** — an acknowledged append absent from the longest
  observed read of its key when later reads exist.
- **dependency cycles** — Tarjan SCC over the union of:
  ``wr`` (T2 read something T1 wrote), ``ww`` (version order, list-append
  only), ``rw`` anti-dependency (T1 read a state missing v, T2 wrote v as
  its successor), per-process session order, and (for strict
  serializability) real-time order. Cycles are classified G0/G1c/G2-item
  by their edge mix, and which classes *fail* the check depends on
  ``consistency_models`` (read-committed < read-atomic < serializable <
  strict-serializable), mirroring the reference's
  ``--consistency-models`` flag (core.clj:160-165).

Histories use the reference's micro-op encoding: op value is a list of
``[f, k, v]`` with f in {"append", "r"} (list-append) or {"w", "r"}
(rw-register).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Set, Tuple

# anomaly class -> weakest consistency model that forbids it
_FORBIDDEN_BY = {
    "G0": "read-uncommitted",        # ww cycles
    "G1a": "read-committed",
    "G1b": "read-committed",
    "G1c": "read-committed",         # ww/wr cycles
    # under snapshot isolation every dependency cycle must contain two
    # ADJACENT rw edges (Fekete et al. 2005) — so a single-rw cycle
    # (G-single) or a multi-rw cycle with no two rw edges adjacent
    # (G-nonadjacent) refutes SI, while classic write skew (two
    # adjacent rw edges) is SI-legal and only fails serializable+
    "G-single": "snapshot-isolation",
    "G-nonadjacent": "snapshot-isolation",
    "G2-item": "serializable",       # >=1 rw edge
    "internal": "read-atomic",       # a txn contradicting its own writes
    "realtime": "strict-serializable",
    "incompatible-order": "read-uncommitted",
    # a read observing a value that NO transaction — committed, failed,
    # or indeterminate — ever wrote is data corruption, invalid at any
    # model (Elle's :unwritten / garbage-read discipline)
    "unwritten-read": "read-uncommitted",
    # two external reads of one key within one txn disagreeing: legal
    # non-repeatable read at read-committed, fractured at read-atomic+
    "fractured-read": "read-atomic",
    # detection of lost appends relies on real-time ordering ("a read
    # that STARTED after the append completed misses it") — under plain
    # serializability such a read may legally serialize earlier, so this
    # only fails strict models; true serializability losses surface as
    # ww/wr/rw cycles instead (including the unobserved-append rw edges
    # below: a read of k missing acked value v must serialize before
    # v's append — lists only grow)
    "lost-append": "strict-serializable",
}

_MODEL_ORDER = ["read-uncommitted", "read-committed", "read-atomic",
                "snapshot-isolation", "serializable",
                "strict-serializable"]


def _model_leq(a: str, b: str) -> bool:
    return _MODEL_ORDER.index(a) <= _MODEL_ORDER.index(b)


class _Graph:
    def __init__(self):
        self.edges: Dict[int, Dict[int, Set[str]]] = defaultdict(
            lambda: defaultdict(set))

    def add(self, a: int, b: int, kind: str):
        if a != b:
            self.edges[a][b].add(kind)

    def sccs(self) -> List[List[int]]:
        """Tarjan's strongly-connected components (iterative)."""
        index: Dict[int, int] = {}
        low: Dict[int, int] = {}
        on_stack: Set[int] = set()
        stack: List[int] = []
        out: List[List[int]] = []
        counter = [0]
        nodes = set(self.edges)
        for tos in self.edges.values():
            nodes.update(tos)

        for root in nodes:
            if root in index:
                continue
            work = [(root, iter(self.edges.get(root, {})))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(self.edges.get(w, {}))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        out.append(comp)
        return out

    def cycle_kinds(self, comp: List[int]) -> Set[str]:
        cset = set(comp)
        kinds: Set[str] = set()
        for a in comp:
            for b, ks in self.edges.get(a, {}).items():
                if b in cset:
                    kinds.update(ks)
        return kinds

    def shortest_path(self, src: int, dst: int, cset: Set[int],
                      avoid_kind: Optional[str] = None
                      ) -> Optional[List[int]]:
        """BFS path src -> dst inside ``cset``; edges whose ONLY kinds
        include ``avoid_kind`` are usable iff they also carry another
        kind (an edge is excluded only when avoid_kind is its sole
        justification)."""
        from collections import deque
        prev: Dict[int, int] = {src: src}
        q = deque([src])
        while q:
            v = q.popleft()
            if v == dst:
                path = [v]
                while path[-1] != src:
                    path.append(prev[path[-1]])
                return path[::-1]
            for w, ks in self.edges.get(v, {}).items():
                if w not in cset or w in prev:
                    continue
                if avoid_kind is not None and ks <= {avoid_kind}:
                    continue
                prev[w] = v
                q.append(w)
        return None

    def minimal_cycle(self, comp: List[int]
                      ) -> Optional[Tuple[List[int], List[Set[str]]]]:
        """Find a short explanatory cycle in the SCC, preferring the
        *weakest* anomaly shape (Elle's discipline: report the most
        specific cycle, not the whole SCC): first a cycle with no rw
        edges, then exactly-one-rw (G-single witness), else any cycle.
        Returns (nodes, edge kinds between consecutive nodes, cyclic)."""
        cset = set(comp)

        def close(path):
            kinds = []
            for a, b in zip(path, path[1:] + path[:1]):
                kinds.append(set(self.edges[a][b]))
            return path, kinds

        # Bounded search: one BFS per candidate edge is O(V+E); cap the
        # candidates per class so a dense worst-case SCC (badly broken
        # system -> most txns in one component) stays O(K*(V+E)) instead
        # of O(E*(V+E)). The first cycle found in the strongest class
        # wins — any witness cycle explains the anomaly.
        MAX_TRIES = 64

        # (a) rw-free cycle: edge (u, v) without rw + path v -> u
        # avoiding rw-only edges
        tries = 0
        for u in comp:
            for v, ks in self.edges.get(u, {}).items():
                if v not in cset or ks <= {"rw"}:
                    continue
                tries += 1
                if tries > MAX_TRIES:
                    break
                p = self.shortest_path(v, u, cset, avoid_kind="rw")
                if p is not None:
                    return close([u] + p[:-1])
            if tries > MAX_TRIES:
                break
        # (b) exactly one rw edge: for each rw edge (u, v), rw-free
        # path v -> u
        tries = 0
        for u in comp:
            for v, ks in self.edges.get(u, {}).items():
                if v not in cset or "rw" not in ks:
                    continue
                tries += 1
                if tries > MAX_TRIES:
                    break
                p = self.shortest_path(v, u, cset, avoid_kind="rw")
                if p is not None:
                    return close([u] + p[:-1])
            if tries > MAX_TRIES:
                break
        # (c) any cycle at all (>= 2 rw edges)
        for u in comp:
            for v in self.edges.get(u, {}):
                if v not in cset:
                    continue
                p = self.shortest_path(v, u, cset)
                if p is not None:
                    return close([u] + p[:-1])
        return None


def _classify_cycle(kinds: Set[str], rw_edge_count: int = 2) -> str:
    rw = "rw" in kinds
    realtime_only = kinds <= {"realtime", "process"}
    if realtime_only:
        return "realtime"
    if rw:
        # Elle distinguishes exactly-one-rw cycles (G-single) from
        # multi-rw G2-item. When called from the minimal-cycle path the
        # count is exact; the SCC-level fallback passes 2, so an SCC
        # that is genuinely single-rw would be labeled G2-item there —
        # sound (never over-claims) but under-reports at the
        # snapshot-isolation level, where G-single is forbidden and
        # G2-item is not. The fallback is unreachable for real SCCs
        # (minimal_cycle always finds a witness); this note documents
        # the dependency.
        return "G-single" if rw_edge_count == 1 else "G2-item"
    if "wr" in kinds:
        return "G1c"
    return "G0"


def _collect_txns(history) -> Tuple[List[dict], List[dict]]:
    """Returns (committed, failed) txns: each a dict with
    index/process/ops (completed micro-ops for committed, invoked for
    failed/info)."""
    from ..gen.history import pairs
    committed, failed = [], []
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis" or inv.get("f") != "txn":
            continue
        if comp is not None and comp["type"] == "ok":
            committed.append({"id": len(committed), "index": inv["index"],
                              "end": comp["index"],
                              "process": inv["process"],
                              "ops": comp["value"]})
        elif comp is None or comp["type"] in ("fail", "info"):
            failed.append({"process": inv["process"],
                           "ops": inv["value"],
                           "definite_fail": (comp is not None
                                             and comp["type"] == "fail")})
    return committed, failed


def check_list_append(history,
                      consistency_model: str = "strict-serializable",
                      cycle_search_budget: int = 20_000) -> dict:
    committed, failed = _collect_txns(history)
    anomalies: Dict[str, List[Any]] = defaultdict(list)

    # appended values must be unique per key for inference; the workload
    # generator guarantees this
    writer: Dict[Tuple[Any, Any], Tuple[int, int]] = {}   # (k,v)->(txn,pos)
    failed_writes: Set[Tuple[Any, Any]] = set()
    maybe_writes: Set[Tuple[Any, Any]] = set()   # indeterminate (info)
    for t in failed:
        for op in t["ops"] or []:
            if op[0] == "append":
                (failed_writes if t["definite_fail"]
                 else maybe_writes).add((op[1], op[2]))
    for t in committed:
        for pos, op in enumerate(t["ops"]):
            if op[0] == "append":
                writer[(op[1], op[2])] = (t["id"], pos)

    # within-txn consistency: a read of k must be (shared external
    # prefix) + (this txn's own appends to k so far) — the txn sees its
    # own writes ("internal", Adya's intra-transactional reads) and all
    # its external reads of k come from ONE snapshot ("fractured-read")
    for t in committed:
        own: Dict[Any, List[Any]] = defaultdict(list)
        ext_prefix: Dict[Any, List[Any]] = {}
        for op in t["ops"]:
            k = op[1]
            if op[0] == "append":
                own[k].append(op[2])
                continue
            if op[2] is None:
                continue
            vs, suffix = list(op[2]), own[k]
            if suffix and vs[-len(suffix):] != suffix:
                anomalies["internal"].append(
                    {"key": k, "read": vs, "own-appends": list(suffix),
                     "txn": t["ops"]})
                continue
            prefix = vs[:len(vs) - len(suffix)]
            if k in ext_prefix and ext_prefix[k] != prefix:
                anomalies["fractured-read"].append(
                    {"key": k, "reads": [ext_prefix[k], prefix],
                     "txn": t["ops"]})
            else:
                ext_prefix[k] = prefix

    # reads indexed by key once; the anomaly scans below iterate only
    # same-key reads (linear-ish, not quadratic in the whole history)
    reads_of_key: Dict[Any, List[Tuple[int, List[Any]]]] = \
        defaultdict(list)   # k -> [(txn id, values)]
    for t in committed:
        for op in t["ops"]:
            if op[0] == "r" and op[2] is not None:
                reads_of_key[op[1]].append((t["id"], list(op[2])))

    # same-txn append order is version order: observing two of one txn's
    # appends to k out of program order contradicts any execution
    for t in committed:
        by_key: Dict[Any, List[Any]] = defaultdict(list)
        for op in t["ops"]:
            if op[0] == "append":
                by_key[op[1]].append(op[2])
        for k, vs in by_key.items():
            if len(vs) < 2:
                continue
            for _, read_vs in reads_of_key.get(k, ()):
                pos = {repr(v): i for i, v in enumerate(read_vs)}
                seen = [repr(v) for v in vs if repr(v) in pos]
                if any(pos[a] > pos[b]
                       for a, b in zip(seen, seen[1:])):
                    anomalies["incompatible-order"].append(
                        {"key": k, "read": read_vs,
                         "appended-in-order": vs})

    # per-key longest read; order compatibility between reads
    longest: Dict[Any, List[Any]] = {}
    for t in committed:
        for op in t["ops"]:
            if op[0] != "r" or op[2] is None:
                continue
            k, vs = op[1], list(op[2])
            if len(set(map(repr, vs))) != len(vs):
                anomalies["incompatible-order"].append(
                    {"key": k, "read": vs, "why": "duplicate element"})
                continue
            cur = longest.get(k, [])
            shorter, longer = sorted([vs, cur], key=len)
            if longer[:len(shorter)] != shorter:
                anomalies["incompatible-order"].append(
                    {"key": k, "read": vs, "longest": cur})
            if len(vs) > len(cur):
                longest[k] = vs

    # G1a: reads observing failed appends; G1b: intermediate reads — a
    # read list that contains another txn's append to k but NOT that
    # txn's LATER append to the same k saw a mid-transaction state
    for t in committed:
        for op in t["ops"]:
            if op[0] != "r" or op[2] is None:
                continue
            k = op[1]
            seen = set(map(repr, op[2]))
            for v in op[2]:
                if (k, v) in failed_writes:
                    anomalies["G1a"].append({"key": k, "value": v,
                                             "txn": t["ops"]})
                elif (k, v) not in writer and (k, v) not in maybe_writes:
                    anomalies["unwritten-read"].append(
                        {"key": k, "value": v, "txn": t["ops"]})
                w = writer.get((k, v))
                if w is not None and w[0] != t["id"]:
                    wt = committed[w[0]]
                    later = [o[2] for i, o in enumerate(wt["ops"])
                             if i > w[1] and o[0] == "append"
                             and o[1] == k]
                    if any(repr(v2) not in seen for v2 in later):
                        anomalies["G1b"].append({"key": k, "value": v})

    # lost appends: acked append missing from reads that *began* after
    # the append completed (a read overlapping the append in real time
    # may legally serialize before it, so it owes us nothing)
    reads_by_key = defaultdict(list)
    for t in committed:
        for op in t["ops"]:
            if op[0] == "r" and op[2] is not None:
                reads_by_key[op[1]].append((t["index"], list(op[2])))
    for (k, v), (tid, _) in writer.items():
        t = committed[tid]
        later = [vs for (inv, vs) in reads_by_key.get(k, [])
                 if inv > t["end"]]
        if later:
            newest = max(later, key=len)
            if v not in newest:
                anomalies["lost-append"].append({"key": k, "value": v})

    # dependency graph
    g = _Graph()
    version_pos: Dict[Tuple[Any, Any], int] = {}
    for k, vs in longest.items():
        for i, v in enumerate(vs):
            version_pos[(k, v)] = i
    # ww: consecutive appends in a key's version order
    for k, vs in longest.items():
        for i in range(len(vs) - 1):
            a = writer.get((k, vs[i]))
            b = writer.get((k, vs[i + 1]))
            if a and b:
                g.add(a[0], b[0], "ww")
    for t in committed:
        for op in t["ops"]:
            if op[0] != "r" or op[2] is None:
                continue
            k, vs = op[1], op[2]
            # wr: we read the last element's writer
            if vs:
                w = writer.get((k, vs[-1]))
                if w:
                    g.add(w[0], t["id"], "wr")
            # rw: the next version after our read state was written by
            # someone else
            order = longest.get(k, [])
            if len(vs) < len(order):
                nxt = writer.get((k, order[len(vs)]))
                if nxt:
                    g.add(t["id"], nxt[0], "rw")
    # generalized anti-dependency: lists only grow, so a read of k
    # missing acked value v must serialize before v's append — even
    # when v never shows up in ANY read (the version-order inference
    # can't place it, but the edge is still sound). This is what turns
    # an unobserved lost append into a visible cycle when its writer is
    # otherwise ordered before the reader (VERDICT r4 next #6).
    # Iterates same-key reads only (reads_of_key above).
    seen_of_key: Dict[Any, List[Tuple[int, Set[str]]]] = defaultdict(list)
    for k, rds in reads_of_key.items():
        for rid, vs in rds:
            seen_of_key[k].append((rid, set(map(repr, vs))))
    for (k, v), (wid, _) in writer.items():
        rv = repr(v)
        for rid, seen in seen_of_key.get(k, ()):
            if rid != wid and rv not in seen:
                g.add(rid, wid, "rw")
    return _finish(g, committed, anomalies, consistency_model,
                   cycle_search_budget=cycle_search_budget)


def _finish(g: _Graph, committed: List[dict],
            anomalies: Dict[str, List[Any]], consistency_model: str,
            cycle_search_budget: int = 20_000,
            filter_timeout: bool = False) -> dict:
    """Shared tail of both checkers: session + realtime edges, SCC cycle
    classification, model-filtered verdict.

    ``cycle_search_budget`` caps the total SCC nodes examined for
    explanatory cycles; past it, remaining SCCs are reported as a
    ``cycle-search-timeout`` pseudo-anomaly (Elle's behavior on dense
    graphs) which makes an otherwise-clean verdict ``"unknown"`` — a
    skipped search proves nothing either way. ``filter_timeout``
    reproduces the reference rw-register workload's hack of dropping
    that pseudo-anomaly entirely (txn_rw_register.clj:138-150: "we're
    probably gonna hit a zillion SCCs causing cycle search timeouts,
    but none of them are relevant to us")."""
    by_process = defaultdict(list)
    for t in committed:
        by_process[t["process"]].append(t)
    for ts in by_process.values():
        ts.sort(key=lambda t: t["index"])
        for a, b in zip(ts, ts[1:]):
            g.add(a["id"], b["id"], "process")
    # realtime order (strict serializability only): a -> b iff a
    # completed before b was invoked. Interval reduction preserving
    # reachability: for each a, link every b whose invoke lies in
    # (a.end, e_min], where e_min is the earliest end among txns invoked
    # after a.end — any later c is reachable through the txn achieving
    # e_min (its end < c.invoke gives the next realtime hop). Linking
    # only the FIRST successor would miss b's concurrent with it.
    if consistency_model == "strict-serializable":
        import bisect
        invokes = sorted(committed, key=lambda t: t["index"])
        inv_keys = [t["index"] for t in invokes]
        # suffix minimum of end over invoke order
        suffix_min_end = [0] * (len(invokes) + 1)
        suffix_min_end[len(invokes)] = 1 << 62
        for i in range(len(invokes) - 1, -1, -1):
            suffix_min_end[i] = min(invokes[i]["end"],
                                    suffix_min_end[i + 1])
        for a in committed:
            lo = bisect.bisect_right(inv_keys, a["end"])
            if lo >= len(invokes):
                continue
            e_min = suffix_min_end[lo]
            j = lo
            while j < len(invokes) and invokes[j]["index"] <= e_min:
                b = invokes[j]
                if b["id"] != a["id"]:
                    g.add(a["id"], b["id"], "realtime")
                j += 1

    budget = cycle_search_budget
    skipped_sccs = 0
    largest_skipped = 0
    for comp in g.sccs():
        if budget <= 0:
            skipped_sccs += 1
            largest_skipped = max(largest_skipped, len(comp))
            continue
        budget -= len(comp)
        cyc = g.minimal_cycle(comp)
        if cyc is None:   # unreachable for a real SCC; keep the old path
            kinds = g.cycle_kinds(comp)
            cls = _classify_cycle(kinds, 2)
            anomalies[cls].append(
                {"txns": [committed[i]["ops"] for i in comp[:6]],
                 "edges": sorted(kinds)})
            continue
        nodes, edge_kinds = cyc
        # an edge "needs" rw only when rw is its sole justification; a
        # cycle needing no rw edge classifies by its other kinds even if
        # some edge also happens to carry rw
        rw_needed = sum(1 for ks in edge_kinds if ks <= {"rw"})
        all_kinds = set().union(*edge_kinds)
        eff_kinds = all_kinds - {"rw"} if rw_needed == 0 else all_kinds
        cls = _classify_cycle(eff_kinds, max(rw_needed, 1)
                              if "rw" in eff_kinds else rw_needed)
        if cls == "G2-item":
            # SI refinement on the witness cycle: >=2 required-rw edges
            # with NO two cyclically adjacent refutes snapshot
            # isolation (G-nonadjacent). Witness-based, so an SCC that
            # ALSO contains a nonadjacent cycle may still report
            # G2-item — sound (never over-claims), possibly
            # under-reports at the SI level.
            pos = [i for i, ks in enumerate(edge_kinds)
                   if ks <= {"rw"}]
            L = len(edge_kinds)
            if len(pos) >= 2 and not any(
                    (a + 1) % L == b
                    for a in pos for b in pos if a != b):
                cls = "G-nonadjacent"
        # minimal cycle with per-edge explanations (Elle-style: each
        # step says WHY txn a must precede txn b)
        steps = []
        for i, ks in enumerate(edge_kinds):
            a = nodes[i]
            b = nodes[(i + 1) % len(nodes)]
            steps.append({"txn": committed[a]["ops"],
                          "then": committed[b]["ops"],
                          "because": sorted(ks)})
        anomalies[cls].append(
            {"cycle-length": len(nodes), "steps": steps[:8],
             "edges": sorted(all_kinds)})

    if skipped_sccs and not filter_timeout:
        anomalies["cycle-search-timeout"].append(
            {"sccs-skipped": skipped_sccs,
             "largest-scc": largest_skipped,
             "budget": cycle_search_budget})
    bad = {a: v for a, v in anomalies.items()
           if a != "cycle-search-timeout"
           and _model_leq(_FORBIDDEN_BY.get(a, "read-uncommitted"),
                          consistency_model)}
    valid = not bad
    if valid and "cycle-search-timeout" in anomalies:
        valid = "unknown"   # unsearched SCCs prove nothing either way
    return {
        "valid?": valid,
        "anomaly-types": sorted(anomalies),
        "anomalies": {k: v[:8] for k, v in bad.items()},
        "txn-count": len(committed),
        "consistency-model": consistency_model,
    }


def check_rw_register(history,
                      consistency_model: str = "strict-serializable",
                      cycle_search_budget: int = 20_000) -> dict:
    """rw-register anomalies. Writes are unique per key, so wr edges are
    exact. Version order per key is inferred only from sound facts —
    write-follows-read within a committed txn (the reference's
    ``:wfr-keys? true``, txn_rw_register.clj:162-168) and the initial
    nil version preceding every written version — from which ww and
    generalized anti-dependency (rw) edges follow. Session + realtime
    edges are added in :func:`_finish`. Never false-positive; a sound
    subset of Elle's rw-register analysis (it won't invent version
    orders it cannot prove)."""
    committed, failed = _collect_txns(history)
    anomalies: Dict[str, List[Any]] = defaultdict(list)

    writer: Dict[Tuple[Any, Any], int] = {}
    failed_writes: Set[Tuple[Any, Any]] = set()
    maybe_writes: Set[Tuple[Any, Any]] = set()   # indeterminate (info)
    for t in failed:
        for op in t["ops"] or []:
            if op[0] == "w":
                (failed_writes if t["definite_fail"]
                 else maybe_writes).add((op[1], op[2]))
    for t in committed:
        for op in t["ops"]:
            if op[0] == "w":
                writer[(op[1], op[2])] = t["id"]

    g = _Graph()
    # G1b: reading a non-final write of another txn
    final_write: Dict[Tuple[int, Any], Any] = {}
    for w_t in committed:
        for op in w_t["ops"]:
            if op[0] == "w":
                final_write[(w_t["id"], op[1])] = op[2]
    # readers[(k, v)] = txns that externally observed version v of k
    # (v None = the initial unwritten version); a fractured txn that
    # observes several versions of one key is recorded against each
    readers: Dict[Tuple[Any, Any], Set[int]] = defaultdict(set)
    vo_pairs: Set[Tuple[Any, Any, Any]] = set()   # (k, v1, v2): v1 < v2
    for t in committed:
        # single pass per txn: external reads (before own writes), the
        # write-follows-read version-order pairs (wfr: last external
        # read of k before this txn's FIRST write of k orders those
        # versions), and internal consistency (a read after this txn's
        # own write must see it)
        last_read: Dict[Any, Any] = {}
        wrote: Dict[Any, Any] = {}
        for op in t["ops"]:
            f, k, v = op[0], op[1], op[2]
            if f == "r":
                if k in wrote:
                    if v != wrote[k]:
                        anomalies["internal"].append(
                            {"key": k, "expected": wrote[k],
                             "read": v, "txn": t["ops"]})
                    continue
                if k in last_read and last_read[k] != v:
                    # two external reads of one key from one txn must
                    # come from a single snapshot
                    anomalies["fractured-read"].append(
                        {"key": k, "reads": [last_read[k], v],
                         "txn": t["ops"]})
                last_read[k] = v
                readers[(k, v)].add(t["id"])
                if v is not None:
                    if (k, v) in failed_writes:
                        anomalies["G1a"].append({"key": k, "value": v})
                    elif (k, v) not in writer \
                            and (k, v) not in maybe_writes:
                        anomalies["unwritten-read"].append(
                            {"key": k, "value": v, "txn": t["ops"]})
                    w = writer.get((k, v))
                    if w is not None and w != t["id"]:
                        g.add(w, t["id"], "wr")
                        if final_write.get((w, k)) != v:
                            anomalies["G1b"].append({"key": k,
                                                     "value": v})
            else:
                if k not in wrote and k in last_read:
                    vo_pairs.add((k, last_read[k], v))
                wrote[k] = v

    # version-order inference (sound, never guessed):
    #   - wfr: a txn that read version v1 of k and then wrote v2 orders
    #     v1 < v2
    #   - the initial (nil) version precedes every written version
    # From v1 < v2 follow ww (writer(v1) -> writer(v2)) and the
    # generalized anti-dependency rw: ANY txn that observed v1 must
    # precede the writer of any later version (it would have seen it
    # otherwise) — this is what exposes write skew and other G2-item /
    # G-single cycles the wr/session edges alone cannot.
    writers_by_key: Dict[Any, Set[int]] = defaultdict(set)
    for (k, v), w in writer.items():
        writers_by_key[k].add(w)
    # realtime version-order inference (strict only; Elle's realtime
    # version orders): a committed writer of (k, v') that COMPLETED
    # before a read of (k, v) was INVOKED must serialize before the
    # reader; the reader observes v, so v' cannot lie between v's
    # writer and the reader — v' < v in k's version order
    if consistency_model == "strict-serializable":
        for (k, v), rs in list(readers.items()):
            if v is None or writer.get((k, v)) is None:
                continue
            w = writer[(k, v)]
            for (k2, v2), w2 in writer.items():
                if k2 != k or v2 == v or w2 == w:
                    continue
                if any(committed[w2]["end"] < committed[r]["index"]
                       for r in rs if r != w2):
                    vo_pairs.add((k, v2, v))
    # a nil-reader that itself writes k precedes every other writer of
    # k (its nil read pins it before them all), so ITS version is k's
    # FIRST: every other version follows it — vo pairs, hence ww +
    # generalized-rw edges (e.g. a later reader of this first version
    # anti-depends on every other writer of k). Sound ONLY under a
    # serialization assumption (at read-committed the nil read may be
    # legally stale while the write installs late), so gated like the
    # realtime inference above — weaker models must not inherit ww
    # edges that would classify as G0 there.
    if _model_leq("serializable", consistency_model):
        own_write: Dict[Tuple[Any, int], List[Any]] = defaultdict(list)
        for (k, v), w in writer.items():
            own_write[(k, w)].append(v)
        for (k, v), rs in list(readers.items()):
            if v is not None:
                continue
            for r in rs:
                for v2 in own_write.get((k, r), ()):
                    for w3 in writers_by_key.get(k, ()):
                        if w3 != r:
                            for v3 in own_write.get((k, w3), ()):
                                vo_pairs.add((k, v2, v3))
    for k, v1, v2 in vo_pairs:
        w2 = writer.get((k, v2))
        if w2 is None:
            continue
        w1 = writer.get((k, v1)) if v1 is not None else None
        if w1 is not None and w1 != w2:
            g.add(w1, w2, "ww")
        for r in readers.get((k, v1), ()):
            if r != w2:
                g.add(r, w2, "rw")
    # nil precedes everything: its readers anti-depend on every writer
    for (k, v), rs in list(readers.items()):
        if v is not None:
            continue
        for w2 in writers_by_key.get(k, ()):
            for r in rs:
                if r != w2:
                    g.add(r, w2, "rw")

    # filter_timeout: reference parity — the rw-register workload drops
    # cycle-search timeouts (txn_rw_register.clj:138-150)
    return _finish(g, committed, anomalies, consistency_model,
                   cycle_search_budget=cycle_search_budget,
                   filter_timeout=True)
