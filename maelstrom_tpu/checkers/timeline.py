"""timeline.html: a per-process visual timeline of operations, colored by
outcome, with hover details. Parity: jepsen.checker.timeline/html as
composed into the reference's checker (core.clj:91-100)."""

from __future__ import annotations

import html
from collections import defaultdict

from ..gen.history import pairs

_COLOR = {"ok": "#a2d9a2", "fail": "#f6a4a4", "info": "#f5d58a"}

ROW_H = 18
PX_PER_S = 120.0


def render_timeline(history, path: str):
    procs = []
    ops_by_proc = defaultdict(list)
    t_max = 1.0
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        proc = inv.get("process")
        # histories assembled outside the runner (nemesis-only records,
        # hand-written fixtures, external EDN imports) may lack time
        # fields — skip untimed invokes instead of raising KeyError,
        # and draw an untimed completion as instantaneous
        if inv.get("time") is None:
            continue
        if proc not in ops_by_proc:
            procs.append(proc)
        t0 = inv["time"] / 1e9
        t1 = (comp["time"] / 1e9) if comp and comp.get("time") is not None \
            else t0 + 0.01
        outcome = comp["type"] if comp else "info"
        ops_by_proc[proc].append((t0, t1, outcome, inv, comp))
        t_max = max(t_max, t1)

    rows = []
    for i, proc in enumerate(procs):
        # lanes and ops are both absolutely positioned at i * ROW_H so
        # bars always sit inside their own process row
        rows.append(
            f'<div class="lane" style="top:{i * ROW_H}px">'
            f'<span class="proc">{html.escape(str(proc))}</span></div>')
        for (t0, t1, outcome, inv, comp) in ops_by_proc[proc]:
            left = t0 * PX_PER_S
            width = max((t1 - t0) * PX_PER_S, 2)
            title = (f"{inv.get('f')} {inv.get('value')!r} -> "
                     f"{outcome}"
                     + (f" {comp.get('value')!r}" if comp else ""))
            rows.append(
                f'<div class="op" style="top:{i * ROW_H + 2}px;'
                f'left:{left + 80:.1f}px;width:{width:.1f}px;'
                f'background:{_COLOR.get(outcome, "#ccc")}" '
                f'title="{html.escape(title)}"></div>')

    doc = f"""<!doctype html>
<html><head><meta charset="utf-8"><title>timeline</title><style>
body {{ font-family: sans-serif; margin: 0; }}
.wrap {{ position: relative; height: {len(procs) * ROW_H + 40}px;
         width: {t_max * PX_PER_S + 160:.0f}px; }}
.lane {{ position: absolute; left: 0; right: 0; height: {ROW_H}px;
         box-sizing: border-box; border-bottom: 1px solid #eee; }}
.proc {{ font-size: 11px; color: #666; padding-left: 4px; }}
.op {{ position: absolute; height: {ROW_H - 4}px; border-radius: 2px;
       box-sizing: border-box; border: 1px solid rgba(0,0,0,0.2); }}
h1 {{ font-size: 14px; padding: 4px 8px; margin: 0; }}
</style></head><body>
<h1>operation timeline (hover for details)</h1>
<div class="wrap">
{chr(10).join(rows)}
</div></body></html>"""
    with open(path, "w") as f:
        f.write(doc)
