"""PN-counter checker: interval arithmetic over possible counter values.

The true final value must equal the sum of all *definitely applied* adds
plus any subset of *possibly applied* (indeterminate) adds. We track the set
of attainable values as a sorted list of disjoint closed integer ranges
(merging adjacent ranges, like a Guava TreeRangeSet): starting from
``[sum(definite), sum(definite)]``, each indeterminate delta ``d`` maps the
range set ``R`` to ``R ∪ (R + d)``. Every final read must land inside the
resulting set.

Parity: reference src/maelstrom/workload/pn_counter.clj:79-125.
"""

from __future__ import annotations

from typing import List, Tuple

# blow-up guard: beyond this many disjoint ranges, collapse to the convex
# hull (sound: may accept a value the precise set would reject, never rejects
# a valid history)
MAX_RANGES = 100_000


def _merge(ranges: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    if not ranges:
        return []
    ranges.sort()
    out = [ranges[0]]
    for lo, hi in ranges[1:]:
        plo, phi = out[-1]
        if lo <= phi + 1:
            out[-1] = (plo, max(phi, hi))
        else:
            out.append((lo, hi))
    return out


def _add_delta(ranges: List[Tuple[int, int]], d: int
               ) -> List[Tuple[int, int]]:
    shifted = [(lo + d, hi + d) for lo, hi in ranges]
    merged = _merge(ranges + shifted)
    if len(merged) > MAX_RANGES:
        return [(merged[0][0], merged[-1][1])]
    return merged


def _contains(ranges: List[Tuple[int, int]], v: int) -> bool:
    import bisect
    i = bisect.bisect_right(ranges, (v, float("inf"))) - 1
    return i >= 0 and ranges[i][0] <= v <= ranges[i][1]


def pn_counter_checker(history) -> dict:
    from ..gen.history import pairs
    definite_sum = 0
    indeterminate: List[int] = []
    final_reads = {}        # process -> last ok read tagged final
    fallback_reads = {}     # process -> last ok read (untagged histories)
    for p in pairs(history):
        inv, comp = p["invoke"], p["complete"]
        if inv.get("process") == "nemesis":
            continue
        if inv["f"] == "add":
            if comp is not None and comp["type"] == "ok":
                definite_sum += inv["value"]
            elif comp is None or comp["type"] == "info":
                indeterminate.append(inv["value"])
        elif inv["f"] == "read" and comp is not None \
                and comp["type"] == "ok":
            if inv.get("final"):
                final_reads[inv["process"]] = comp["value"]
            fallback_reads[inv["process"]] = comp["value"]
    # only reads from the final (post-heal, quiesced) phase are judged; a
    # history with no tagged reads falls back to last-read-per-process
    if not final_reads:
        final_reads = fallback_reads
    ranges = [(definite_sum, definite_sum)]
    for d in indeterminate:
        if d:
            ranges = _add_delta(ranges, d)
    errors = {p: v for p, v in final_reads.items()
              if not isinstance(v, int) or not _contains(ranges, v)}
    return {
        "valid?": (not errors) if final_reads else "unknown",
        "errors": errors,
        "final-reads": list(final_reads.values()),
        "acceptable": [list(r) for r in ranges[:64]],
        "acceptable-range-count": len(ranges),
        "definite-sum": definite_sum,
        "indeterminate-count": len(indeterminate),
    }
