"""Built-in network services.

Services are harness-provided nodes that user nodes can call as primitives:
``lin-kv`` (linearizable KV), ``seq-kv`` (sequentially-consistent KV),
``lww-kv`` (eventually-consistent last-write-wins KV), and ``lin-tso`` (a
linearizable monotonic timestamp oracle). Each service is a *pure state
machine* wrapped in a *consistency wrapper* and run as a network node on its
own thread.

Parity: reference src/maelstrom/service.clj — PersistentKV :31-56, LWWKV
:65-114, PersistentTSO :116-132, Linearizable :141-155, Sequential :161-210,
Eventual :214-243, worker loop :245-263, default services :290-296.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, Optional, Tuple

from ..core import errors
from ..core.errors import RPCError
from ..core.message import reply_body
from ..net.net import Net


# --- pure state machines --------------------------------------------------

class PersistentKV:
    """read / write / cas over an immutable map (service.clj:31-56)."""

    name = "persistent-kv"

    def initial(self):
        return {}

    def read_only(self, body: dict) -> bool:
        return body.get("type") == "read"

    def handle(self, state: dict, body: dict) -> Tuple[dict, dict]:
        t = body.get("type")
        if t == "read":
            k = body.get("key")
            if k not in state:
                raise errors.key_does_not_exist(f"key {k!r} does not exist")
            return state, reply_body(body, type="read_ok", value=state[k])
        if t == "write":
            s = dict(state)
            s[body.get("key")] = body.get("value")
            return s, reply_body(body, type="write_ok")
        if t == "cas":
            k = body.get("key")
            if k not in state:
                if body.get("create_if_not_exists"):
                    s = dict(state)
                    s[k] = body.get("to")
                    return s, reply_body(body, type="cas_ok")
                raise errors.key_does_not_exist(f"key {k!r} does not exist")
            if state[k] != body.get("from"):
                raise errors.precondition_failed(
                    f"expected {body.get('from')!r}, but had {state[k]!r}")
            s = dict(state)
            s[k] = body.get("to")
            return s, reply_body(body, type="cas_ok")
        raise errors.not_supported(f"unknown op type {t!r}")


class LWWKV(PersistentKV):
    """Last-write-wins KV: every value carries a Lamport clock; states are
    mergeable by pointwise max of (clock, value) (service.clj:65-114)."""

    name = "lww-kv"

    def initial(self):
        # key -> (clock, value); plus a local clock under key ``"__clock__"``
        return {"__clock__": 0}

    def _tick(self, state):
        return state["__clock__"] + 1

    def handle(self, state, body):
        t = body.get("type")
        clock = self._tick(state)
        if t == "read":
            k = body.get("key")
            if k == "__clock__" or k not in state:
                raise errors.key_does_not_exist(f"key {k!r} does not exist")
            _, v = state[k]
            return state, reply_body(body, type="read_ok", value=v)
        if t == "write":
            s = dict(state)
            s[body.get("key")] = (clock, body.get("value"))
            s["__clock__"] = clock
            return s, reply_body(body, type="write_ok")
        if t == "cas":
            k = body.get("key")
            if k == "__clock__" or k not in state:
                if body.get("create_if_not_exists"):
                    s = dict(state)
                    s[k] = (clock, body.get("to"))
                    s["__clock__"] = clock
                    return s, reply_body(body, type="cas_ok")
                raise errors.key_does_not_exist(f"key {k!r} does not exist")
            _, v = state[k]
            if v != body.get("from"):
                raise errors.precondition_failed(
                    f"expected {body.get('from')!r}, but had {v!r}")
            s = dict(state)
            s[k] = (clock, body.get("to"))
            s["__clock__"] = clock
            return s, reply_body(body, type="cas_ok")
        raise errors.not_supported(f"unknown op type {t!r}")

    def merge(self, a, b):
        """Pointwise last-write-wins merge: higher clock wins; equal clocks
        tie-break deterministically on the value's repr (values may be
        mutually incomparable JSON)."""
        def newer(x, y):
            return (y[0], repr(y[1])) > (x[0], repr(x[1]))

        out = dict(a)
        for k, v in b.items():
            if k == "__clock__":
                out[k] = max(out.get(k, 0), v)
            elif k not in out or newer(out[k], v):
                out[k] = v
        return out


class PersistentTSO:
    """Monotonic timestamp oracle (service.clj:116-132)."""

    name = "lin-tso"

    def initial(self):
        return 0

    def read_only(self, body):
        return False

    def handle(self, state, body):
        if body.get("type") == "ts":
            return state + 1, reply_body(body, type="ts_ok", ts=state + 1)
        raise errors.not_supported(f"unknown op type {body.get('type')!r}")


# --- consistency wrappers -------------------------------------------------

class Linearizable:
    """All ops applied to a single current state under a lock
    (service.clj:141-155)."""

    def __init__(self, machine):
        self.machine = machine
        self.state = machine.initial()
        self.lock = threading.Lock()

    def handle(self, client: str, body: dict) -> dict:
        with self.lock:
            self.state, reply = self.machine.handle(self.state, body)
            return reply


class Sequential:
    """Keeps a ring of recent states. Read-only ops from a client may be
    served by *any* state at least as new as that client's watermark (then
    advance the watermark); mutations always apply to the newest state. This
    yields a per-client-monotonic total order without real-time recency
    (service.clj:161-210)."""

    RING = 16

    def __init__(self, machine, seed: Optional[int] = None):
        self.machine = machine
        self.states = [machine.initial()]   # index 0 is oldest retained
        self.base = 0                       # absolute index of states[0]
        self.watermarks: Dict[str, int] = {}
        self.lock = threading.Lock()
        self.rng = random.Random(seed)

    def newest_index(self) -> int:
        return self.base + len(self.states) - 1

    def handle(self, client: str, body: dict) -> dict:
        with self.lock:
            wm = self.watermarks.get(client, self.base)
            wm = max(wm, self.base)
            if self.machine.read_only(body):
                idx = self.rng.randint(wm, self.newest_index())
                state = self.states[idx - self.base]
                _, reply = self.machine.handle(state, body)
                self.watermarks[client] = idx
                return reply
            state = self.states[-1]
            new_state, reply = self.machine.handle(state, body)
            self.states.append(new_state)
            if len(self.states) > self.RING:
                self.states.pop(0)
                self.base += 1
            self.watermarks[client] = self.newest_index()
            return reply


class Eventual:
    """n independent replicas; each op is applied at a random replica, and
    random pairs of replicas merge over time (service.clj:214-243). Requires
    a mergeable machine (LWWKV)."""

    def __init__(self, machine, n: int = 5, merge_prob: float = 0.5,
                 seed: Optional[int] = None):
        self.machine = machine
        self.replicas = [machine.initial() for _ in range(n)]
        self.lock = threading.Lock()
        self.merge_prob = merge_prob
        self.rng = random.Random(seed)

    def handle(self, client: str, body: dict) -> dict:
        with self.lock:
            if len(self.replicas) > 1 and self.rng.random() < self.merge_prob:
                i, j = self.rng.sample(range(len(self.replicas)), 2)
                self.replicas[i] = self.machine.merge(self.replicas[i],
                                                      self.replicas[j])
            i = self.rng.randrange(len(self.replicas))
            self.replicas[i], reply = self.machine.handle(self.replicas[i],
                                                          body)
            return reply


# --- service worker -------------------------------------------------------

class Service:
    """A wrapped state machine running as a network node on its own thread
    (service.clj:245-263)."""

    def __init__(self, name: str, wrapper, net: Net):
        self.name = name
        self.wrapper = wrapper
        self.net = net
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._run, name=f"svc-{name}",
                                       daemon=True)

    def start(self):
        self.net.add_node(self.name)
        self.thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                m = self.net.recv(self.name, timeout=0.2)
            except Exception:
                break
            if m is None:
                continue
            try:
                reply = self.wrapper.handle(m.src, m.body)
            except RPCError as e:
                reply = e.to_body(in_reply_to=m.body.get("msg_id"))
            except Exception as e:
                reply = RPCError(13, f"service {self.name} crashed: {e}"
                                 ).to_body(in_reply_to=m.body.get("msg_id"))
            try:
                self.net.send(self.name, m.src, reply)
            except Exception:
                pass

    def stop(self):
        self._stop.set()
        self.thread.join(timeout=2.0)
        self.net.remove_node(self.name)


def default_services(net: Net, seed: Optional[int] = None):
    """lww-kv (eventual), seq-kv (sequential), lin-kv (linearizable),
    lin-tso (linearizable TSO) — service.clj:290-296."""
    return [
        Service("lww-kv", Eventual(LWWKV(), seed=seed), net),
        Service("seq-kv", Sequential(PersistentKV(), seed=seed), net),
        Service("lin-kv", Linearizable(PersistentKV()), net),
        Service("lin-tso", Linearizable(PersistentTSO()), net),
    ]


def start_services(services):
    for s in services:
        s.start()
    return services


def stop_services(services):
    for s in services:
        s.stop()
