"""Cluster lifecycle: bring up services + node processes, init handshake,
teardown with crash diagnostics.

Parity: reference src/maelstrom/db.clj — setup :24-69 (services on primary,
spawn nodes, ``init`` RPC with 10s timeout requiring ``init_ok``), teardown
:71-82 (kill processes then services).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..core import errors
from ..net.net import Net
from .client import Client
from .process import NodeProcess, start_node
from .services import Service, default_services, start_services, stop_services

INIT_TIMEOUT = 10.0  # seconds (db.clj:60)


class DB:
    def __init__(self, net: Net, node_ids: List[str], bin: str,
                 args: Optional[List[str]] = None,
                 log_dir: Optional[str] = None, log_stderr: bool = False,
                 seed: Optional[int] = None):
        self.net = net
        self.node_ids = node_ids
        self.bin = bin
        self.args = args or []
        self.log_dir = log_dir
        self.log_stderr = log_stderr
        self.seed = seed
        self.processes: Dict[str, NodeProcess] = {}
        self.services: List[Service] = []

    def setup(self):
        self.services = start_services(default_services(self.net,
                                                        seed=self.seed))
        try:
            for node_id in self.node_ids:
                self.processes[node_id] = start_node(
                    node_id, self.bin, self.args, self.net,
                    log_dir=self.log_dir, log_stderr=self.log_stderr)
            self._init_all()
        except Exception:
            self.teardown(raise_crashes=False)
            raise

    def _init_all(self):
        """Send the init RPC to every node (db.clj:46-69)."""
        client = Client.open(self.net, timeout=INIT_TIMEOUT)
        try:
            for node_id in self.node_ids:
                body = {"type": "init", "node_id": node_id,
                        "node_ids": list(self.node_ids)}
                try:
                    reply = client.rpc(node_id, body, timeout=INIT_TIMEOUT)
                except errors.RPCError as e:
                    proc = self.processes.get(node_id)
                    extra = ""
                    if proc is not None and not proc.alive():
                        extra = "\n\n" + proc._crash_report(proc.proc.poll())
                    raise RuntimeError(
                        f"node {node_id} did not acknowledge the init "
                        f"message within {INIT_TIMEOUT}s: {e}{extra}"
                    ) from None
                if reply.get("type") != "init_ok":
                    raise RuntimeError(
                        f"expected init_ok from {node_id}, got {reply!r}")
        finally:
            client.close()

    def teardown(self, raise_crashes: bool = True):
        crash_errors = []
        for node_id, proc in self.processes.items():
            try:
                proc.stop()
            except Exception as e:
                crash_errors.append(e)
        self.processes = {}
        stop_services(self.services)
        self.services = []
        if crash_errors and raise_crashes:
            raise crash_errors[0]
