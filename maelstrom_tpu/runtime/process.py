"""Process runtime: runs user nodes as child processes.

Each node is an arbitrary binary speaking newline-delimited JSON over
STDIN/STDOUT and logging to STDERR. Three daemon threads bridge it to the
simulated network:

- stdin thread:  ``net.recv(node)`` -> JSON line -> child stdin
- stdout thread: child stdout line -> parse/validate -> ``net.send``
- stderr thread: child stderr line -> per-node log file (+ optional console)

The last 32 lines of stdout/stderr are kept in ring buffers so that crashes
produce useful diagnostics. Malformed output produces teaching-quality error
messages, since this framework is a learning tool first.

Parity: reference src/maelstrom/process.clj — io threads :68-166, ring
buffers :22-24, parse-msg :35-66, start-node! :168-215, stop-node! :217-256.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import threading
from collections import deque
from typing import List, Optional

from ..core.message import Message
from ..net.net import Net

RING_BUFFER_LINES = 32


class NodeCrashed(RuntimeError):
    pass


def parse_msg(node_id: str, line: str) -> Message:
    """Parse one stdout line from a node into a Message, with helpful
    errors (process.clj:35-66)."""
    try:
        d = json.loads(line)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"node {node_id} printed a line to STDOUT which was not "
            f"well-formed JSON:\n\n  {line!r}\n\nParse error: {e}. Remember "
            f"that every line printed to STDOUT must be a JSON message; use "
            f"STDERR for debugging output.") from None
    if not isinstance(d, dict):
        raise ValueError(
            f"node {node_id} printed a JSON value to STDOUT which was not "
            f"an object:\n\n  {line!r}\n\nMessages must be JSON objects with "
            f"src, dest, and body fields.")
    for k in ("src", "dest", "body"):
        if k not in d:
            raise ValueError(
                f"node {node_id} printed a message missing its {k!r} "
                f"field:\n\n  {line!r}")
    if not isinstance(d["body"], dict):
        raise ValueError(
            f"node {node_id} printed a message whose body is not an "
            f"object:\n\n  {line!r}")
    if not isinstance(d["body"].get("type"), str):
        raise ValueError(
            f"node {node_id} printed a message whose body has no string "
            f"'type' field:\n\n  {line!r}")
    return Message(id=-1, src=d["src"], dest=d["dest"], body=d["body"])


class NodeProcess:
    """A running node child process bridged to the network."""

    def __init__(self, node_id: str, cmd: List[str], net: Net,
                 log_path: Optional[str] = None, log_stderr: bool = False):
        self.node_id = node_id
        self.cmd = cmd
        self.net = net
        self.log_stderr = log_stderr
        self.stdout_ring = deque(maxlen=RING_BUFFER_LINES)
        self.stderr_ring = deque(maxlen=RING_BUFFER_LINES)
        self.error: Optional[Exception] = None
        self._stop = threading.Event()
        self._log_file = open(log_path, "w") if log_path else None

        # Node processes are plain protocol programs: make sure they never
        # initialize an accelerator runtime, even on machines where a
        # sitecustomize hook registers one in every interpreter (concurrent
        # child startups would otherwise contend for the device and hang).
        env = {k: v for k, v in os.environ.items()
               if k not in ("PALLAS_AXON_POOL_IPS",)}
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1, env=env)
        self._threads = [
            threading.Thread(target=self._stdin_loop,
                             name=f"{node_id}-stdin", daemon=True),
            threading.Thread(target=self._stdout_loop,
                             name=f"{node_id}-stdout", daemon=True),
            threading.Thread(target=self._stderr_loop,
                             name=f"{node_id}-stderr", daemon=True),
        ]
        for t in self._threads:
            t.start()

    # --- io threads -------------------------------------------------------

    def _stdin_loop(self):
        """Pump network deliveries into the child's stdin
        (process.clj:154-166)."""
        try:
            while not self._stop.is_set():
                m = self.net.recv(self.node_id, timeout=1.0)
                if m is None:
                    continue
                line = json.dumps(m.to_wire())
                self.proc.stdin.write(line + "\n")
                self.proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass  # child exited
        except Exception as e:  # node removed from net etc.
            if not self._stop.is_set():
                self.error = self.error or e

    def _stdout_loop(self):
        """Parse the child's stdout lines and put them on the network
        (process.clj:136-152)."""
        try:
            for line in self.proc.stdout:
                line = line.rstrip("\n")
                if not line:
                    continue
                self.stdout_ring.append(line)
                try:
                    m = parse_msg(self.node_id, line)
                except ValueError as e:
                    self.error = self.error or e
                    continue
                try:
                    self.net.send(m.src, m.dest, m.body)
                except Exception as e:
                    self.error = self.error or e
        except (OSError, ValueError):
            pass

    def _stderr_loop(self):
        """Copy the child's stderr to the node log (process.clj:115-134)."""
        try:
            for line in self.proc.stderr:
                line = line.rstrip("\n")
                self.stderr_ring.append(line)
                if self._log_file:
                    self._log_file.write(line + "\n")
                    self._log_file.flush()
                if self.log_stderr:
                    print(f"[{self.node_id}] {line}", flush=True)
        except (OSError, ValueError):
            pass

    # --- lifecycle --------------------------------------------------------

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, timeout: float = 5.0):
        """Stop the node; raise NodeCrashed with diagnostics if it had
        already died or misbehaved (process.clj:217-256)."""
        crashed = not self.alive()
        exit_code = self.proc.poll()
        self._stop.set()
        if not crashed:
            try:
                self.proc.terminate()
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        for t in self._threads:
            t.join(timeout=2.0)
        for pipe in (self.proc.stdin, self.proc.stdout, self.proc.stderr):
            try:
                pipe and pipe.close()
            except Exception:
                pass
        if self._log_file:
            self._log_file.close()
            self._log_file = None
        if crashed:
            raise NodeCrashed(self._crash_report(exit_code))
        if self.error:
            e, self.error = self.error, None
            raise NodeCrashed(
                f"node {self.node_id} emitted invalid output:\n{e}")

    def _crash_report(self, exit_code) -> str:
        out = "\n".join(self.stdout_ring) or "(none)"
        err = "\n".join(self.stderr_ring) or "(none)"
        return (f"node {self.node_id} ({shlex.join(self.cmd)}) exited with "
                f"status {exit_code} before the test finished.\n\n"
                f"Last lines of STDOUT:\n{out}\n\n"
                f"Last lines of STDERR:\n{err}")


def start_node(node_id: str, bin: str, args: List[str], net: Net,
               log_dir: Optional[str] = None,
               log_stderr: bool = False) -> NodeProcess:
    """Register node_id on the network and spawn its binary
    (process.clj:168-215)."""
    net.add_node(node_id)
    log_path = None
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"{node_id}.log")
    cmd = [bin] + list(args)
    return NodeProcess(node_id, cmd, net, log_path=log_path,
                       log_stderr=log_stderr)
