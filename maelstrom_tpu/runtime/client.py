"""Client / RPC layer.

A :class:`Client` occupies a network node id ``c<N>`` and issues synchronous,
one-outstanding-request RPCs to nodes: send a request with a fresh ``msg_id``,
then receive until a reply with matching ``in_reply_to`` arrives or the
timeout elapses. ``error`` replies raise :class:`~..core.errors.RPCError`;
:func:`with_errors` maps exceptions to operation outcomes the way checkers
expect (timeouts / indefinite errors -> ``info`` unless the op is idempotent,
definite errors -> ``fail``).

Parity: reference src/maelstrom/client.clj — open!/close! :41-59, send!
:66-79, recv! :81-117, rpc! :140-151, with-errors :153-172, defrpc
schema-checking :228-270 (here :func:`rpc_call` + the schema registry).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Set

from ..core import errors, schema
from ..core.errors import RPCError
from ..net.net import Net

DEFAULT_TIMEOUT = 5.0   # seconds (client.clj:18-20)


class Client:
    def __init__(self, net: Net, node_id: str,
                 timeout: float = DEFAULT_TIMEOUT):
        self.net = net
        self.node_id = node_id
        self.timeout = timeout
        self._next_msg_id = 0
        self._lock = threading.Lock()

    @classmethod
    def open(cls, net: Net, timeout: float = DEFAULT_TIMEOUT) -> "Client":
        """Allocate a fresh client node id c0, c1, ... on this network."""
        with net._client_ctr_lock:
            n = net._client_ctr
            net._client_ctr = n + 1
        node_id = f"c{n}"
        net.add_node(node_id)
        return cls(net, node_id, timeout)

    def close(self):
        self.net.remove_node(self.node_id)

    def new_msg_id(self) -> int:
        with self._lock:
            i = self._next_msg_id
            self._next_msg_id = i + 1
            return i

    def send(self, dest: str, body: dict) -> int:
        """Send a request with a fresh msg_id; returns the msg_id."""
        body = dict(body)
        msg_id = self.new_msg_id()
        body["msg_id"] = msg_id
        self.net.send(self.node_id, dest, body)
        return msg_id

    def recv_reply(self, msg_id: int, timeout: Optional[float] = None) -> dict:
        """Receive until a reply to msg_id arrives; unrelated messages are
        discarded (one outstanding request at a time, client.clj:81-117)."""
        timeout = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise errors.timeout(
                    f"timed out after {timeout}s waiting for reply to "
                    f"msg {msg_id} on {self.node_id}")
            m = self.net.recv(self.node_id, remaining)
            if m is None:
                raise errors.timeout(
                    f"timed out after {timeout}s waiting for reply to "
                    f"msg {msg_id} on {self.node_id}")
            if m.body.get("in_reply_to") == msg_id:
                return self._throw_errors(m.body)

    @staticmethod
    def _throw_errors(body: dict) -> dict:
        if body.get("type") == "error":
            code = body.get("code")
            if not isinstance(code, int):
                raise errors.malformed_request(
                    f"error body without integer code: {body!r}")
            raise RPCError(code, body.get("text", ""))
        return body

    def rpc(self, dest: str, body: dict,
            timeout: Optional[float] = None) -> dict:
        msg_id = self.send(dest, body)
        return self.recv_reply(msg_id, timeout)


def rpc_call(client: Client, dest: str, namespace: str, rpc_type: str,
             timeout: Optional[float] = None, **fields) -> dict:
    """Schema-checked RPC using the registry (the defrpc equivalent).

    Validates the request body against the registered request schema, issues
    the RPC, and validates the reply against the response schema.
    """
    d = schema.get_rpc(namespace, rpc_type)
    body = dict(fields)
    body["type"] = rpc_type
    if d is not None:
        schema.check(d.full_request_schema(), {**body, "msg_id": 0},
                     f"{rpc_type} request")
    resp = client.rpc(dest, body, timeout)
    if d is not None:
        schema.check(d.full_response_schema(), resp, f"{rpc_type} response")
    return resp


def with_errors(op: dict, idempotent: Set[str], fn):
    """Run fn() (which completes ``op`` and returns it); map errors to
    Jepsen-style outcomes (client.clj:153-172):

    - timeout / indefinite error -> type ``fail`` if op's :f is idempotent
      (safe to treat an unknown outcome as failure), else ``info``
    - definite RPC error -> type ``fail`` with the error attached
    """
    try:
        return fn()
    except RPCError as e:
        out = dict(op)
        if e.code == 0:  # timeout
            out["type"] = "fail" if op.get("f") in idempotent else "info"
            out["error"] = ["timeout", e.text]
        elif e.definite:
            out["type"] = "fail"
            out["error"] = [e.name, e.text]
        else:
            out["type"] = "fail" if op.get("f") in idempotent else "info"
            out["error"] = [e.name, e.text]
        return out
