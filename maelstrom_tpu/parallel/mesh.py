"""Multi-chip scaling: shard the instance batch over a device mesh.

Protocol instances are independent, so the natural parallelism is pure
data parallelism along the instance axis: each device simulates its own
block of clusters, and the only cross-device communication is a ``psum``
of the fleet-wide net counters — which rides ICI. Recorded-instance
event tensors stay sharded and are gathered once at the end for the
host-side checkers.

Shard assignment is **round-robin over GLOBAL instance ids** under one
master RNG key: shard *s* of *S* simulates global ids ``{j*S + s}``, and
every draw folds ``(purpose, tick, global id)`` into the single
``PRNGKey(seed)`` (tpu/runtime.py's purity invariant). An instance's
trajectory is therefore a pure function of ``(seed, global id)`` —
independent of the shard count — which is what makes a checkpoint
written at S shards resumable at S' shards bit-identically
(``campaign/checkpoint.reshard_carry``): re-chunking the instance axis
moves state between devices but never changes any instance's stream.
Round-robin (rather than contiguous blocks) keeps the RECORDED instance
set shard-count-invariant too: the first R locals of every shard are
exactly global ids ``{0 .. R*S-1}`` for any S. Gathered per-instance
outputs cross the wire shard-major and are re-ordered to global-id
order on host (:func:`deinterleave`).

This is the TPU-native replacement for the reference's "scale = more
processes/threads on one JVM" model (SURVEY §2.4 data-parallelism row):
the batch axis over chips via ``jax.shard_map`` over a 1-D ``Mesh``, with
XLA inserting the collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..tpu.pipeline import DEFAULT_SCAN_TOP_K
from ..tpu.runtime import Carry, Model, NetStats, SimConfig, simulate
from ..telemetry.recorder import Telemetry

AXIS = "instances"


def _shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the public API (>= 0.6,
    ``check_vma``) when present, else the experimental one (0.4.x,
    ``check_rep``). Replication checking is off either way — the scan
    carry mixes unvaried zero-init leaves with seed-varied ones (see
    the callers' notes)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)

# reshard kinds of the wire-carry leaves (checkpointed per leaf so
# campaign/checkpoint.reshard_carry can re-chunk a wire written at S
# shards onto S' shards; see wire_leaf_kinds)
SHARD_LEAF_INSTANCE = "instance"   # chunks along the global instance axis
SHARD_LEAF_SUM = "sum"             # one additive partial-sum slot per shard
SHARD_LEAF_KEY = "key"             # the replicated master RNG key


def _seed32(seed: int) -> int:
    """Wrap an arbitrary python-int seed into int32 range so huge-but-
    valid seeds behave the same on host and on device (both sharded
    paths AND the unsharded oracle derive from this one value)."""
    return (int(seed) + 2**31) % 2**32 - 2**31


def shard_instance_ids(n_instances: int, n_shards: int):
    """``[n_shards, n_instances]`` GLOBAL instance ids per shard under
    the round-robin assignment: shard ``s`` simulates global ids
    ``{j * n_shards + s : j < n_instances}``. The deterministic id
    layout both sharded runners and the ``run_sim_unsharded`` oracle
    derive their RNG streams from (``n_instances`` is PER SHARD)."""
    import numpy as np
    return np.arange(n_shards * n_instances, dtype=np.int32).reshape(
        n_instances, n_shards).T.copy()


def _shard_index(mesh):
    """This shard's flat index in [0, mesh.size) — row-major over the
    mesh axes, matching the order sharded outputs concatenate in under
    ``P(axes)``. Traced (shard_map body only)."""
    sizes = dict(mesh.shape)
    idx = jnp.int32(0)
    for ax in mesh.axis_names:
        idx = idx * sizes[ax] + jax.lax.axis_index(ax)
    return idx


def _shard_ids(mesh, n_instances: int):
    """The executing shard's global instance ids (traced; shard_map
    body only) — row ``_shard_index(mesh)`` of
    :func:`shard_instance_ids`."""
    return (jnp.arange(n_instances, dtype=jnp.int32) * mesh.size
            + _shard_index(mesh))


def deinterleave(x, n_shards: int, axis: int = 0):
    """Reorder a shard-major gathered axis (shard s's block of I locals
    at ``[s*I, (s+1)*I)``; local j holding global id ``j*S + s``) into
    global-id order. Host-side (numpy) — reordering a sharded axis on
    device would be an all-to-all."""
    import numpy as np
    x = np.asarray(x)
    if n_shards <= 1:
        return x
    x = np.moveaxis(x, axis, 0)
    s = int(n_shards)
    t = x.shape[0]
    x = x.reshape((s, t // s) + x.shape[1:]).swapaxes(0, 1).reshape(
        (t,) + x.shape[1:])
    return np.moveaxis(x, 0, axis)


def interleave(x, n_shards: int, axis: int = 0):
    """Inverse of :func:`deinterleave`: chunk a global-id-ordered axis
    into the shard-major round-robin layout the wire uses."""
    import numpy as np
    x = np.asarray(x)
    if n_shards <= 1:
        return x
    x = np.moveaxis(x, axis, 0)
    s = int(n_shards)
    t = x.shape[0]
    x = x.reshape((t // s, s) + x.shape[1:]).swapaxes(0, 1).reshape(
        (t,) + x.shape[1:])
    return np.moveaxis(x, 0, axis)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first n_devices (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devs)} devices are visible "
                f"(set --xla_force_host_platform_device_count for a "
                f"virtual CPU mesh)")
        devs = devs[:n_devices]
    import numpy as np
    return Mesh(np.array(devs), (AXIS,))


def _empty_events(model: Model, sim: SimConfig, n_ticks=None):
    """Zero-size dense event block for record_instances == 0 shards —
    the tick fns emit no events ys at all then (TickOutputs.events is
    None), but a uniform array must still cross the shard_map wire."""
    ticks = sim.n_ticks if n_ticks is None else n_ticks
    return jnp.zeros((ticks, 0, sim.client.n_clients, 2,
                      2 + model.ev_vals), jnp.int32)


def _tel_out_spec(tel: Telemetry, axes):
    """Per-instance telemetry leaves concatenate across shards; the
    fleet series buffer is shard-local and comes back psum'd."""
    spec = jax.tree.map(lambda _: P(axes), tel)
    return spec._replace(series=P())


def merge_unsharded_telemetry(tels):
    """Host-side equivalent of the shard_map telemetry merge: concat
    the per-instance leaves across shards, sum the fleet series (the
    oracle side of the sharded-telemetry equivalence tests)."""
    import numpy as np
    tels = list(tels)
    merged = jax.tree.map(lambda *xs: np.concatenate(
        [np.asarray(x) for x in xs], axis=0), *tels)
    return merged._replace(series=sum(np.asarray(t.series)
                                      for t in tels))


@partial(jax.jit, static_argnames=("model", "sim", "mesh"))
def _run_sharded(model: Model, sim: SimConfig, mesh: Mesh, seed, params):
    """seed: the replicated int32 master seed; ``sim`` describes the
    PER-DEVICE shard (each shard derives its own global instance ids
    from its mesh position). Works for any mesh rank — stats psum over
    every mesh axis, sharded outputs split over all axes jointly (so a
    1-D ICI mesh and a 2-D DCN x ICI hybrid mesh share this code path).
    Returns (stats, violations, events, telemetry) where telemetry is
    the MERGED per-instance recorder (instance leaves concatenated over
    shards, fleet series psum'd) or None when telemetry is off;
    per-instance outputs come back in SHARD-MAJOR order (the wrapper
    deinterleaves on host)."""
    axes = mesh.axis_names
    with_tel = sim.telemetry.enabled

    def shard_body(seed_rep, params_rep):
        ids = _shard_ids(mesh, sim.n_instances)
        with jax.named_scope("simulate_shard"):
            carry, ys = simulate(model, sim, seed_rep.reshape(()),
                                 params_rep, instance_ids=ids)
        stats = carry.stats
        with jax.named_scope("psum_stats"):
            for ax in axes:
                stats = jax.tree.map(lambda x: jax.lax.psum(x, ax), stats)
        events = (ys.events if ys.events is not None
                  else _empty_events(model, sim))
        if not with_tel:
            return stats, carry.violations, events
        tel = carry.telemetry
        with jax.named_scope("psum_series"):
            series = tel.series
            for ax in axes:
                series = jax.lax.psum(series, ax)
        return stats, carry.violations, events, tel._replace(
            series=series)

    out_specs = (P(), P(axes), P(None, axes))
    if with_tel:
        from ..telemetry.recorder import init_telemetry
        tel_template = jax.eval_shape(
            lambda: init_telemetry(sim.n_instances, sim.telemetry))
        out_specs = out_specs + (_tel_out_spec(tel_template, axes),)

    # zero-initialized carry components are unvaried constants while the
    # seed-derived ones vary per shard; check_vma would reject the scan
    # carry mix, and everything here is embarrassingly parallel anyway
    out = _shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=out_specs,
    )(seed, params)
    if not with_tel:
        return out + (None,)
    return out


def _deinterleave_outputs(violations, events, tel, n_shards: int):
    """Host-side re-order of the sharded runners' per-instance outputs
    from shard-major wire order to global-id order (shared by the
    sharded paths and the run_sim_unsharded oracle so they can never
    drift)."""
    violations = deinterleave(violations, n_shards, axis=0)
    events = deinterleave(events, n_shards, axis=1)
    if tel is not None:
        import numpy as np
        series = np.asarray(tel.series)
        # the fleet series buffer is psum'd, not instance-batched — keep
        # it out of the per-instance re-order ('()' has no tree leaves)
        tel = jax.tree.map(
            lambda x: deinterleave(x, n_shards, axis=0),
            tel._replace(series=()))
        tel = tel._replace(series=series)
    return violations, events, tel


def run_sim_unsharded(model: Model, sim: SimConfig, seed: int,
                      n_shards: int, params=None,
                      return_telemetry: bool = False):
    """The equivalence oracle for :func:`run_sim_sharded`: replay every
    shard's ``simulate`` serially on one device with the identical
    master seed and the identical global instance ids
    (:func:`shard_instance_ids`) and accumulate the same (stats,
    violations, events) triple — plus, with ``return_telemetry``, the
    merged per-instance recorder. A sharded run must match this
    bit-for-bit — shard_map and collective placement may change
    performance, never results."""
    import numpy as np

    if params is None:
        params = model.make_params(sim.net.n_nodes)
    run_one = jax.jit(lambda ids: simulate(
        model, sim, jnp.int32(_seed32(seed)), params, instance_ids=ids))
    all_ids = shard_instance_ids(sim.n_instances, n_shards)
    stats, viol, evs, tels = None, [], [], []
    for s in range(n_shards):
        carry_u, ys_u = run_one(jnp.asarray(all_ids[s]))
        st = jax.tree.map(int, carry_u.stats)
        stats = st if stats is None else jax.tree.map(
            lambda a, b: a + b, stats, st)
        viol.append(np.asarray(carry_u.violations))
        evs.append(np.asarray(ys_u.events)
                   if ys_u.events is not None
                   else np.asarray(_empty_events(model, sim)))
        if carry_u.telemetry is not None:
            tels.append(carry_u.telemetry)
    tel = merge_unsharded_telemetry(tels) if tels else None
    violations, events, tel = _deinterleave_outputs(
        np.concatenate(viol, axis=0), np.concatenate(evs, axis=1),
        tel, n_shards)
    out = (NetStats(*stats), violations, events)
    if return_telemetry:
        out = out + (tel,)
    return out


def _carry_to_wire(c: Carry, sim: SimConfig) -> Carry:
    """Reshape a per-shard Carry so EVERY leaf has a leading
    shard-divisible axis (scalars -> [1], key [2] -> [1, 2]) and can
    cross a shard_map boundary under a uniform ``P(axes)`` spec. The
    wire format is canonical (instance axis LEADING) whatever the sim's
    internal layout — one transpose per chunk dispatch, amortized over
    the chunk's ticks."""
    from ..tpu.runtime import canonical_carry
    c = canonical_carry(c, sim)
    tel = c.telemetry
    if tel is not None:
        # per-instance telemetry leaves already lead with the instance
        # axis; only the fleet series buffer (shard-local, not
        # instance-batched) needs a leading shard axis like stats/key
        tel = tel._replace(series=tel.series.reshape(
            (1,) + tel.series.shape))
    return Carry(
        pool=c.pool, node_state=c.node_state,
        client_state=c.client_state,
        # the fault engine's snapshot slab and the fuzzer's randomized
        # schedule lanes are instance-batched like node_state
        # (canonical_carry already led their batch axes)
        snapshots=c.snapshots,
        fault_sched=c.fault_sched,
        stats=jax.tree.map(lambda x: x.reshape(1), c.stats),
        violations=c.violations,
        key=c.key.reshape(1, *c.key.shape),
        telemetry=tel,
        # device verdict lanes are [I, N_LANES] batch-LEADING in both
        # layouts — already wire-shaped (an ordinary instance leaf)
        check_summary=c.check_summary)


def _carry_from_wire(w: Carry, sim: SimConfig) -> Carry:
    from ..tpu.runtime import carry_from_canonical
    tel = w.telemetry
    if tel is not None:
        tel = tel._replace(series=tel.series.reshape(
            tel.series.shape[1:]))
    c = Carry(
        pool=w.pool, node_state=w.node_state,
        client_state=w.client_state,
        snapshots=w.snapshots,
        fault_sched=w.fault_sched,
        stats=jax.tree.map(lambda x: x.reshape(()), w.stats),
        violations=w.violations,
        key=w.key.reshape(*w.key.shape[1:]),
        telemetry=tel,
        check_summary=w.check_summary)
    return carry_from_canonical(c, sim)


def wire_template(model: Model, sim: SimConfig, mesh: Mesh, params=None):
    """Abstract template (shapes/dtypes/treedef) of the GLOBAL wire
    carry ``run_sim_sharded_chunked`` threads between dispatches: the
    per-shard wire with every leading axis scaled by the shard count
    (each leaf crosses the shard_map boundary under ``P(axes)``).
    ``campaign/checkpoint.restore_carry`` validates a sharded
    checkpoint against it on resume — a mesh-size mismatch routes
    through ``reshard_carry`` (pure shard-count change) or fails the
    shape check instead of silently mis-sharding. Accepts an
    ``AbstractMesh`` (the shard auditor's no-device path) — only the
    mesh size is consumed."""
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)
    n = int(mesh.size)
    shard = jax.eval_shape(
        lambda p: _carry_to_wire(init_carry_abstract(model, sim, p),
                                 sim), params)
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((s.shape[0] * n,) + s.shape[1:],
                                       s.dtype), shard)


def wire_leaf_kinds(model: Model, sim: SimConfig, params=None):
    """Per-leaf reshard kind for the wire carry, in tree-flatten order
    (aligned with the ``carry/{i}`` arrays a sharded checkpoint
    stores): ``"instance"`` leaves chunk along the global
    (round-robin-interleaved) instance axis, ``"sum"`` leaves are
    additive per-shard partial sums (NetStats slots, the fleet
    telemetry series), ``"key"`` is the replicated master RNG key.
    Recorded into ``state.npz`` at save time so
    ``campaign/checkpoint.reshard_carry`` can re-chunk leaf-wise, and
    statically cross-checked by the shard auditor
    (``analysis/shard_audit.py``)."""
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)
    shard = jax.eval_shape(
        lambda p: _carry_to_wire(init_carry_abstract(model, sim, p),
                                 sim), params)
    kinds = jax.tree.map(lambda _: SHARD_LEAF_INSTANCE, shard)
    kinds = kinds._replace(
        stats=jax.tree.map(lambda _: SHARD_LEAF_SUM, shard.stats),
        key=SHARD_LEAF_KEY)
    if shard.telemetry is not None:
        kinds = kinds._replace(
            telemetry=kinds.telemetry._replace(series=SHARD_LEAF_SUM))
    return list(jax.tree.leaves(kinds))


def init_carry_abstract(model: Model, sim: SimConfig, params):
    """One shard's init carry under eval_shape (seed value irrelevant —
    only shapes/dtypes are consumed)."""
    from ..tpu.runtime import init_carry
    return init_carry(model, sim, 0, params)


def make_sharded_chunk_fn(model: Model, sim: SimConfig, mesh: Mesh,
                          params, scan_k: int = DEFAULT_SCAN_TOP_K):
    """Build the sharded production dispatch step: the jitted,
    wire-donating ``chunk_fn(wire, t0, params, length)`` plus the
    ``wire_spec`` its carry crosses the shard_map boundary under.
    ``sim`` describes the PER-DEVICE shard; ``scan_k`` is the per-shard
    violation scan's top-K width.

    Public because it IS the executable the sharded runner dispatches:
    the IR/cost analyzer (``analysis/ir_lint.py``) lowers and compiles
    this exact callable to verify donation aliasing (JXP403), and the
    shard auditor (``analysis/shard_audit.py``) AOT-lowers it per mesh
    size for the collective census / ICI manifest — not a re-lowered
    copy."""
    from ..tpu.pipeline import violation_scan
    from ..tpu.runtime import init_carry, make_tick_fn

    axes = mesh.axis_names
    dummy_w = jax.eval_shape(
        lambda p: _carry_to_wire(init_carry(model, sim, 0, p), sim),
        params)
    wire_spec = jax.tree.map(lambda _: P(axes), dummy_w)

    @partial(jax.jit, static_argnames=("length",), donate_argnums=0)
    def chunk_fn(wire, t0, params, length):
        def body(w, t0_rep, params_rep):
            ids = _shard_ids(mesh, sim.n_instances)
            carry = _carry_from_wire(w, sim)
            tick = make_tick_fn(model, sim, params_rep,
                                instance_ids=ids)
            carry, ys = jax.lax.scan(
                tick, carry,
                t0_rep.reshape(()) + jnp.arange(length, dtype=jnp.int32))
            events = (ys.events if ys.events is not None
                      else _empty_events(model, sim, length))
            # detached per-shard snapshots ([1, 5] stats / [1, K, 3]
            # scan, shard-leading so they concatenate under P(axes)):
            # the heartbeat reads them after the wire is donated away.
            # The scan rows carry GLOBAL instance ids — no host remap.
            svec = jnp.stack(list(carry.stats)).reshape(1, -1)
            viol_src = carry.violations
            if carry.check_summary is not None:
                from ..checkers import device_summary
                viol_src = viol_src + (
                    carry.check_summary[:, device_summary.L_FLAGS]
                    != 0).astype(jnp.int32)
            scan = violation_scan(
                viol_src, carry.telemetry, ids, k=scan_k)[None]
            return _carry_to_wire(carry, sim), events, svec, scan
        return _shard_map(
            body, mesh=mesh,
            in_specs=(wire_spec, P(), P()),
            out_specs=(wire_spec, P(None, axes), P(axes),
                       P(axes)))(wire, t0, params)

    return chunk_fn, wire_spec


def run_sim_sharded_chunked(model: Model, sim: SimConfig, seed: int,
                            params=None, mesh: Optional[Mesh] = None,
                            chunk: int = 100,
                            return_telemetry: bool = False,
                            perf: Optional[dict] = None,
                            heartbeat=None, fail_fast: bool = False,
                            scan_k: Optional[int] = None,
                            checkpoint_cb=None,
                            checkpoint_every: int = 0,
                            resume=None, check_mode: Optional[str] = None,
                            return_check_summary: bool = False,
                            profiler=None,
                            aot_store: Optional[str] = None):
    """:func:`run_sim_sharded` issued as a sequence of ``chunk``-tick
    device dispatches — the production dispatch pattern (single giant
    dispatches fault the TPU tunnel; see bench.py) — with the carry left
    SHARDED across the mesh between dispatches. Bit-identical to the
    single-scan path by construction (the tick function depends only on
    (carry, t)), which :func:`run_sim_unsharded` then verifies.

    The dispatch loop is the shared chunk executor
    (:func:`..tpu.pipeline.run_chunked`): chunk *k*'s events are
    fetched while chunk *k + 1* runs on device, the wire carry is
    donated between dispatches, and chunk plans prefer a divisor of the
    horizon so every dispatch shares one compile. Pass a dict as
    ``perf`` to receive the driver's dispatch/fetch overlap stats.

    ``heartbeat`` (a :class:`..telemetry.stream.HeartbeatWriter`) gets
    one record per consumed chunk: each shard computes its own detached
    NetStats snapshot + top-K first-violation scan ON DEVICE (fresh
    [1, 5] / [1, K, 3] blocks, so they survive the wire donation) and
    the host merges the ``[n_shards, K, 3]`` scans — violating counts
    summed, rows re-ranked by earliest tick, local instance indices
    remapped to the merged global ids the returned ``violations`` array
    uses (``stream.combine_shard_scans``). ``scan_k`` defaults to
    :data:`..tpu.pipeline.DEFAULT_SCAN_TOP_K`. ``fail_fast`` stops
    dispatching within one chunk of a consumed chunk's scan showing a
    tripped invariant; the events then cover only
    ``perf["ticks-dispatched"]`` ticks.

    Returns the same (psum'd NetStats, violations, events) triple —
    events concatenated on host along the tick axis — plus the merged
    per-instance telemetry when ``return_telemetry`` is set.

    ``checkpoint_cb(wire, ticks, host)``/``checkpoint_every``/``resume``
    are the campaign durability hooks (campaign/checkpoint.py), exactly
    as on :func:`..tpu.pipeline.run_sim_pipelined` — the checkpointed
    state is the WIRE carry (kind ``"sharded"``), ``host`` carries the
    dense per-chunk event blocks under ``"events"`` (already in
    global-id order) plus the per-leaf reshard metadata under
    ``"shard"``. A resumed sharded run accepts a DIFFERENT mesh size:
    :func:`campaign.checkpoint.restore_carry` routes a pure
    shard-count mismatch through ``reshard_carry`` (re-chunking the
    instance axis), and the global-id RNG derivation makes the resumed
    trajectories bit-identical to an uninterrupted run at the new
    shard count.

    ``profiler`` (a :class:`..telemetry.profiler.DeviceProfiler`,
    observational, same contract as on
    :func:`..tpu.pipeline.run_sim_pipelined`): captured chunks
    dispatch under device-time measurement — the measured wall covers
    the whole sharded dispatch including the tick-loop-free stat
    collectives — and their heartbeat records gain the ``device-ms``
    per-phase lane. Trajectories bit-identical on or off.

    ``aot_store`` (a directory, or None): the certified AOT executable
    store (``tpu/aot_store.py``), exactly as on
    :func:`..tpu.pipeline.run_sim_pipelined` — a warm store
    deserializes the sharded chunk executable instead of tracing and
    compiling it; the outcome lands under ``perf["aot"]``.
    Trajectories are bit-identical warm or cold.
    """
    import numpy as np

    from ..tpu.pipeline import resume_plans, run_chunked
    from ..tpu.runtime import init_carry
    from ..telemetry.stream import (combine_shard_scans,
                                    scan_to_violation,
                                    scan_to_violations, stats_vec_to_net)

    mesh = mesh or make_mesh()
    mesh, seed_arr, params = _prepare(model, sim, seed, mesh, params)
    n_shards = int(mesh.size)
    if scan_k is None:
        scan_k = DEFAULT_SCAN_TOP_K

    plans = resume_plans(sim.n_ticks, chunk, resume)

    chunk_fn, wire_spec = make_sharded_chunk_fn(model, sim, mesh,
                                                params, scan_k=scan_k)
    aot_rec = None
    if aot_store is not None:
        from ..tpu.aot_store import wrap_sharded
        wrapped, aot_rec = wrap_sharded(
            chunk_fn, model=model, sim=sim, mesh=mesh, params=params,
            scan_k=scan_k, store_dir=aot_store)
        if wrapped is not None:
            chunk_fn = wrapped

    @jax.jit
    def init_fn(seed_rep, params):
        def body(seed_rep, params_rep):
            ids = _shard_ids(mesh, sim.n_instances)
            return _carry_to_wire(init_carry(
                model, sim, seed_rep.reshape(()), params_rep,
                instance_ids=ids), sim)
        return _shard_map(
            body, mesh=mesh, in_specs=(P(), P()),
            out_specs=wire_spec)(seed_rep, params)

    events_chunks = ([np.asarray(e) for e in resume.events]
                     if resume else [])
    chunk_idx = [resume.chunks if resume else 0]
    tripped = [False]

    # fuzz runs: the heartbeat's fault-fuzz lane (schedules-active per
    # chunk) comes from one host-side re-draw of the whole fleet's
    # windows — schedules are pure functions of (master seed, global
    # instance id), zero mid-run device traffic (faults/fuzz.py)
    fuzz_windows = None
    if heartbeat is not None and sim.faults.has_fuzz:
        from ..faults import fuzz as faults_fuzz
        fuzz_windows = faults_fuzz.fleet_windows(
            sim.faults, sim.net.n_nodes, _seed32(seed),
            np.arange(sim.n_instances * n_shards, dtype=np.int32))

    # profiler state: dispatch-side chunk cursor + the previous
    # dispatch's detached stats block (see run_sim_pipelined — syncing
    # on it keeps a captured chunk's measurement clean while uncaptured
    # chunks keep the fetch/compute overlap)
    dispatch_idx = [resume.chunks if resume else 0]
    sync_ref = [None]

    def dispatch(w, t0, length):
        idx = dispatch_idx[0]
        dispatch_idx[0] += 1
        prof_rec = None
        if profiler is not None and profiler.should_capture(idx):
            (w, events, svec, scan), prof_rec = profiler.capture(
                chunk_fn, (w, jnp.int32(t0), params, length), length,
                sync=sync_ref[0])
        else:
            w, events, svec, scan = chunk_fn(w, jnp.int32(t0), params,
                                             length)
        sync_ref[0] = svec
        return w, (events, svec, scan, prof_rec)

    def consume(payload, t0, length):
        events, svec, scan, prof_rec = payload
        # dense event blocks cross the wire shard-major; accumulate in
        # global-id order so the host history is shard-count-invariant
        # (what lets a resharded resume concatenate with chunks written
        # at a different mesh size)
        events_chunks.append(deinterleave(np.asarray(events), n_shards,
                                          axis=1))
        scan_np = combine_shard_scans(np.asarray(scan), None)
        if int(scan_np[0, 0]) > 0:
            tripped[0] = True
        if heartbeat is not None:
            extra = None
            if fuzz_windows is not None:
                from ..faults import fuzz as faults_fuzz
                extra = {"fault-fuzz": faults_fuzz.span_counters(
                    fuzz_windows, t0, length)}
            if sim.check_summary and check_mode:
                extra = dict(extra or {})
                extra["check"] = {
                    "mode": check_mode,
                    "flagged": int(scan_np[0, 0]),
                    "of": sim.n_instances * n_shards}
            if prof_rec is not None:
                extra = dict(extra or {})
                extra["device-ms"] = prof_rec["per-phase-ms"]
                extra["device-source"] = prof_rec["source"]
            heartbeat.record_chunk(
                chunk=chunk_idx[0], t0=t0, ticks=length,
                net=stats_vec_to_net(np.asarray(svec).sum(axis=0)),
                violation=scan_to_violation(scan_np),
                violations=scan_to_violations(scan_np),
                device_s=(prof_rec["device-s"]
                          if prof_rec is not None else None),
                extra=extra)
        chunk_idx[0] += 1

    should_stop = (lambda: tripped[0]) if fail_fast else None
    checkpoint = None
    if checkpoint_cb is not None and checkpoint_every > 0:
        shard_meta = {
            "n-shards": n_shards,
            "instances-per-shard": int(sim.n_instances),
            "interleaved": True,
            "leaf-kinds": wire_leaf_kinds(model, sim, params)}

        def checkpoint(wire_st, ticks, _chunks):
            checkpoint_cb(wire_st, ticks,
                          {"events": list(events_chunks),
                           "chunks": chunk_idx[0],
                           "shard": shard_meta})
    if resume is not None:
        wire0 = resume.carry
    else:
        wire0 = init_fn(seed_arr, params)
    if plans:
        wire, chunk_stats = run_chunked(
            wire0, plans, dispatch, consume, should_stop,
            checkpoint=checkpoint, checkpoint_every=checkpoint_every)
    else:
        wire = wire0
        chunk_stats = {"chunks": 0, "ticks-dispatched":
                       resume.ticks if resume else 0}
    if perf is not None:
        perf.update(chunk_stats)
        if profiler is not None and profiler.records:
            perf["device"] = profiler.summary()
        if aot_rec is not None:
            from ..tpu.aot_store import finalize_record
            perf["aot"] = finalize_record(aot_rec)

    # final: per-shard stats summed on host (stats crossed the boundary
    # as [n_shards]-length arrays, one slot per shard; int adds commute,
    # so the total is invariant to how a reshard regrouped the slots)
    stats = NetStats(*(int(jnp.sum(x)) for x in wire.stats))
    violations = deinterleave(np.asarray(wire.violations), n_shards,
                              axis=0)
    out = (stats, violations, np.concatenate(events_chunks, axis=0))
    if return_telemetry:
        tel = wire.telemetry
        if tel is not None:
            # wire format: per-instance leaves concatenated shard-major
            # across shards (deinterleave to global-id order); the
            # series buffer crossed as one [n_shards, n_windows, lanes]
            # block — fleet-merge it
            tel = jax.tree.map(np.asarray, tel)
            series = tel.series.sum(axis=0)
            tel = jax.tree.map(
                lambda x: deinterleave(x, n_shards, axis=0), tel)
            tel = tel._replace(series=series)
        out = out + (tel,)
    if return_check_summary:
        cs = wire.check_summary
        if cs is not None:
            # an ordinary instance-sharded wire leaf: shard-major
            # across shards, deinterleave to global-id order like
            # ``violations``
            cs = deinterleave(np.asarray(cs), n_shards, axis=0)
        out = out + (cs,)
    return out


def run_sim_sharded(model: Model, sim: SimConfig, seed: int, params=None,
                    mesh: Optional[Mesh] = None,
                    return_telemetry: bool = False):
    """Run one ``sim``-sized shard per device across the mesh (any
    rank; default the 1-D local-device mesh).

    Returns (fleet-wide NetStats summed over devices, per-instance
    on-device invariant-violation tick counts
    [n_instances * n_devices], events [T, R * n_devices, C, 2,
    2 + model.ev_vals]) — plus, when ``return_telemetry`` is set, the
    merged per-instance flight recorder: instance-axis leaves
    [n_instances * n_devices] like ``violations``, fleet series psum'd
    over the mesh (None when telemetry is disabled). Per-instance axes
    are in GLOBAL instance-id order (host-deinterleaved from the
    shard-major wire).
    """
    mesh = mesh or make_mesh()
    mesh, seed_arr, params = _prepare(model, sim, seed, mesh, params)
    stats, violations, events, tel = _run_sharded(model, sim, mesh,
                                                  seed_arr, params)
    violations, events, tel = _deinterleave_outputs(
        violations, events, tel, int(mesh.size))
    if return_telemetry:
        return stats, violations, events, tel
    return stats, violations, events


def _prepare(model: Model, sim: SimConfig, seed: int, mesh: Mesh, params):
    """Shared preamble of the sharded runners — MUST stay common so the
    chunked path and the single-scan path (the equivalence oracle's
    subject) can never drift in seed derivation or params fallback.
    One replicated master seed; per-shard decorrelation comes from the
    GLOBAL instance ids each shard derives from its mesh position
    (``_shard_ids``), never from per-shard seeds — the shard-count
    invariance cross-mesh resume rests on."""
    # the per-message journal is a single-device feature; shard bodies
    # drop TickOutputs.journal_* — refuse silently-ignored config
    assert sim.journal_instances == 0, \
        "journal_instances is not supported under shard_map"
    seed_arr = jnp.asarray(_seed32(seed), dtype=jnp.int32)
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)   # shard_map needs a pytree
    return mesh, seed_arr, params
