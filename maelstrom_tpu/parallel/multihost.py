"""Multi-host scaling: DCN x ICI hybrid meshes for the instance axis.

Single-host runs shard instances over one 1-D ICI mesh (:mod:`.mesh`).
At pod/multi-host scale the same data parallelism factors over two axes
— hosts over DCN, chips-per-host over ICI — so the collectives that
matter (the psum'd fleet counters) reduce over ICI within a host and
only the tiny reduced scalars cross DCN. Protocol instances never
communicate with each other, so there is no cross-instance traffic at
all; this is the TPU-native analogue of the reference's scale model
(more JVM threads/processes on one box, SURVEY §2.4), lifted to a pod.

The sharded execution itself is :func:`.mesh.run_sim_sharded`, which is
mesh-rank-agnostic — this module only provides process bring-up and the
hybrid mesh constructor::

    from maelstrom_tpu.parallel import mesh, multihost
    multihost.init()                       # jax.distributed from env
    m = multihost.make_hybrid_mesh()       # ("dcn", "ici") axes
    stats, violations, events = mesh.run_sim_sharded(
        model, sim, seed=0, mesh=m)

Degenerate single-host form (1 process) builds a (1, n_devices) mesh —
what the tests exercise on the virtual CPU mesh; the sharding compiles
and runs identically, only the DCN axis size changes.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def init(**kw) -> None:
    """Initialize jax.distributed from the environment (coordinator
    address / process id / process count env vars or explicit kwargs).
    No-op when already initialized or single-process."""
    try:
        jax.distributed.initialize(**kw)
    except (RuntimeError, ValueError):
        pass   # already initialized, or single-process local run


def make_hybrid_mesh() -> Mesh:
    """(n_hosts, chips_per_host) mesh named ("dcn", "ici"). On one
    process this degenerates to (1, n_devices); on a pod each host's
    process-local devices form one ICI row (``process_is_granule`` —
    hosts on a shared slice still granulate by process, so the ICI axis
    never crosses a host boundary)."""
    n_procs = jax.process_count()
    devs = jax.devices()
    per_host = len(devs) // n_procs
    if n_procs > 1:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            (per_host,), (n_procs,), devices=devs,
            process_is_granule=True)
        arr = np.asarray(arr).reshape(n_procs, per_host)
    else:
        arr = np.asarray(devs).reshape(1, per_host)
    return Mesh(arr, (DCN_AXIS, ICI_AXIS))
