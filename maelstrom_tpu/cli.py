"""Command-line interface.

Commands (parity: reference src/maelstrom/core.clj -main :267-284 and
option specs :136-229):

- ``test``   — run one workload test (process or TPU runtime)
- ``demo``   — the built-in self-test matrix over the bundled example nodes
- ``serve``  — browse the store directory over HTTP
- ``doc``    — regenerate doc/workloads.md + doc/protocol.md from schemas
- ``check``  — re-run checkers offline on a stored history
- ``export`` — emit Jepsen-compatible EDN histories for adjudication by
  stock Elle/Knossos outside this image
- ``lint``   — the static-analysis gate: trace-hygiene, abstract-eval
  contract, and schema/wire conformance passes, plus the opt-in
  IR-hazard audit and per-model cost budget (``--ir`` / ``--cost``;
  doc/lint.md)
- ``fleet-stats`` — render a TPU run's device-telemetry report (text +
  SVG dashboards from fleet-metrics.json; doc/observability.md)
- ``watch``  — tail a live (or dead) run's streaming heartbeat.jsonl
  into a terminal report (doc/observability.md live-runs section)
- ``triage`` — replay a run's flagged instances bit-exactly and emit
  per-instance forensics bundles (spacetime SVG + EDN journal + repro)
- ``shrink`` — minimize a fault run's failing scenario into a small
  still-failing deterministic plan: fuzz runs reconstruct each flagged
  instance's randomized schedule from the seed, --fault-plan runs
  delta-debug the (usually over-specified) plan itself
  (faults/shrink.py; doc/guide/10-faults.md)
- ``campaign`` — the durable control plane: ``submit`` a sweep matrix
  as a resumable work queue, ``run`` drains it with periodic carry
  checkpoints, ``status``/``watch --campaign`` follow it live,
  ``resume`` continues killed work bit-exactly, ``report`` writes the
  multi-run trend summary (doc/guide/09-campaigns.md)
"""

from __future__ import annotations

import argparse
import functools
import http.server
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bin_cmd(bin_path: str, args: List[str]):
    """Resolve --bin into (bin, argv): .py files run under this python."""
    if bin_path.endswith(".py"):
        return sys.executable, [bin_path] + args
    return bin_path, args


def _positive_int(value: str) -> int:
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(f"must be a positive int: {value}")
    return n


def _nonnegative_int(value: str) -> int:
    n = int(value)
    if n < 0:
        raise argparse.ArgumentTypeError(
            f"must be a non-negative int: {value}")
    return n


def parse_concurrency(value: str, node_count: int) -> int:
    """'10' -> 10, '4n' -> 4 * node_count (core.clj opt-spec parity)."""
    if value.endswith("n"):
        return int(value[:-1]) * node_count
    return int(value)


def add_test_options(p: argparse.ArgumentParser):
    p.add_argument("-w", "--workload", required=True,
                   help="workload name (echo, broadcast, g-set, "
                        "g-counter, pn-counter, lin-kv, unique-ids, ...)")
    p.add_argument("--bin", help="node binary (process runtime)")
    p.add_argument("--runtime", choices=["process", "tpu", "native"],
                   default="process")
    p.add_argument("--node-count", type=int, default=1)
    p.add_argument("--concurrency", default="1n",
                   help="client count; '4n' means 4 per node")
    p.add_argument("--rate", type=float, default=10.0,
                   help="expected ops/sec across all clients")
    p.add_argument("--time-limit", type=float, default=20.0)
    p.add_argument("--latency", type=float, default=0.0,
                   help="mean inter-node latency in ms")
    p.add_argument("--latency-dist", default="exponential",
                   choices=["constant", "uniform", "exponential"])
    p.add_argument("--nemesis", action="append", default=[],
                   choices=["partition", "crash-restart", "link-degrade",
                            "clock-skew", "membership"],
                   help="fault kinds, composable (repeat the flag). "
                        "'partition' runs everywhere; the fault-plan "
                        "kinds (crash-restart, link-degrade, "
                        "clock-skew, membership — the last drives "
                        "mid-run node remove/rejoin through Raft "
                        "joint consensus) are device-resident "
                        "TPU-runtime lanes generated on the nemesis "
                        "interval grid (maelstrom_tpu/faults/, "
                        "doc/guide/10-faults.md)")
    p.add_argument("--nemesis-interval", type=float, default=10.0)
    p.add_argument("--fault-plan", default=None,
                   help="TPU runtime: JSON fault-plan file (phases of "
                        "crash-restart / link-degradation / clock-skew "
                        "/ membership lanes; doc/guide/10-faults.md). "
                        "Mutually exclusive with the generated fault "
                        "--nemesis kinds; composes with --nemesis "
                        "partition")
    p.add_argument("--fault-fuzz", default=None,
                   help="TPU runtime: JSON fault DISTRIBUTION file — "
                        "per-instance RANDOMIZED crash/link/skew "
                        "schedules drawn on device from the schedule-"
                        "RNG lane, a different scenario per instance "
                        "(maelstrom_tpu/faults/fuzz.py; doc/guide/"
                        "10-faults.md). Flagged instances replay "
                        "bit-exactly from the seed and `maelstrom "
                        "shrink` minimizes them. Mutually exclusive "
                        "with --fault-plan and the generated fault "
                        "--nemesis kinds; composes with --nemesis "
                        "partition")
    p.add_argument("--fault-snapshot-every", type=_positive_int,
                   default=None,
                   help="TPU runtime: ticks between crash-recovery "
                        "snapshot-slab captures (default: the plan's "
                        "own snapshot_every, else 1 = write-through "
                        "durability; larger strides model async "
                        "persistence)")
    p.add_argument("--nemesis-kind", default="random-halves",
                   choices=["random-halves", "isolated-node",
                            "majorities-ring", "scripted"],
                   help="partition grudge shape (TPU runtime; the "
                        "process runtime mixes all kinds randomly)")
    p.add_argument("--nemesis-schedule-file", default=None,
                   help="TPU runtime: JSON file of phases [[until_tick,"
                        " [[node...], ...]], ...] — traffic allowed "
                        "only within each listed group until the "
                        "phase's tick (node ids are 0-based ints; "
                        "implies --nemesis partition --nemesis-kind "
                        "scripted). Phases are force-healed from "
                        "time_limit - recovery_time onward (the final "
                        "heal window)")
    p.add_argument("--recovery-time", type=float, default=None,
                   help="final heal + quiesce window in seconds "
                        "(default: runtime-specific)")
    from .workloads.topology import TOPOLOGIES
    p.add_argument("--topology", default="grid",
                   choices=sorted(TOPOLOGIES))
    p.add_argument("--availability", default=None,
                   help="'total' or a fraction like 0.9")
    p.add_argument("--key-count", type=int, default=None)
    p.add_argument("--max-txn-length", type=int, default=None)
    p.add_argument("--max-writes-per-key", type=int, default=None)
    p.add_argument("--crash-clients", action="store_true",
                   help="kafka: inject client crash ops; crashed "
                        "clients are discarded and reopened, resuming "
                        "from committed offsets")
    p.add_argument("--txn", action="store_true",
                   help="kafka: issue multi-mop send/poll transactions "
                        "(jepsen.tests.kafka :txn? op shape; length "
                        "capped by --max-txn-length)")
    p.add_argument("--consistency-models", default=None,
                   choices=["read-uncommitted", "read-committed",
                            "read-atomic", "snapshot-isolation", "serializable",
                            "strict-serializable"])
    p.add_argument("--log-stderr", action="store_true")
    p.add_argument("--log-net-send", action="store_true")
    p.add_argument("--log-net-recv", action="store_true")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--store", default="store")
    # TPU-runtime knobs
    p.add_argument("--n-instances", type=int, default=64)
    p.add_argument("--record-instances", type=int, default=8)
    p.add_argument("--journal-instances", type=int, default=0,
                   help="TPU runtime: instances with full per-message "
                        "journals (messages.svg + msgs-per-op); costs "
                        "device output bandwidth, so opt-in")
    p.add_argument("--ms-per-tick", type=_positive_int, default=1,
                   help="TPU runtime: virtual-clock resolution "
                        "(fidelity vs throughput trade)")
    p.add_argument("--rpc-timeout", type=float, default=None,
                   help="TPU runtime: client RPC timeout in simulated "
                        "seconds (default 1.0). Fault campaigns want "
                        "it short so clients cycle instead of hanging "
                        "on crashed/unreachable nodes")
    p.add_argument("--p-loss", type=float, default=0.0)
    p.add_argument("--no-telemetry", action="store_true",
                   help="TPU runtime: disable the device flight "
                        "recorder (doc/observability.md); no "
                        "fleet-metrics.json is written")
    p.add_argument("--telemetry-stride", type=int, default=0,
                   help="TPU runtime: ticks per fleet-series window "
                        "(0 = auto, <= 256 windows)")
    p.add_argument("--pipeline", choices=["auto", "on", "off"],
                   default="auto",
                   help="TPU runtime: chunked donated executor "
                        "(tpu/pipeline.py) — auto pipelines any horizon "
                        "spanning multiple chunks; results are "
                        "bit-identical either way")
    p.add_argument("--chunk-ticks", type=_positive_int, default=100,
                   help="TPU runtime: ticks per pipelined device "
                        "dispatch")
    p.add_argument("--event-capacity", type=_nonnegative_int, default=0,
                   help="TPU runtime: compacted event rows per chunk "
                        "(0 = auto from the client rate; overflow is "
                        "flagged in results.perf.phases.pipeline)")
    p.add_argument("--no-heartbeat", action="store_true",
                   help="TPU runtime: do not stream heartbeat.jsonl "
                        "into the store dir during the run "
                        "(doc/observability.md live-runs section)")
    p.add_argument("--fail-fast", action="store_true",
                   help="TPU runtime: stop dispatching chunks once the "
                        "device-side violation scan trips (at most one "
                        "in-flight chunk runs past the detection); "
                        "results gain a top-level \"fail-fast\" block "
                        "and `maelstrom triage` picks up from there. "
                        "Needs the chunked executor (a multi-chunk "
                        "horizon or --pipeline on)")
    p.add_argument("--scan-top-k", type=_positive_int, default=8,
                   help="TPU runtime: violation-scan lanes per chunk — "
                        "the heartbeat names the top-K earliest "
                        "tripping instances per chunk instead of just "
                        "the argmin, and `maelstrom triage` replays "
                        "all of them (default 8)")
    p.add_argument("--checkpoint-every", type=_nonnegative_int,
                   default=0,
                   help="TPU runtime: durable carry checkpoint every K "
                        "chunks (0 = off). A checkpointed run killed "
                        "at any point resumes BIT-EXACTLY via "
                        "`maelstrom campaign resume <run-dir>` "
                        "(doc/guide/09-campaigns.md)")
    p.add_argument("--check-workers", type=_nonnegative_int,
                   default=None,
                   help="TPU runtime: checker-farm worker processes "
                        "for the host verdict pipeline (checkers/"
                        "pool.py) — per-instance histories decode and "
                        "check in parallel, streaming per chunk. 0 "
                        "forces the serial path; default auto uses a "
                        "pool only for >= 16 recorded instances on a "
                        "multi-core host. Verdicts are identical at "
                        "every setting")
    p.add_argument("--check-mode", choices=["farm", "device", "both"],
                   default="farm",
                   help="TPU runtime: host verdict routing. `farm` "
                        "checks every recorded instance (the PR-13 "
                        "pipeline); `device` keeps O(1)-per-instance "
                        "summary lanes in the fused tick (checkers/"
                        "device_summary.py) and routes ONLY flagged "
                        "instances to the farm — O(chips) checking; "
                        "`both` runs the farm on everything AND "
                        "audits that every farm-invalid instance was "
                        "device-flagged (the A/B oracle). Flagged "
                        "verdicts are byte-identical across modes")
    p.add_argument("--compile-cache", default=".jax_cache",
                   help="persistent XLA compile cache dir (default "
                        ".jax_cache; MAELSTROM_COMPILE_CACHE=0 or "
                        "--compile-cache 0 disables) — resumed/queued "
                        "runs skip recompiles; perf.phases records "
                        "hit/miss counts")
    p.add_argument("--aot-store", default="auto",
                   help="certified AOT executable store dir (default "
                        "auto = the compile cache's .aot sibling; "
                        "'off' or MAELSTROM_AOT=0 disables) — a store "
                        "hit dispatches the serialized executable and "
                        "skips trace+compile entirely; "
                        "perf.phases.aot records hit/load-s/"
                        "fingerprint")
    p.add_argument("--profile-dir", default=None,
                   help="TPU runtime: capture a jax.profiler trace of "
                        "the run into this directory")
    p.add_argument("--device-profile", default="auto",
                   choices=["auto", "on", "off"],
                   help="per-chunk device-time attribution (telemetry/"
                        "profiler.py): auto (default) captures the "
                        "first chunks then every Nth, on captures "
                        "every chunk, off disables. Captured chunks "
                        "gain the heartbeat device-ms lane and feed "
                        "results.perf.phases.device + `maelstrom "
                        "profile`; purely observational — "
                        "trajectories are bit-identical either way")


def _availability(v):
    if v is None or v == "total":
        return v
    return float(v)


def _parse_schedule_file(path: str, node_count: int):
    """Load a scripted-nemesis JSON file ([[until_tick, [groups...]],
    ...]) into NemesisConfig.schedule phases. Returns (error_message,
    schedule) — exactly one is truthy."""
    from .tpu.runtime import scripted_isolate_groups
    with open(path) as f:
        phases = json.load(f)
    for until, groups in phases:
        for g in groups:
            for m in g:
                if not isinstance(m, int) or not 0 <= m < node_count:
                    return (f"error: schedule group member {m!r} is "
                            f"not a node index in [0, {node_count})",
                            ())
    return None, tuple(
        scripted_isolate_groups(until, [set(g) for g in groups],
                                node_count)
        for until, groups in phases)


def cmd_test(args) -> int:
    node_count = args.node_count
    concurrency = parse_concurrency(args.concurrency, node_count)
    from .faults import FAULT_KINDS
    fault_kinds = [k for k in args.nemesis if k in FAULT_KINDS]
    if args.runtime != "tpu" and (fault_kinds or args.fault_plan
                                  or args.fault_fuzz):
        print("error: the fault-plan engine (--fault-plan, "
              "--fault-fuzz and the "
              f"{'/'.join(FAULT_KINDS)} nemesis kinds) is "
              "device-resident — --runtime tpu only; the host runtimes "
              "speak --nemesis partition (doc/guide/10-faults.md)",
              file=sys.stderr)
        return 2
    fault_plan = None
    if args.fault_plan:
        if fault_kinds:
            print("error: --fault-plan and the generated fault "
                  "--nemesis kinds are mutually exclusive — put the "
                  "faults in the plan file", file=sys.stderr)
            return 2
        from .faults import SpecError, validate_fault_plan
        try:
            with open(args.fault_plan) as f:
                fault_plan = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: --fault-plan {args.fault_plan}: {e}",
                  file=sys.stderr)
            return 2
        try:
            validate_fault_plan(fault_plan, node_count)
        except SpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    fault_fuzz = None
    if args.fault_fuzz:
        if args.fault_plan or fault_kinds:
            print("error: --fault-fuzz (per-instance randomized "
                  "schedules) is mutually exclusive with --fault-plan "
                  "and the generated fault --nemesis kinds",
                  file=sys.stderr)
            return 2
        from .faults import SpecError, validate_fault_fuzz
        try:
            with open(args.fault_fuzz) as f:
                fault_fuzz = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: --fault-fuzz {args.fault_fuzz}: {e}",
                  file=sys.stderr)
            return 2
        try:
            validate_fault_fuzz(fault_fuzz, node_count)
        except SpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if args.runtime == "process":
        if not args.bin:
            print("error: --bin is required for the process runtime",
                  file=sys.stderr)
            return 2
        from .runner import run_test
        bin_, bin_args = _bin_cmd(args.bin, [])
        proc_extra = ({} if args.recovery_time is None
                      else {"recovery_time": args.recovery_time})
        results = run_test(args.workload, dict(
            **proc_extra,
            bin=bin_, bin_args=bin_args, node_count=node_count,
            concurrency=concurrency, rate=args.rate,
            time_limit=args.time_limit, latency=args.latency,
            latency_dist=args.latency_dist, p_loss=args.p_loss,
            nemesis=args.nemesis, nemesis_interval=args.nemesis_interval,
            topology=args.topology,
            availability=_availability(args.availability),
            key_count=args.key_count,
            max_txn_length=args.max_txn_length,
            max_writes_per_key=args.max_writes_per_key,
            consistency_models=args.consistency_models,
            crash_clients=args.crash_clients,
            txn=args.txn,
            log_stderr=args.log_stderr,
            log_net_send=args.log_net_send,
            log_net_recv=args.log_net_recv, seed=args.seed,
            store_root=args.store))
    elif args.runtime == "native":
        # the C++ scalar engine (cpp/engine): the full workload table
        # on hosts without an accelerator — same checkers, same
        # artifacts
        from .native.engine import NATIVE_WORKLOADS
        if args.workload not in NATIVE_WORKLOADS:
            print("error: --runtime native implements "
                  f"{', '.join(sorted(NATIVE_WORKLOADS))} only; use "
                  "--runtime tpu for the full model set",
                  file=sys.stderr)
            return 2
        if args.nemesis_kind == "scripted" \
                and not args.nemesis_schedule_file:
            print("error: --nemesis-kind scripted needs "
                  "--nemesis-schedule-file", file=sys.stderr)
            return 2
        schedule = ()
        if args.nemesis_schedule_file:
            err, schedule = _parse_schedule_file(
                args.nemesis_schedule_file, node_count)
            if err:
                print(err, file=sys.stderr)
                return 2
            if "partition" not in args.nemesis:
                args.nemesis = list(args.nemesis) + ["partition"]
        notes = [(args.availability, "--availability", None),
                 (args.latency_dist, "--latency-dist", "exponential")]
        if args.workload != "kafka":
            # crash injection is a kafka-client feature everywhere
            notes.append((args.crash_clients or None,
                          "--crash-clients", None))
        if args.workload not in ("txn-list-append", "txn-rw-register"):
            # only the Elle-checked txn workloads are model-selectable;
            # the rest use WGL / set-full / interval / uniqueness
            notes.append((args.consistency_models,
                          "--consistency-models", None))
        for val, name, default in notes:
            if val != default:
                print(f"note: {name} has no effect on the native "
                      f"{args.workload} runtime (exponential latency; "
                      f"lin-kv is WGL-checked)", file=sys.stderr)
        from .native.harness import run_native_test
        results = run_native_test(dict(
            workload=args.workload,
            consistency_models=args.consistency_models,
            topology=args.topology,
            crash_clients=args.crash_clients,
            txn=args.txn,
            node_count=node_count, concurrency=concurrency,
            rate=args.rate, time_limit=args.time_limit,
            latency=args.latency, p_loss=args.p_loss,
            nemesis=args.nemesis,
            nemesis_interval=args.nemesis_interval,
            nemesis_schedule=schedule,
            n_instances=args.n_instances,
            record_instances=args.record_instances,
            check_workers=args.check_workers,
            seed=args.seed if args.seed is not None else 0,
            store_root=args.store,
            **({} if args.recovery_time is None
               else {"recovery_time": args.recovery_time})))
    else:
        from .models import get_model
        from .tpu.harness import run_tpu_test
        for flag, name in ((args.log_stderr, "--log-stderr"),
                           (args.log_net_send, "--log-net-send"),
                           (args.log_net_recv, "--log-net-recv")):
            if flag:
                print(f"note: {name} has no effect on the TPU runtime "
                      f"(no node processes / host wire log)",
                      file=sys.stderr)
        if args.crash_clients and not args.workload.startswith("kafka"):
            # crash injection is a kafka-client feature everywhere
            print("note: --crash-clients has no effect on the TPU "
                  f"{args.workload} runtime (kafka-only)",
                  file=sys.stderr)
        if args.txn:
            # device-side multi-mop kafka transactions are the one
            # native-vocabulary piece still host-only (deferred —
            # PARITY.md); saying so beats silently running single-mop
            print("note: --txn has no effect on the TPU runtime yet "
                  "(kafka transactions are process/native-runtime "
                  "features; use --runtime native)", file=sys.stderr)
        model = get_model(args.workload, node_count, args.topology,
                          opts={"crash_clients": args.crash_clients})
        if args.key_count and hasattr(model, "n_keys"):
            model.n_keys = args.key_count
        schedule = ()
        if args.nemesis_schedule_file:
            err, schedule = _parse_schedule_file(
                args.nemesis_schedule_file, node_count)
            if err:
                print(err, file=sys.stderr)
                return 2
            # a schedule file implies the scripted partition nemesis;
            # silently running healed would be a lie
            if "partition" not in args.nemesis:
                args.nemesis = list(args.nemesis) + ["partition"]
            args.nemesis_kind = "scripted"
        elif args.nemesis_kind == "scripted":
            print("error: --nemesis-kind scripted needs "
                  "--nemesis-schedule-file", file=sys.stderr)
            return 2
        tpu_opts = dict(
            nemesis_schedule=schedule,
            fault_plan=fault_plan,
            fault_fuzz=fault_fuzz,
            fault_snapshot_every=args.fault_snapshot_every,
            crash_clients=args.crash_clients,
            topology=args.topology,
            heartbeat=not args.no_heartbeat,
            fail_fast=args.fail_fast,
            scan_top_k=args.scan_top_k,
            checkpoint_every=args.checkpoint_every,
            compile_cache=args.compile_cache,
            aot_store=args.aot_store,
            check_workers=args.check_workers,
            check_mode=args.check_mode,
            node_count=node_count, concurrency=concurrency,
            rate=args.rate, time_limit=args.time_limit,
            latency=args.latency, latency_dist=args.latency_dist,
            p_loss=args.p_loss, nemesis=args.nemesis,
            nemesis_interval=args.nemesis_interval,
            nemesis_kind=args.nemesis_kind,
            availability=_availability(args.availability),
            consistency_models=args.consistency_models,
            ms_per_tick=args.ms_per_tick,
            n_instances=args.n_instances,
            record_instances=args.record_instances,
            journal_instances=args.journal_instances,
            telemetry=not args.no_telemetry,
            telemetry_stride=args.telemetry_stride,
            pipeline=args.pipeline,
            chunk_ticks=args.chunk_ticks,
            event_capacity=args.event_capacity,
            profile_dir=args.profile_dir,
            device_profile=args.device_profile,
            store_root=args.store,
            seed=args.seed or 0)
        if args.recovery_time is not None:
            tpu_opts["recovery_time"] = args.recovery_time
        if args.rpc_timeout is not None:
            tpu_opts["rpc_timeout"] = args.rpc_timeout
        results = run_tpu_test(model, tpu_opts)
    print(json.dumps(results, indent=2, default=repr))
    print()
    verdict = results.get("valid?")
    if verdict is True:
        print("Everything looks good! ヽ(‘ー`)ノ")
        return 0
    if verdict == "unknown":
        # exit 2 = indeterminate analysis (reference doc/results.md:66-69)
        print("Errors occurred during analysis, but no anomalies found. ಠ~ಠ")
        return 2
    print("Analysis invalid! (ノಥ益ಥ）ノ ┻━┻")
    return 1


DEMOS = [
    # (workload, node [+ args], extra opts[, expect_valid]) —
    # core.clj:104-126's matrix, over the bundled python nodes.
    # expect_valid=False entries are the bug-injection corpus run
    # end-to-end: the demo FAILS if the checker does NOT catch them
    ("echo", "echo.py", {}),
    ("echo", "echo.py", {"node_count": 2}),
    ("broadcast", "broadcast.py", {"node_count": 5, "topology": "grid"}),
    ("broadcast", "broadcast.py",
     {"node_count": 5, "topology": "tree4", "nemesis": ["partition"],
      "nemesis_interval": 2.0, "recovery_time": 2.0}),
    ("g-set", "g_set.py",
     {"node_count": 3, "nemesis": ["partition"], "nemesis_interval": 2.0,
      "recovery_time": 2.0}),
    ("pn-counter", "pn_counter.py", {"node_count": 3,
                                     "recovery_time": 1.0}),
    ("g-counter", "pn_counter.py", {"node_count": 3,
                                    "recovery_time": 1.0}),
    ("g-counter", "counter_seq_kv.py", {"node_count": 3,
                                        "recovery_time": 1.0}),
    ("unique-ids", "unique_ids.py",
     {"node_count": 3, "availability": "total"}),
    ("lin-kv", "lin_kv_proxy.py", {"node_count": 2}),
    ("lin-kv", "raft.py",
     {"node_count": 3, "rate": 20.0, "nemesis": ["partition"],
      "nemesis_interval": 3.0, "recovery_time": 2.0}),
    ("lin-kv", "paxos.py",
     {"node_count": 5, "rate": 10.0, "nemesis": ["partition"],
      "nemesis_interval": 3.0, "recovery_time": 2.0}),
    ("txn-list-append", "txn_single.py", {"node_count": 1, "rate": 20.0}),
    ("txn-list-append", "datomic_txn.py", {"node_count": 3,
                                           "rate": 15.0}),
    ("txn-list-append", "datomic_list_append.py",
     {"node_count": 3, "rate": 15.0}),
    ("txn-rw-register", "txn_single.py", {"node_count": 1,
                                          "rate": 20.0}),
    # HAT (Bailis et al.): totally available under partitions, weak
    # isolation — passes read-uncommitted; serializable rightly fails it
    # (tests/test_e2e_process.py::test_hat_isolation_tradeoff)
    ("txn-rw-register", "txn_rw_hat.py",
     {"node_count": 3, "rate": 15.0, "nemesis": ["partition"],
      "nemesis_interval": 2.0, "recovery_time": 2.0,
      "availability": "total",
      "consistency_models": "read-uncommitted"}),
    ("txn-list-append", "txn_thunks.py", {"node_count": 3,
                                          "rate": 15.0}),
    ("lin-kv", "raft.py",
     {"node_count": 5, "rate": 15.0, "nemesis": ["partition"],
      "nemesis_interval": 3.0, "recovery_time": 2.0}),
    ("kafka", "kafka_single.py", {"node_count": 1, "rate": 20.0}),
    ("kafka", "kafka_single.py",
     {"node_count": 1, "rate": 20.0, "crash_clients": True}),
    ("kafka", "kafka_lin_kv.py", {"node_count": 3, "rate": 15.0}),
    # atomic transactions end-to-end: the single-root transactor passes
    # under multi-mop --txn load; its --no-atomic mutant (durable sends
    # from aborted txns) must be CAUGHT via the aborted-read anomaly
    ("kafka", "kafka_txn.py",
     {"node_count": 3, "rate": 15.0, "txn": True}),
    ("kafka", "kafka_txn.py --no-atomic",
     {"node_count": 3, "rate": 25.0, "txn": True}, False),
    # the native C++ engine's slice of the matrix (runtime "native":
    # no node binary — the engine IS the cluster), including its own
    # must-be-caught mutants
    ("lin-kv", "(native engine)",
     {"runtime": "native", "n_instances": 64, "record_instances": 4,
      "nemesis": ["partition"], "nemesis_interval": 0.4,
      "p_loss": 0.05, "recovery_time": 0.3, "rate": 200.0,
      "time_limit": 2.0, "threads": 1}),
    ("txn-list-append", "(native engine, dirty-apply mutant)",
     {"runtime": "native", "n_instances": 64, "record_instances": 8,
      "nemesis": ["partition"], "nemesis_interval": 0.3,
      "p_loss": 0.05, "recovery_time": 0.3, "rate": 200.0,
      "time_limit": 3.0, "threads": 1, "txn_dirty_apply": True},
     False),
    ("broadcast", "(native engine, tree2 topology)",
     {"runtime": "native", "n_instances": 48, "record_instances": 4,
      "node_count": 5, "topology": "tree2", "nemesis": ["partition"],
      "nemesis_interval": 0.3, "p_loss": 0.05, "recovery_time": 0.4,
      "rate": 200.0, "time_limit": 2.0, "read_prob": 0.1,
      "threads": 1}),
    ("unique-ids", "(native engine, collision mutant)",
     {"runtime": "native", "n_instances": 48, "record_instances": 4,
      "nemesis": ["partition"], "nemesis_interval": 0.3,
      "p_loss": 0.05, "recovery_time": 0.4, "rate": 200.0,
      "time_limit": 2.0, "threads": 1, "gset_no_gossip": True},
     False),
    ("txn-rw-register", "(native engine)",
     {"runtime": "native", "n_instances": 48, "record_instances": 4,
      "nemesis": ["partition"], "nemesis_interval": 0.3,
      "p_loss": 0.05, "recovery_time": 0.3, "rate": 200.0,
      "time_limit": 2.5, "threads": 1}),
    ("kafka", "(native engine, poll-skip mutant)",
     {"runtime": "native", "n_instances": 48, "record_instances": 4,
      "node_count": 1, "nemesis": [], "p_loss": 0.05,
      "recovery_time": 0.3, "rate": 200.0, "time_limit": 2.0,
      "threads": 1, "gset_no_gossip": True}, False),
]


def cmd_demo(args) -> int:
    """Self-test: the full matrix against the bundled example nodes."""
    from .runner import run_test
    failures = []
    skipped = 0
    for entry in DEMOS:
        workload, node, extra = entry[0], entry[1], entry[2]
        expect_valid = entry[3] if len(entry) > 3 else True
        # pick the runner; the verdict bookkeeping below is shared
        if extra.get("runtime") == "native":
            # engine-backed entry: no node binary to spawn
            label = f"{workload} / {node}"
            print(f"== {label}")
            from .native import native_available
            if not native_available():
                print("   skipped (no native engine on this host)")
                skipped += 1
                continue
            from .native.harness import run_native_test
            opts = {k: v for k, v in extra.items() if k != "runtime"}
            opts.update(workload=workload, seed=1,
                        store_root=args.store)
            runner = lambda: run_native_test(opts)
        else:
            node_file, *node_args = node.split()
            bin_, bin_args = _bin_cmd(
                os.path.join(REPO, "examples", "python", node_file),
                node_args)
            opts = dict(bin=bin_, bin_args=bin_args, node_count=1,
                        concurrency=4, rate=10.0,
                        time_limit=args.time_limit,
                        recovery_time=1.0, store_root=args.store,
                        seed=1)
            opts.update(extra)
            if "availability" in opts:
                opts["availability"] = _availability(
                    opts["availability"])
            label = f"{workload} / {node} {extra or ''}"
            print(f"== {label}")
            runner = lambda: run_test(workload, opts)
        try:
            results = runner()
            verdict = results.get("valid?")
        except Exception as e:
            print(f"   crashed: {e!r}")
            verdict = None
        if expect_valid:
            ok = verdict is True
            print("   valid!" if ok else
                  ("   UNKNOWN (indeterminate analysis)"
                   if verdict == "unknown" else "   INVALID"))
        else:
            ok = verdict is False
            print("   caught (mutant flagged invalid)" if ok else
                  "   NOT CAUGHT — mutant passed the checker")
        if not ok:
            failures.append(label)
    print()
    if failures:
        print(f"{len(failures)} demo(s) failed:")
        for f in failures:
            print(f"  - {f}")
        return 1
    if skipped:
        # a skip is not a pass — report it so 'all passed' can't be
        # read on a host that never ran the native slice
        print(f"{len(DEMOS) - skipped} demos passed, {skipped} "
              f"skipped (no native engine). ヽ(‘ー`)ノ")
    else:
        print(f"All {len(DEMOS)} demos passed. ヽ(‘ー`)ノ")
    return 0


def cmd_serve(args) -> int:
    from .serve import ResultsHandler
    os.makedirs(args.store, exist_ok=True)
    handler = functools.partial(ResultsHandler, directory=args.store)
    with http.server.ThreadingHTTPServer(("", args.port), handler) as srv:
        print(f"Serving {args.store}/ on http://localhost:{args.port}")
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
    return 0


def cmd_doc(args) -> int:
    from .doc import write_docs
    for path in write_docs(args.out):
        print(f"wrote {path}")
    return 0


def _resolve_history_paths(path: str, workload_arg, verb: str):
    """Resolve a store run dir (or bare history file) into
    ``(paths, workload_name, tpu_store)``; raises ValueError with a
    user-facing message. Store layout is
    ``store/<workload>[-bug-<mutant>][-tpu]/<ts>/`` — the mutant suffix
    is preserved (callers strip it where they need the base workload)."""
    import glob

    path = os.path.realpath(path)
    tpu_store = False
    if os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, "history*.jsonl")))
        if not paths:
            raise ValueError(f"no history*.jsonl under {path}")
        inferred = os.path.basename(os.path.dirname(path))
        if inferred.endswith("-tpu"):
            inferred, tpu_store = inferred[:-len("-tpu")], True
        elif inferred.endswith("-native"):
            # native-engine stores share the TPU store shape (one
            # history per recorded instance, no node logs)
            inferred, tpu_store = inferred[:-len("-native")], True
    else:
        paths, inferred = [path], None
    workload_name = workload_arg or inferred
    if not workload_name:
        raise ValueError(f"pass -w/--workload when {verb} a bare "
                         f"history file")
    return paths, workload_name, tpu_store


def _load_history_records(p: str):
    """Parse one history.jsonl, tolerating a truncated tail (run killed
    mid-write): using the surviving prefix beats a traceback."""
    records, bad = [], 0
    with open(p) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
    if bad:
        print(f"warning: {p}: skipped {bad} unparseable line(s)",
              file=sys.stderr)
    return records


def cmd_check(args) -> int:
    """Re-run checkers offline on a stored history — the role of
    re-running jepsen's analysis from a store dir (doc/results.md)."""
    from .checkers import check_history, compose_valid
    from .checkers.availability import availability_checker
    from .checkers.perf import stats_checker
    from .runner import DEFAULTS
    from .workloads import get_workload

    try:
        paths, workload_name, tpu_store = _resolve_history_paths(
            args.path, args.workload, "checking")
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # bug-corpus mutants check with their base workload's checker
    workload_name = workload_name.split("-bug-")[0]

    opts = dict(DEFAULTS)
    opts["availability"] = _availability(args.availability)
    if args.consistency_models:
        opts["consistency_models"] = args.consistency_models
    workload = get_workload(workload_name)(opts)
    checker = workload.get("checker")

    histories = [_load_history_records(p) for p in paths]

    if len(histories) == 1 and not tpu_store:
        results = check_history(histories[0], opts, checker,
                                name=f"{workload_name}-checker")
    else:
        # multi-instance (TPU) run: the workload checker runs per
        # instance; stats/availability are fleet-wide over the union —
        # matching the live harness (tpu/harness.py), where a short
        # instance without e.g. a single ok cas is not a failure
        per_history = []
        for h in histories:
            if checker is None:
                per_history.append({"valid?": True})
                continue
            try:
                per_history.append(checker(h, opts))
            except Exception as e:
                from .checkers import checker_failure
                per_history.append(checker_failure(
                    e, checker=f"{workload_name}-checker",
                    instance=len(per_history)))
        union = [r for h in histories for r in h]
        # fleet stats are informational here (the live TPU harness does
        # not gate on them: a recorded instance that never completed an
        # ok cas under a hostile schedule is not a safety failure)
        stats = stats_checker(union)
        stats.pop("valid?", None)
        results = {
            "instances": {os.path.basename(p): r
                          for p, r in zip(paths, per_history)},
            "stats": stats,
            "availability": availability_checker(
                union, opts["availability"]),
        }
        results["valid?"] = compose_valid(
            [r.get("valid?", True) for r in per_history]
            + [results["availability"].get("valid?", True)])
    results["workload-name"] = workload_name
    print(json.dumps(results, indent=2, default=repr))
    verdict = results["valid?"]
    if verdict is True:
        return 0
    return 2 if verdict == "unknown" else 1


def cmd_export(args) -> int:
    """Export a stored history as Jepsen-compatible EDN op maps so a
    disputed verdict can be adjudicated by stock Elle/Knossos outside
    this image (SURVEY §7: "history export in Jepsen-compatible
    EDN/JSON so the existing JVM checkers remain usable")."""
    from .utils.edn import (history_to_edn_lines,
                            history_to_edn_vector_lines)

    # Jepsen's history.edn is one EDN vector; that's the default shape.
    # --maps emits bare line-delimited op maps for line-oriented tooling.
    to_lines = (history_to_edn_lines if getattr(args, "maps", False)
                else history_to_edn_vector_lines)
    try:
        paths, workload, _ = _resolve_history_paths(
            args.path, args.workload, "exporting")
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.out and args.out.endswith(".edn") and len(paths) > 1:
        print(f"error: -o {args.out} names one file but the run has "
              f"{len(paths)} history shards; pass a directory",
              file=sys.stderr)
        return 2
    if args.out == "-" and len(paths) > 1 and \
            not getattr(args, "maps", False):
        # concatenated vectors are not one readable EDN form — a stock
        # read-string would silently see only the first shard
        print(f"error: the run has {len(paths)} history shards, which "
              f"cannot share stdout as EDN vectors; pass a directory "
              f"(one vector per file) or --maps", file=sys.stderr)
        return 2

    for p in paths:
        records = _load_history_records(p)
        if args.out == "-":
            for line in to_lines(records, workload):
                print(line)
        else:
            base = os.path.basename(p).replace(".jsonl", ".edn")
            dest = (args.out if args.out and args.out.endswith(".edn")
                    else os.path.join(args.out or os.path.dirname(p),
                                      base))
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            with open(dest, "w") as f:
                for line in to_lines(records, workload):
                    f.write(line + "\n")
            print(f"wrote {dest} ({len(records)} ops)", file=sys.stderr)
    return 0


def cmd_fleet_stats(args) -> int:
    """Render the fleet telemetry report of a TPU-runtime store run:
    text summary on stdout plus the rate/drop/latency SVG dashboards
    (re-rendered offline from fleet-metrics.json, so a run dir copied
    off the machine keeps its dashboards reproducible)."""
    from .telemetry.fleet import (FLEET_METRICS_FILE, load_fleet_metrics,
                                  render_report, write_fleet_svgs)

    path = os.path.realpath(args.path)
    try:
        metrics = load_fleet_metrics(path)
    except OSError as e:
        print(f"error: no {FLEET_METRICS_FILE} at {args.path} ({e}); "
              f"fleet metrics are written by TPU-runtime runs unless "
              f"--no-telemetry was passed", file=sys.stderr)
        return 2
    except json.JSONDecodeError as e:
        print(f"error: unparseable fleet metrics at {args.path}: {e}",
              file=sys.stderr)
        return 2
    run_dir = path if os.path.isdir(path) else os.path.dirname(path)
    phases = None
    try:
        with open(os.path.join(run_dir, "results.json")) as f:
            phases = json.load(f).get("perf", {}).get("phases")
    except (OSError, json.JSONDecodeError):
        pass
    print(render_report(metrics, phases=phases))
    if not args.no_svg:
        out_dir = args.out or run_dir
        os.makedirs(out_dir, exist_ok=True)
        for p in write_fleet_svgs(metrics, out_dir):
            print(f"wrote {p}", file=sys.stderr)
    return 0


def cmd_profile(args) -> int:
    """Render a stored run's per-phase device-time table — the
    heartbeat's ``device-ms`` chunk lanes plus the results.json
    ``perf.phases.device`` roll-up — and name the hot scope
    (telemetry/profiler.py). Exit 2 when the run carries no device
    time (profiling off, or a pre-profiler run dir)."""
    from .telemetry.profiler import render_profile_report

    path = os.path.realpath(args.path)
    report = render_profile_report(path)
    if report is None:
        print(f"error: no device-time records at {args.path} — "
              f"device time is captured by chunked TPU-runtime runs "
              f"unless --device-profile off was passed (old run dirs "
              f"predate the lane)", file=sys.stderr)
        return 2
    print(report)
    return 0


def _watch_campaign(args) -> int:
    """``watch --campaign``: tail EVERY item of a campaign dir — the
    merged live table re-rendered each poll until the queue settles
    (all items done/failed and no heartbeat still moving)."""
    import time as _time

    from .campaign.queue import DONE, FAILED, QueueError
    from .campaign.report import campaign_status, render_status

    try:
        status = campaign_status(args.path)
    except QueueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not args.follow:
        print(render_status(status))
        settled = all(r["status"] in (DONE, FAILED)
                      for r in status["items"])
        return 0 if settled else 3
    try:
        while True:
            status = campaign_status(args.path)
            print(render_status(status), flush=True)
            if all(r["status"] in (DONE, FAILED)
                   for r in status["items"]):
                return 0
            _time.sleep(args.interval)
            print()
    except KeyboardInterrupt:
        print()
        return 130


def cmd_watch(args) -> int:
    """Tail a run's streaming heartbeat into a terminal report — the
    live view of a fleet that used to be a black box until the final
    fetch (doc/observability.md). One-shot by default; --follow keeps
    tailing (new chunk records print as they land) until the run-end
    record arrives or Ctrl-C. ``--campaign`` tails a whole campaign
    dir's items instead of one run."""
    import time as _time

    from .telemetry.stream import (heartbeat_path, read_heartbeat,
                                   render_chunk_line,
                                   render_watch_report)

    if args.campaign:
        return _watch_campaign(args)
    path = heartbeat_path(os.path.realpath(args.path))
    if not os.path.exists(path):
        print(f"error: no heartbeat at {args.path} (heartbeat.jsonl is "
              f"streamed by TPU-runtime runs with a --store dir unless "
              f"--no-heartbeat was passed)", file=sys.stderr)
        return 2
    hb = read_heartbeat(path)

    def age():
        try:
            return _time.time() - os.path.getmtime(path)
        except OSError:
            return None

    if not args.follow:
        print(render_watch_report(hb, path=args.path, mtime_age_s=age()))
        return 0 if hb["end"] is not None else 3

    # follow: print the header + chunks seen so far, then poll for new
    # records (the reader re-parses the file — records are tiny)
    h = hb.get("header") or {}
    print(f"run: {h.get('workload', '?')} — {h.get('instances', '?')} "
          f"instances x {h.get('ticks', '?')} ticks, chunk "
          f"{h.get('chunk-ticks', '?')}  [{args.path}]")
    printed = 0
    try:
        while True:
            hb = read_heartbeat(path)
            for rec in hb["chunks"][printed:]:
                # flush per line: a piped follow (CI smoke, tee) must
                # see records as they land, not at block-buffer size
                print(render_chunk_line(rec), flush=True)
            printed = len(hb["chunks"])
            if hb["end"] is not None:
                end = hb["end"]
                print(f"status: {end.get('status', 'complete')} — "
                      f"{end.get('ticks', '?')} ticks in "
                      f"{end.get('wall-s', '?')}s"
                      + (f", valid? {end['valid?']}"
                         if "valid?" in end else ""))
                v = end.get("first-violation")
                if v:
                    print(f"first violation: instance "
                          f"{v.get('instance')} at tick "
                          f"{v.get('tick')}")
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        print()
        return 130


def cmd_triage(args) -> int:
    """Replay the flagged instances of a stored run and emit their
    forensics bundles (checkers/triage.py). Works on complete runs
    (flagged set from results.json) and on partial/fail-fast/killed
    runs (flagged set from the heartbeat's device-side violation
    scan)."""
    from .checkers.triage import (TriageError, render_triage_report,
                                  triage_run)

    try:
        summary = triage_run(
            os.path.realpath(args.path),
            ids=args.instance or None,
            max_instances=args.max_instances,
            out_root=args.out,
            max_svg_events=args.max_svg_events)
    except TriageError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(render_triage_report(summary))
    return 0


def cmd_shrink(args) -> int:
    """Minimize a fault run's failing scenario (faults/shrink.py):
    for a fuzz run, reconstruct each flagged instance's randomized
    schedule from the seed; for a --fault-plan run, start from the
    plan itself. Replay bit-exactly through the pipelined executor,
    delta-debug (ddmin complement-halving + greedy passes) to a
    minimal still-failing nemesis, and write
    triage/instance-<id>/shrunk-plan.json."""
    from .faults.shrink import (ShrinkError, render_shrink_report,
                                shrink_run)

    try:
        summary = shrink_run(
            os.path.realpath(args.path),
            ids=args.instance or None,
            max_instances=args.max_instances,
            max_attempts=args.max_attempts)
    except ShrinkError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(render_shrink_report(summary))
    if summary.get("errors"):
        return 1
    if not summary.get("shrunk") and not summary.get("note"):
        return 1
    return 0


def cmd_campaign(args) -> int:
    """The durable campaign control plane (doc/guide/09-campaigns.md):
    submit a sweep matrix as a work queue, drain it from any number of
    workers, watch it live, resume killed work from checkpoints, and
    aggregate the trend summary the serve browser renders."""
    from .campaign.checkpoint import CheckpointError
    from .campaign.queue import (QueueError, load_campaign,
                                 requeue_stale, submit_campaign)
    from .campaign.report import (campaign_report, campaign_status,
                                  render_report, render_status)
    from .campaign.runner import resume_run, run_campaign
    from .campaign.spec import SpecError, load_spec

    try:
        if args.action == "submit":
            spec = load_spec(args.path)
            cdir = submit_campaign(spec, args.store)
            meta = load_campaign(cdir)
            print(f"submitted campaign {meta['name']!r}: "
                  f"{meta['n-items']} item(s)")
            print(cdir)
            return 0
        if args.action == "run":
            from .utils.compile_cache import enable_compile_cache
            enable_compile_cache(args.compile_cache or ".jax_cache")
            requeued = requeue_stale(args.path)
            if requeued:
                print(f"requeued {len(requeued)} preempted item(s): "
                      f"{requeued}")
            # only EXPLICIT flags override per-item spec opts (both
            # flags default to None so 'not given' is distinguishable)
            overrides = {}
            if args.checkpoint_every is not None:
                overrides["checkpoint_every"] = args.checkpoint_every
            if args.compile_cache is not None:
                overrides["compile_cache"] = args.compile_cache
            if args.aot_store is not None:
                overrides["aot_store"] = args.aot_store
            summary = run_campaign(
                args.path, max_items=args.max_items,
                overrides=overrides, triage_invalid=args.triage)
            print(f"\nran {summary['ran']} item(s): "
                  f"{summary['done']} done "
                  f"({summary['invalid']} invalid), "
                  f"{summary['failed']} failed")
            return 1 if (summary["failed"] or summary["invalid"]) else 0
        if args.action == "status":
            print(render_status(campaign_status(args.path)))
            return 0
        if args.action == "resume":
            if os.path.exists(os.path.join(args.path, "campaign.json")):
                # campaign dir: requeue dead work, then drain it
                requeued = requeue_stale(args.path, force=args.force)
                print(f"requeued {len(requeued)} preempted item(s)"
                      + (f": {requeued}" if requeued else ""))
                summary = run_campaign(args.path,
                                       max_items=args.max_items,
                                       triage_invalid=args.triage)
                print(f"\nran {summary['ran']} item(s): "
                      f"{summary['done']} done "
                      f"({summary['invalid']} invalid), "
                      f"{summary['failed']} failed")
                return 1 if (summary["failed"] or summary["invalid"]) \
                    else 0
            # single run dir: finish it in place
            results = resume_run(os.path.realpath(args.path))
            print(json.dumps(results, indent=2, default=repr))
            verdict = results.get("valid?")
            return 0 if verdict is True else (
                2 if verdict == "unknown" else 1)
        if args.action == "report":
            summary = campaign_report(
                args.path, static_cost=not args.no_static_cost)
            print(render_report(summary))
            print(f"\nwrote {os.path.join(args.path, 'summary.json')}")
            return 0
    except (SpecError, QueueError, CheckpointError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    raise AssertionError(f"unhandled campaign action {args.action!r}")


def cmd_lint(args) -> int:
    """Run the analysis passes; --strict turns error findings into a
    nonzero exit (the pre-merge gate, tools/lint_gate.sh)."""
    from .analysis import render_text, run_lint
    from .analysis.findings import DEFAULT_BASELINE

    # None = runner default (all default passes; trace-only when paths
    # restrict). --ir / --cost are additive shorthands for the opt-in
    # IR-hazard and cost-budget passes (--update-baseline implies
    # --cost: re-recording IS a cost-pass run).
    passes = list(args.passes) if args.passes else []
    if args.ir:
        passes.append("ir")
    if args.cost or args.update_baseline:
        passes.append("cost")
    if args.lanes or args.update_manifest:
        passes.append("lanes")
    if args.ranges or args.update_ranges:
        passes.append("ranges")
    if args.shard or args.update_shard_manifest:
        passes.append("shard")
    if args.aot or args.update_aot:
        passes.append("aot")
    baseline = None if args.no_baseline else (args.baseline
                                              or DEFAULT_BASELINE)
    report = run_lint(repo_root=args.root,
                      passes=tuple(dict.fromkeys(passes)) or None,
                      paths=args.paths or None,
                      baseline_path=baseline,
                      cost_baseline_path=args.cost_baseline,
                      update_cost_baseline=args.update_baseline,
                      lane_manifest_path=args.lane_manifest,
                      update_lane_manifest=args.update_manifest,
                      range_manifest_path=args.range_manifest,
                      update_range_manifest=args.update_ranges,
                      ranges_horizon_log2=args.ranges_horizon_log2,
                      shard_manifest_path=args.shard_manifest,
                      update_shard_manifest=args.update_shard_manifest,
                      aot_manifest_path=args.aot_manifest,
                      update_aot_manifest=args.update_aot,
                      aot_store_path=args.aot_store)
    if args.json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        print(render_text(report))
    if args.strict and report.errors():
        return 1
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="maelstrom_tpu",
        description="A TPU-native workbench for learning and testing "
                    "distributed systems.")
    sub = parser.add_subparsers(dest="command", required=True)

    p_test = sub.add_parser("test", help="run one workload test")
    add_test_options(p_test)

    p_demo = sub.add_parser("demo", help="run the self-test demo matrix")
    p_demo.add_argument("--time-limit", type=float, default=5.0)
    p_demo.add_argument("--store", default="store")

    p_serve = sub.add_parser("serve", help="browse the store over HTTP")
    p_serve.add_argument("--port", type=int, default=8080)
    p_serve.add_argument("--store", default="store")

    p_doc = sub.add_parser("doc", help="regenerate schema-driven docs")
    p_doc.add_argument("--out", default="doc")

    p_check = sub.add_parser(
        "check", help="re-run checkers offline on a stored history")
    p_check.add_argument("path",
                         help="a store run dir (e.g. store/lin-kv/latest)"
                              " or a history.jsonl file")
    p_check.add_argument("-w", "--workload", default=None,
                         help="workload name (inferred from a store dir"
                              " path)")
    p_check.add_argument("--availability", default=None)
    p_check.add_argument("--consistency-models", default=None,
                         choices=["read-uncommitted", "read-committed",
                                  "read-atomic", "snapshot-isolation", "serializable",
                                  "strict-serializable"])

    p_export = sub.add_parser(
        "export", help="export a stored history as Jepsen-compatible "
                       "EDN for adjudication by stock Elle/Knossos")
    p_export.add_argument("path",
                          help="a store run dir (e.g. "
                               "store/txn-list-append/latest) or a "
                               "history.jsonl file")
    p_export.add_argument("-w", "--workload", default=None,
                          help="workload name (inferred from a store "
                               "dir path)")
    p_export.add_argument("-o", "--out", default=None,
                          help="output .edn file, directory, or '-' "
                               "for stdout (default: next to the input)")
    p_export.add_argument("--maps", action="store_true",
                          help="emit line-delimited op maps instead of "
                               "the default single EDN vector "
                               "(history.edn shape)")

    p_fleet = sub.add_parser(
        "fleet-stats", help="render the fleet telemetry report of a "
                            "TPU-runtime store run (doc/observability"
                            ".md)")
    p_fleet.add_argument("path",
                         help="a store run dir (e.g. "
                              "store/echo-tpu/latest) or a "
                              "fleet-metrics.json file")
    p_fleet.add_argument("-o", "--out", default=None,
                         help="directory for the SVG dashboards "
                              "(default: the run dir)")
    p_fleet.add_argument("--no-svg", action="store_true",
                         help="text report only")

    p_profile = sub.add_parser(
        "profile", help="render a stored run's per-phase device-time "
                        "table and name the hot scope "
                        "(doc/observability.md)")
    p_profile.add_argument("path",
                           help="a store run dir (e.g. store/echo-tpu/"
                                "latest) with heartbeat device-ms "
                                "lanes and/or a results.json "
                                "perf.phases.device roll-up")

    p_watch = sub.add_parser(
        "watch", help="tail a run's streaming heartbeat.jsonl into a "
                      "live terminal report (doc/observability.md)")
    p_watch.add_argument("path",
                         help="a store run dir (e.g. store/lin-kv-tpu/"
                              "latest) or a heartbeat.jsonl file")
    p_watch.add_argument("-f", "--follow", action="store_true",
                         help="keep tailing until the run-end record "
                              "(or Ctrl-C); default is one shot")
    p_watch.add_argument("--interval", type=float, default=1.0,
                         help="--follow poll interval in seconds")
    p_watch.add_argument("--campaign", action="store_true",
                         help="PATH is a campaign dir: tail ALL items' "
                              "heartbeats as one merged live table "
                              "(terminates when every item is "
                              "done/failed)")

    p_triage = sub.add_parser(
        "triage", help="replay a run's flagged instances and emit "
                       "per-instance forensics bundles (spacetime SVG "
                       "+ EDN journal + repro.json)")
    p_triage.add_argument("path",
                          help="a store run dir (complete, fail-fast-"
                               "stopped, or killed mid-run)")
    p_triage.add_argument("--instance", type=int, action="append",
                          default=[],
                          help="triage this instance id (repeatable; "
                               "default: the run's flagged instances)")
    p_triage.add_argument("--max-instances", type=_positive_int,
                          default=8,
                          help="cap on instances to replay (default 8)")
    p_triage.add_argument("-o", "--out", default=None,
                          help="output directory (default: "
                               "<run-dir>/triage)")
    p_triage.add_argument("--max-svg-events", type=_positive_int,
                          default=1500,
                          help="Lamport SVG event cap; beyond it the "
                               "diagram is annotated '+N elided'")

    p_shrink = sub.add_parser(
        "shrink", help="minimize a fault run's failing scenario: "
                       "rebuild each flagged instance's randomized "
                       "schedule from the seed (fuzz runs) or start "
                       "from the deterministic plan itself "
                       "(--fault-plan runs), then delta-debug to a "
                       "minimal still-failing plan "
                       "(triage/instance-<id>/shrunk-plan.json)")
    p_shrink.add_argument("path",
                          help="a store run dir of a --fault-fuzz or "
                               "--fault-plan run with flagged "
                               "instances")
    p_shrink.add_argument("--instance", type=int, action="append",
                          default=[],
                          help="shrink this instance id (repeatable; "
                               "default: the run's flagged instances)")
    p_shrink.add_argument("--max-instances", type=_positive_int,
                          default=4,
                          help="cap on instances to shrink (default 4)")
    p_shrink.add_argument("--max-attempts", type=_positive_int,
                          default=24,
                          help="replay budget per instance — each "
                               "candidate reduction recompiles the "
                               "single-instance tick (default 24)")

    p_camp = sub.add_parser(
        "campaign", help="durable sweep campaigns: submit a work-queue "
                         "matrix, drain/resume it across process "
                         "deaths, aggregate trend reports "
                         "(doc/guide/09-campaigns.md)")
    camp_sub = p_camp.add_subparsers(dest="action", required=True)
    c_submit = camp_sub.add_parser(
        "submit", help="expand a campaign spec (JSON; TOML on py3.11+) "
                       "into a queued campaign dir")
    c_submit.add_argument("path", help="campaign spec file")
    c_submit.add_argument("--store", default="store")
    c_run = camp_sub.add_parser(
        "run", help="drain the queue: claim items, run them through "
                    "the pipelined executor with periodic carry "
                    "checkpoints; exit 1 if any item failed or was "
                    "invalid")
    c_run.add_argument("path", help="campaign dir (from submit)")
    c_run.add_argument("--max-items", type=_positive_int, default=None)
    c_run.add_argument("--checkpoint-every", type=_nonnegative_int,
                       default=None,
                       help="chunks between carry checkpoints "
                            "(default 4; 0 disables)")
    c_run.add_argument("--compile-cache", default=None,
                       help="persistent XLA compile cache dir "
                            "(default .jax_cache; an explicit flag "
                            "also overrides per-item spec settings; "
                            "MAELSTROM_COMPILE_CACHE=0 disables)")
    c_run.add_argument("--aot-store", default=None,
                       help="certified AOT executable store dir "
                            "(default auto = the compile cache's .aot "
                            "sibling; an explicit flag also overrides "
                            "per-item spec settings; 'off' or "
                            "MAELSTROM_AOT=0 disables)")
    c_run.add_argument("--triage", action="store_true",
                       help="auto-run `maelstrom triage` on each "
                            "invalid item's run dir")
    c_status = camp_sub.add_parser(
        "status", help="merge every item's heartbeat into one live "
                       "table")
    c_status.add_argument("path", help="campaign dir")
    c_resume = camp_sub.add_parser(
        "resume", help="campaign dir: requeue dead workers' items and "
                       "drain (each resumes from its checkpoint); run "
                       "dir: finish that one run in place, bit-"
                       "identical to an uninterrupted execution")
    c_resume.add_argument("path", help="campaign dir or run dir")
    c_resume.add_argument("--max-items", type=_positive_int,
                          default=None)
    c_resume.add_argument("--force", action="store_true",
                          help="also requeue running items with no/"
                               "foreign locks (lost remote worker)")
    c_resume.add_argument("--triage", action="store_true",
                          help="auto-triage invalid items")
    c_report = camp_sub.add_parser(
        "report", help="aggregate completed items into "
                       "<campaign>/summary.json trend rows (rendered "
                       "by `maelstrom serve`)")
    c_report.add_argument("path", help="campaign dir")
    c_report.add_argument("--no-static-cost", action="store_true",
                          help="skip the per-config ir_bytes_est "
                               "column (one abstract trace per "
                               "distinct model config)")

    p_lint = sub.add_parser(
        "lint", help="static analysis: trace-hygiene, contract, and "
                     "schema/wire conformance passes, plus the opt-in "
                     "IR hazard audit (--ir) and per-model cost budget "
                     "(--cost) (doc/lint.md)")
    p_lint.add_argument("paths", nargs="*",
                        help="restrict the trace-hygiene pass to these "
                             "files (other passes then run only when "
                             "named explicitly with --pass)")
    p_lint.add_argument("--strict", action="store_true",
                        help="exit 1 on any unsuppressed error-severity "
                             "finding")
    p_lint.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    p_lint.add_argument("--pass", dest="passes", action="append",
                        choices=["trace", "contract", "schema", "ir",
                                 "cost", "lanes", "ranges", "shard",
                                 "aot"],
                        help="run only the named pass(es); default "
                             "trace+contract+schema (ir/cost are "
                             "opt-in — they trace/compile every "
                             "registered model)")
    p_lint.add_argument("--ir", action="store_true",
                        help="run the IR hazard pass (JXP4xx): audit "
                             "the lowered tick jaxpr of every "
                             "registered model x both carry layouts "
                             "and verify donation aliasing on the "
                             "compiled pipeline/mesh executors "
                             "(doc/lint.md)")
    p_lint.add_argument("--cost", action="store_true",
                        help="run the cost-budget gate (COST5xx): "
                             "static tick cost of every registered "
                             "model x both layouts vs "
                             "analysis/cost_baseline.json; >10% "
                             "regression is an error")
    p_lint.add_argument("--update-baseline", action="store_true",
                        help="re-record analysis/cost_baseline.json "
                             "from the current tree (implies --cost); "
                             "commit the result with the PR that "
                             "justifies the new cost")
    p_lint.add_argument("--cost-baseline", default=None,
                        help="cost-baseline file (default "
                             "maelstrom_tpu/analysis/cost_baseline"
                             ".json)")
    p_lint.add_argument("--lanes", action="store_true",
                        help="run the lane-liveness pass (LNE6xx): "
                             "backward dataflow slice of every "
                             "registered model x both carry layouts — "
                             "live message-lane sets, dead carry "
                             "leaves, dead stores — gated against "
                             "analysis/lane_manifest.json "
                             "(doc/lint.md)")
    p_lint.add_argument("--update-manifest", action="store_true",
                        help="re-record analysis/lane_manifest.json "
                             "from the current tree (implies --lanes); "
                             "commit the result with the PR that "
                             "changes the lane vocabulary")
    p_lint.add_argument("--lane-manifest", default=None,
                        help="lane-manifest file (default "
                             "maelstrom_tpu/analysis/lane_manifest"
                             ".json)")
    p_lint.add_argument("--ranges", action="store_true",
                        help="run the value-range pass (ABS7xx): "
                             "interval abstract interpretation of "
                             "every registered model x both carry "
                             "layouts — int32 overflow proofs to the "
                             "tick horizon, scatter write-write race "
                             "detection, provable OOB indices — gated "
                             "against analysis/range_manifest.json "
                             "(doc/lint.md)")
    p_lint.add_argument("--update-ranges", action="store_true",
                        help="re-record analysis/range_manifest.json "
                             "from the current tree (implies "
                             "--ranges); commit the result with the PR "
                             "that changes the proven ranges")
    p_lint.add_argument("--range-manifest", default=None,
                        help="range-manifest file (default "
                             "maelstrom_tpu/analysis/range_manifest"
                             ".json)")
    p_lint.add_argument("--ranges-horizon-log2", type=int, default=None,
                        help="override the largest probed horizon "
                             "(log2; default 24) — the lint_gate "
                             "canary probes 31 so every cumulative "
                             "counter trips ABS701")
    p_lint.add_argument("--shard", action="store_true",
                        help="run the SPMD partition pass (SHD8xx): "
                             "AOT-lower the sharded chunk step of "
                             "every registered model x both carry "
                             "layouts under an abstract mesh — "
                             "collective census, ICI-bytes estimates "
                             "per mesh size {1,2,4,8}, cross-shard "
                             "dependence / replicated-leaf / "
                             "lost-donation audits, and static "
                             "cross-mesh reshard proofs — gated "
                             "against analysis/shard_manifest.json "
                             "(doc/lint.md)")
    p_lint.add_argument("--update-shard-manifest", action="store_true",
                        help="re-record analysis/shard_manifest.json "
                             "from the current tree (implies "
                             "--shard); commit the result with the PR "
                             "that changes the sharded communication "
                             "pattern")
    p_lint.add_argument("--shard-manifest", default=None,
                        help="shard-manifest file (default "
                             "maelstrom_tpu/analysis/shard_manifest"
                             ".json)")
    p_lint.add_argument("--aot", action="store_true",
                        help="run the certified-executable pass "
                             "(EXE9xx): re-derive the canonical jaxpr "
                             "digest of the production chunk "
                             "dispatches from current source, gate it "
                             "against analysis/aot_manifest.json, and "
                             "audit every entry of the AOT executable "
                             "store — payload integrity, fingerprint "
                             "drift, deserialized donation aliasing, "
                             "collective census, toolchain match "
                             "(doc/lint.md)")
    p_lint.add_argument("--update-aot", action="store_true",
                        help="re-record analysis/aot_manifest.json "
                             "from the current tree (implies --aot); "
                             "with an explicit --aot-store DIR also "
                             "compiles the audit subjects and "
                             "populates that store; commit the "
                             "manifest with the PR that changes the "
                             "dispatch")
    p_lint.add_argument("--aot-manifest", default=None,
                        help="AOT-manifest file (default "
                             "maelstrom_tpu/analysis/aot_manifest"
                             ".json)")
    p_lint.add_argument("--aot-store", default=None,
                        help="AOT executable store to audit/populate "
                             "(default: the compile cache's .aot "
                             "sibling; 'off' skips the store audit)")
    p_lint.add_argument("--baseline", default=None,
                        help="baseline file (default "
                             "maelstrom_tpu/analysis/baseline.json)")
    p_lint.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report and gate on "
                             "every finding, including expected fixtures")
    p_lint.add_argument("--root", default=REPO,
                        help="repo root to lint (default: this checkout)")

    args = parser.parse_args(argv)
    try:
        return {"test": cmd_test, "demo": cmd_demo, "serve": cmd_serve,
                "doc": cmd_doc, "check": cmd_check,
                "export": cmd_export, "lint": cmd_lint,
                "fleet-stats": cmd_fleet_stats, "watch": cmd_watch,
                "profile": cmd_profile,
                "triage": cmd_triage, "shrink": cmd_shrink,
                "campaign": cmd_campaign}[args.command](args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
