"""TPU-runtime model registry: workload name -> vectorized model.

The device-side counterpart of workloads/__init__.py's registry; the CLI's
``--runtime tpu`` resolves through here.
"""

from __future__ import annotations


def get_model(workload: str, node_count: int, topology: str = "grid"):
    from .crdt import (BroadcastModel, GCounterModel, GossipSetModel,
                       PNCounterModel)
    from .echo import EchoModel
    from .kafka import KafkaModel, KAFKA_BUGGY_MODELS
    from .raft import RaftModel
    from .raft_buggy import BUGGY_MODELS
    from .txn_raft import (TXN_BUGGY_MODELS, TxnListAppendModel,
                           TxnRwRegisterModel)
    from .unique_ids import UniqueIdsModel

    if workload == "echo":
        return EchoModel()
    if workload == "unique-ids":
        return UniqueIdsModel()
    if workload == "broadcast":
        return BroadcastModel(topology)
    if workload == "g-set":
        return GossipSetModel(topology)
    if workload == "pn-counter":
        return PNCounterModel(n_nodes_hint=node_count, topology="total")
    if workload == "g-counter":
        return GCounterModel(n_nodes_hint=node_count, topology="total")
    if workload == "lin-kv":
        return RaftModel(n_nodes_hint=node_count)
    if workload.startswith("lin-kv-bug-"):
        kind = workload[len("lin-kv-bug-"):]
        if kind in BUGGY_MODELS:
            return BUGGY_MODELS[kind](n_nodes_hint=node_count)
    if workload == "txn-list-append":
        return TxnListAppendModel(n_nodes_hint=node_count)
    if workload == "txn-rw-register":
        return TxnRwRegisterModel(n_nodes_hint=node_count)
    for prefix in ("txn-list-append-bug-", "txn-rw-register-bug-"):
        if workload.startswith(prefix):
            kind = workload[len(prefix):]
            if prefix.startswith("txn-rw-register"):
                kind = "rw-" + kind
            if kind in TXN_BUGGY_MODELS:
                return TXN_BUGGY_MODELS[kind](n_nodes_hint=node_count)
    if workload == "kafka":
        return KafkaModel()
    if workload.startswith("kafka-bug-"):
        kind = workload[len("kafka-bug-"):]
        if kind in KAFKA_BUGGY_MODELS:
            return KAFKA_BUGGY_MODELS[kind]()
    raise ValueError(
        f"no TPU model for workload {workload!r}; available: echo, "
        f"broadcast, g-set, g-counter, pn-counter, lin-kv, kafka, "
        f"txn-list-append, txn-rw-register, "
        f"lin-kv-bug-{{{', '.join(BUGGY_MODELS)}}}, "
        f"txn-list-append-bug-{{{', '.join(TXN_BUGGY_MODELS)}}}")
