"""TPU-runtime model registry: workload name -> vectorized model.

The device-side counterpart of workloads/__init__.py's registry; the CLI's
``--runtime tpu`` resolves through here.

``opts`` carries the native-engine vocabulary-parity flags so the TPU
runtime speaks them the same way ``run_native_test`` does:

- ``crash_clients`` — kafka: clients randomly crash and resume from the
  committed offsets (``models/kafka.py``; the native engine's
  ``kafka_crash_clients`` twin).
- ``txn_dirty_apply`` — txn workloads: select the dirty-apply mutant by
  FLAG instead of by mutant workload name (the native engine's
  ``flag_txn_dirty_apply``); the returned model carries the mutant's
  own name, so stored runs/replays resolve it unambiguously.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def get_model(workload: str, node_count: int, topology: str = "grid",
              opts: Optional[Dict[str, Any]] = None):
    from .crdt import (BroadcastModel, GCounterModel, GossipSetModel,
                       PNCounterModel)
    from .echo import EchoModel
    from .kafka import KafkaModel, KAFKA_BUGGY_MODELS
    from .raft import RaftModel
    from .raft_buggy import BUGGY_MODELS
    from .txn_raft import (TXN_BUGGY_MODELS, TxnListAppendModel,
                           TxnRwRegisterModel)
    from .unique_ids import UniqueIdsModel

    opts = opts or {}
    if opts.get("txn_dirty_apply") and workload in ("txn-list-append",
                                                    "txn-rw-register"):
        # flag-selected mutant (native-engine parity): same automaton
        # as the -bug-dirty-apply workload name
        workload = f"{workload}-bug-dirty-apply"

    if workload == "echo":
        return EchoModel()
    if workload == "unique-ids":
        return UniqueIdsModel()
    if workload == "broadcast":
        return BroadcastModel(topology)
    if workload == "g-set":
        return GossipSetModel(topology)
    if workload == "pn-counter":
        return PNCounterModel(n_nodes_hint=node_count, topology="total")
    if workload == "g-counter":
        return GCounterModel(n_nodes_hint=node_count, topology="total")
    if workload == "lin-kv":
        return RaftModel(n_nodes_hint=node_count)
    if workload.startswith("lin-kv-bug-"):
        kind = workload[len("lin-kv-bug-"):]
        if kind in BUGGY_MODELS:
            return BUGGY_MODELS[kind](n_nodes_hint=node_count)
    if workload == "txn-list-append":
        return TxnListAppendModel(n_nodes_hint=node_count)
    if workload == "txn-rw-register":
        return TxnRwRegisterModel(n_nodes_hint=node_count)
    for prefix in ("txn-list-append-bug-", "txn-rw-register-bug-"):
        if workload.startswith(prefix):
            kind = workload[len(prefix):]
            if prefix.startswith("txn-rw-register"):
                kind = "rw-" + kind
            if kind in TXN_BUGGY_MODELS:
                return TXN_BUGGY_MODELS[kind](n_nodes_hint=node_count)
    if workload == "kafka":
        return KafkaModel(crash_clients=bool(opts.get("crash_clients")))
    if workload.startswith("kafka-bug-"):
        kind = workload[len("kafka-bug-"):]
        if kind in KAFKA_BUGGY_MODELS:
            return KAFKA_BUGGY_MODELS[kind](
                crash_clients=bool(opts.get("crash_clients")))
    raise ValueError(
        f"no TPU model for workload {workload!r}; available: echo, "
        f"broadcast, g-set, g-counter, pn-counter, lin-kv, kafka, "
        f"txn-list-append, txn-rw-register, "
        f"lin-kv-bug-{{{', '.join(BUGGY_MODELS)}}}, "
        f"txn-list-append-bug-{{{', '.join(TXN_BUGGY_MODELS)}}}")
