"""Device-side kafka workload: keyed append-only logs in fixed slots.

The reference's kafka workload (src/maelstrom/workload/kafka.clj:89-154:
send/send_ok{offset}, poll/poll_ok{msgs}, commit_offsets,
list_committed_offsets) against the single-node log node
(demo kafka_single_node semantics). Vectorized: each instance is one
node plus C clients; per-key logs live in ``[n_keys, log_cap]`` value
slots, consumer positions are tracked server-side per client id (the
role of the reference client's ``positions`` map — on-device clients are
stateless, so the broker holds the cursor, preserving the same
per-process poll monotonicity the checker verifies).

Fixed-shape encodings: a poll returns up to ``poll_max`` messages for
every key (``n_keys * poll_max * 2`` body lanes of ``[offset+1, value]``
pairs, 0 = absent); commit/list replies carry ``n_keys`` offset+1 lanes.

Bug corpus: :class:`KafkaOffsetReuse` hands out the same offset twice
under concurrent sends (the classic non-atomic fetch-and-add) — caught
by the checker as duplicate-offset / inconsistent-offset / lost-write.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..tpu import wire
from ..tpu.runtime import EV_INFO, EV_OK, Model, TYPE_ERROR

F_SEND = 1
F_POLL = 2
F_COMMIT = 3
F_LIST = 4
F_CRASH = 5       # --crash-clients: the client "crashes" — its broker-
                  # side consumer cursor resets to the committed
                  # offsets (jepsen.tests.kafka :crash-clients; the
                  # native engine's kafka_crash_clients twin)

T_SEND = 30
T_SEND_OK = 31
T_POLL = 32
T_POLL_OK = 33
T_COMMIT = 34
T_COMMIT_OK = 35
T_LIST = 36
T_LIST_OK = 37
T_CRASH = 38
T_CRASH_OK = 39


class KafkaRow(NamedTuple):
    log_vals: jnp.ndarray    # [K, cap]
    log_len: jnp.ndarray     # [K]
    committed: jnp.ndarray   # [K] highest committed offset (-1 none)
    positions: jnp.ndarray   # [C, K] next offset each client polls from


class KafkaModel(Model):
    name = "kafka"
    checker_name = "kafka"
    max_out = 1
    idempotent_fs = (F_POLL, F_LIST)
    # schema-conformance map (SCH305): registry RPC name -> wire TYPE.
    # `txn` is None: kafka transactions are a process/native-runtime
    # feature — the device model never encodes them (cli gates --txn)
    WIRE_TYPES = {"send": T_SEND, "poll": T_POLL,
                  "commit_offsets": T_COMMIT,
                  "list_committed_offsets": T_LIST,
                  "txn": None}

    # bug switches (see KafkaOffsetReuse / KafkaCommitRegression)
    reuse_offsets = False     # non-atomic offset assignment
    commit_monotonic = True   # False: commits blindly overwrite

    def __init__(self, n_keys: int = 4, log_cap: int = 64,
                 poll_max: int = 3, crash_clients: bool = False,
                 crash_rate: float = 0.05):
        self.n_keys = n_keys
        self.log_cap = log_cap
        self.poll_max = poll_max
        # --crash-clients (native-engine vocabulary parity): clients
        # randomly issue crash ops; the broker resets their consumer
        # cursor to the committed offsets, so the next poll legally
        # jumps backwards (the checker wrapper marks it reassigned)
        self.crash_clients = bool(crash_clients)
        self.crash_rate = float(crash_rate)
        self.body_lanes = max(n_keys * poll_max * 2, n_keys, 3)
        self.ev_vals = 1 + self.body_lanes
        self.op_lanes = 4

    def _config(self):
        return (self.n_keys, self.log_cap, self.poll_max,
                self.crash_clients, self.crash_rate)

    def __hash__(self):
        return hash((type(self), self._config()))

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._config() == other._config())

    # consumer cursors are a fixed [MAX_CLIENTS, K] block (init_row has
    # no access to the client count); concurrency must stay <= this
    MAX_CLIENTS = 8

    def init_row(self, n_nodes, node_idx, key, params):
        del node_idx, key, params
        return KafkaRow(
            log_vals=jnp.zeros((self.n_keys, self.log_cap), jnp.int32),
            log_len=jnp.zeros((self.n_keys,), jnp.int32),
            committed=jnp.full((self.n_keys,), -1, jnp.int32),
            positions=jnp.zeros((self.MAX_CLIENTS, self.n_keys),
                                jnp.int32),
        )

    def make_params(self, n_nodes: int):
        return None

    def handle(self, row: KafkaRow, node_idx, msg, t, key, cfg, params):
        assert cfg.n_clients <= self.MAX_CLIENTS, (
            f"kafka model tracks {self.MAX_CLIENTS} consumer cursors; "
            f"concurrency {cfg.n_clients} would alias them")
        mtype = msg[wire.TYPE]
        src = msg[wire.SRC]
        positions = row.positions
        ci = jnp.clip(src - cfg.n_nodes, 0, self.MAX_CLIENTS - 1)

        is_send = mtype == T_SEND
        is_poll = mtype == T_POLL
        is_commit = mtype == T_COMMIT
        is_list = mtype == T_LIST
        is_any = is_send | is_poll | is_commit | is_list
        if self.crash_clients:
            # client crash: the broker discards the consumer's cursor
            # and re-seats it at the committed offsets (next unread
            # after the commit; committed is -1 when none)
            is_crash = mtype == T_CRASH
            is_any = is_any | is_crash
            positions = jnp.where(
                is_crash, positions.at[ci].set(row.committed + 1),
                positions)

        k = jnp.clip(msg[wire.BODY], 0, self.n_keys - 1)
        v = msg[wire.BODY + 1]

        # --- send: assign offset = log length, append
        off = row.log_len[k]
        if self.reuse_offsets:
            # BUG: hand out the previous offset again (non-atomic
            # fetch-and-add): two sends share (key, offset)
            off = jnp.maximum(off - 1, 0)
        fits = off < self.log_cap
        do_send = is_send & fits
        log_vals = jnp.where(
            do_send,
            row.log_vals.at[k, jnp.clip(off, 0, self.log_cap - 1)].set(v),
            row.log_vals)
        log_len = jnp.where(do_send,
                            row.log_len.at[k].set(
                                jnp.maximum(row.log_len[k], off + 1)),
                            row.log_len)

        # --- poll: up to poll_max messages per key from this client's
        # cursor; cursor advances past what was returned
        poll_body = jnp.zeros((self.body_lanes,), jnp.int32)
        new_pos = positions[ci]
        for kk in range(self.n_keys):
            pos = positions[ci, kk]
            base = kk * self.poll_max * 2
            for j in range(self.poll_max):
                o = pos + j
                have = o < log_len[kk]
                poll_body = poll_body.at[base + 2 * j].set(
                    jnp.where(have, o + 1, 0))
                poll_body = poll_body.at[base + 2 * j + 1].set(
                    jnp.where(have, log_vals[kk, jnp.clip(
                        o, 0, self.log_cap - 1)], 0))
            new_pos = new_pos.at[kk].set(
                jnp.minimum(pos + self.poll_max, log_len[kk]))
        positions = jnp.where(is_poll,
                              positions.at[ci].set(new_pos), positions)

        # --- commit_offsets: committed[k] advances to this client's
        # processed position - 1 (never regresses)
        my_pos = row.positions[ci]
        commit_vals = my_pos  # offset+1 encoding (0 = nothing polled)
        if self.commit_monotonic:
            new_committed = jnp.maximum(row.committed, my_pos - 1)
        else:
            # BUG variant: blind overwrite — a lagging client's commit
            # drags the group's committed offsets backwards
            new_committed = jnp.where(my_pos > 0, my_pos - 1,
                                      row.committed)
        committed = jnp.where(is_commit, new_committed, row.committed)

        # --- reply
        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        out = out.at[0, wire.VALID].set(jnp.where(is_any, 1, 0))
        out = out.at[0, wire.DEST].set(src)
        tail = (jnp.where(is_list, T_LIST_OK, T_CRASH_OK)
                if self.crash_clients else T_LIST_OK)
        out = out.at[0, wire.TYPE].set(
            jnp.where(is_send & fits, T_SEND_OK,
            jnp.where(is_send, TYPE_ERROR,
            jnp.where(is_poll, T_POLL_OK,
            jnp.where(is_commit, T_COMMIT_OK, tail)))))
        out = out.at[0, wire.REPLYTO].set(msg[wire.MSGID])
        body = jnp.zeros((self.body_lanes,), jnp.int32)
        # send_ok: offset; full log: error 11 (definite, retryable)
        body = body.at[0].set(
            jnp.where(is_send & fits, off,
                      jnp.where(is_send, 11, 0)))
        body = jnp.where(is_poll, poll_body, body)
        kmask = jnp.arange(self.body_lanes) < self.n_keys
        body = jnp.where(is_commit & kmask,
                         jnp.pad(commit_vals,
                                 (0, self.body_lanes - self.n_keys)),
                         body)
        body = jnp.where(is_list & kmask,
                         jnp.pad(row.committed + 1,
                                 (0, self.body_lanes - self.n_keys)),
                         body)
        out = jax.lax.dynamic_update_slice(out, body[None], (0, wire.BODY))

        row = KafkaRow(log_vals=log_vals, log_len=log_len,
                       committed=committed, positions=positions)
        return row, out

    def invariants(self, node_state: KafkaRow, cfg, params):
        # committed offsets never exceed the log end
        return jnp.any(node_state.committed >= node_state.log_len)

    def summary_step(self, summ, node_state: KafkaRow, events, cfg,
                     params):
        """Committed-offset device lane: frontier = the committed
        watermark summed over every (node, key) — per-slot commits only
        advance on a correct trace (commit_monotonic), so a blind
        overwrite downward (KafkaCommitRegression) regresses the sum
        even when some other node still holds a higher offset and a
        fleet-max watermark would mask it. The hash folds every node's
        committed log prefix (forensic only: replication catching up
        legitimately churns it, so no flag keys off it); the model flag
        mirrors the committed-past-log-end invariant."""
        from ..checkers import device_summary
        del events
        committed = node_state.committed                   # [N, K]
        frontier = jnp.sum(committed + 1, dtype=jnp.int32)
        pos = jnp.arange(self.log_cap, dtype=jnp.int32)    # [cap]
        in_pref = pos[None, None, :] <= committed[:, :, None]
        contrib = ((node_state.log_vals * device_summary.HASH_C1 + pos)
                   * ((pos << 1) | 1))
        h = jnp.sum(jnp.where(in_pref, contrib, 0), dtype=jnp.int32)
        return device_summary.fold_frontier(
            summ, frontier, h,
            model_flag=jnp.any(committed >= node_state.log_len))

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg, params):
        kf, kk = jax.random.split(key)
        r = jax.random.uniform(kf)
        k = jax.random.randint(kk, (), 0, self.n_keys, dtype=jnp.int32)
        f = jnp.where(r < 0.45, F_SEND,
                      jnp.where(r < 0.85, F_POLL,
                                jnp.where(r < 0.95, F_COMMIT, F_LIST)))
        if self.crash_clients:
            # crash injection on its own folded key, so enabling the
            # mode never perturbs the base op-mix draws
            kc = jax.random.fold_in(key, 3)
            f = jnp.where(jax.random.uniform(kc) < self.crash_rate,
                          F_CRASH, f)
        v = 1 + uniq  # unique message value per instance
        return jnp.stack([f, k, jnp.where(f == F_SEND, v, 0),
                          jnp.int32(0)])

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        del key
        tail = (jnp.where(op[0] == F_LIST, T_LIST, T_CRASH)
                if self.crash_clients else T_LIST)
        mtype = jnp.where(op[0] == F_SEND, T_SEND,
                          jnp.where(op[0] == F_POLL, T_POLL,
                                    jnp.where(op[0] == F_COMMIT, T_COMMIT,
                                              tail)))
        return wire.make_msg(src=0, dest=0, type_=mtype, msg_id=msg_id,
                             body=(op[1], op[2]),
                             body_lanes=self.body_lanes,
                             netid=cfg.netid)

    def decode_reply_wide(self, op, msg, cfg, params):
        mtype = msg[wire.TYPE]
        ok = ((mtype == T_SEND_OK) | (mtype == T_POLL_OK)
              | (mtype == T_COMMIT_OK) | (mtype == T_LIST_OK))
        etype = jnp.where(ok, EV_OK, EV_INFO)
        vals = jnp.zeros((self.ev_vals,), jnp.int32)
        vals = vals.at[0].set(op[0])
        body = jax.lax.dynamic_slice(msg, (wire.BODY,),
                                     (self.body_lanes,))
        # send_ok: (k, v, offset+1); others: raw body
        send_vals = jnp.zeros((self.body_lanes,), jnp.int32)
        send_vals = send_vals.at[0].set(op[1]).at[1].set(op[2])
        send_vals = send_vals.at[2].set(body[0] + 1)
        payload = jnp.where(mtype == T_SEND_OK, send_vals, body)
        vals = jax.lax.dynamic_update_slice(vals, payload, (1,))
        return etype, vals

    # --- host-side decoding ----------------------------------------------

    def invoke_record(self, *vals):
        f = vals[0]
        if f == F_SEND:
            return {"f": "send", "value": [vals[1], vals[2]]}
        if f == F_POLL:
            return {"f": "poll", "value": None}
        if f == F_COMMIT:
            return {"f": "commit_offsets", "value": {}}
        if f == F_CRASH:
            # crash ops never complete ok by design (the checker's
            # crash-clients vocabulary; checkers/perf.py exempts them)
            return {"f": "crash", "value": None}
        return {"f": "list_committed_offsets",
                "value": list(range(self.n_keys))}

    def complete_record(self, *vals_etype):
        vals, etype = vals_etype[:-1], vals_etype[-1]
        f = vals[0]
        if etype != EV_OK:
            return self.invoke_record(*vals)
        if f == F_SEND:
            return {"f": "send",
                    "value": [vals[1], vals[2], vals[3] - 1]}
        if f == F_POLL:
            msgs = {}
            for kk in range(self.n_keys):
                base = 1 + kk * self.poll_max * 2
                pairs = []
                for j in range(self.poll_max):
                    off1, v = vals[base + 2 * j], vals[base + 2 * j + 1]
                    if off1 > 0:
                        pairs.append([off1 - 1, v])
                if pairs:
                    msgs[kk] = pairs
            return {"f": "poll", "value": msgs}
        offsets = {kk: vals[1 + kk] - 1 for kk in range(self.n_keys)
                   if vals[1 + kk] > 0}
        name = ("commit_offsets" if f == F_COMMIT
                else "list_committed_offsets")
        return {"f": name, "value": offsets}

    def checker(self):
        from ..checkers.kafka import (kafka_checker,
                                      mark_reassigned_after_crashes)
        if not self.crash_clients:
            return lambda history, opts: kafka_checker(history)
        # crash-clients mode: a reopened consumer resumes from the
        # committed offsets, so its first poll after a crash may
        # legally jump backwards — tag it reassigned, exactly the flag
        # the native engine rides on its own records
        return lambda history, opts: kafka_checker(
            mark_reassigned_after_crashes(history))


class KafkaOffsetReuse(KafkaModel):
    """BUG: non-atomic offset assignment — concurrent sends to a key can
    be acked with the same offset, silently overwriting each other."""
    name = "kafka-bug-offset-reuse"
    reuse_offsets = True


class KafkaCommitRegression(KafkaModel):
    """BUG: commit_offsets blindly overwrites instead of taking the max,
    so a lagging consumer drags the group's committed offsets backwards
    — caught by the checker's server-reported commit-regression rule."""
    name = "kafka-bug-commit-regression"
    commit_monotonic = False


KAFKA_BUGGY_MODELS = {
    "offset-reuse": KafkaOffsetReuse,
    "commit-regression": KafkaCommitRegression,
}
