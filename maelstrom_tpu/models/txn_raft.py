"""Device-side transactional workloads over the vectorized Raft log.

The reference's txn-list-append / txn-rw-register workloads
(src/maelstrom/workload/txn_list_append.clj:74-143,
txn_rw_register.clj:83-168) run lists of micro-ops atomically and hand
the history to Elle. Here the replicated state machine is the vectorized
:class:`~.raft.RaftModel` — a whole transaction is ONE log entry, applied
atomically at commit on every node, with the leader replying read
results at apply time. This completes the north-star config #5
(BASELINE.json: txn-list-append over Raft, Elle strict-serializability).

Fixed-shape encodings (SURVEY §7 hard parts):

- a txn is ``txn_max`` micro-op slots ``(f, k, v)`` plus a length lane;
- request body  = ``[len, (f,k,v)*txn_max]`` (+ a proxy-hops lane);
- log entry     = ``[len, (f,k,v)*txn_max, client, client_msg_id]``;
- reply body    = the request echo plus per-micro-op read results
  (list-append: ``txn_max * list_cap`` value lanes; rw-register: read
  values folded into the echoed ``v`` lanes);
- appended/written values are minted unique per instance from the
  client-striped op counter (``uniq``), which is what lets Elle infer
  version orders (unique elements per key, txn_list_append.clj:30-38).

A list-append txn whose appends would overflow a key's fixed value slots
aborts whole with error 30 (txn-conflict, definite) — atomicity is
preserved and the checker sees a clean :fail.

Bug corpus: :class:`TxnDirtyApply` flips ``apply_uncommitted`` — nodes
apply and the leader replies at *append* time instead of commit, so a
leader change truncates acked transactions (lost appends, fractured
reads) — caught by the Elle checker on recorded instances.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..tpu import wire
from ..tpu.runtime import EV_INFO, EV_OK, TYPE_ERROR
from .raft import RaftModel, RaftRow
from . import raft_core
from .raft_core import iclip, sel

# micro-op f codes
MF_R = 1
MF_APPEND = 2    # list-append write
MF_W = 2         # rw-register write (same slot, different semantics)

# message types (distinct from the Raft protocol's 10-13)
T_TXN = 20
T_TXN_OK = 21


class _TxnRaftBase(RaftModel):
    """Shared txn-over-Raft machinery; subclasses set the state-machine
    semantics (list-append vs rw-register)."""

    idempotent_fs = ()          # txns are never idempotent
    write_f = MF_APPEND

    def __init__(self, n_nodes_hint: int = 3, log_cap: int = 96,
                 n_keys: int = 8, txn_max: int = 3, list_cap: int = 16,
                 read_prob: float = 0.5, **kw):
        self.txn_max = txn_max
        self.list_cap = list_cap
        self.read_prob = read_prob
        super().__init__(n_nodes_hint=n_nodes_hint, log_cap=log_cap,
                         n_keys=n_keys, **kw)
        # [len, (f,k,v)*txn_max, client, cmsg]
        self.entry_lanes = 1 + 3 * txn_max + 2
        self.op_lanes = 1 + 3 * txn_max
        self.proxy_hops_lane = 1 + 3 * txn_max
        self.ev_vals = self._reply_width()
        self.body_lanes = max(6 + self.entry_lanes,
                              self._reply_width(),
                              self.proxy_hops_lane + 1)

    def _config(self):
        return super()._config() + (self.txn_max, self.list_cap,
                                    self.read_prob)

    def _reply_width(self) -> int:
        raise NotImplementedError

    # --- request / entry encoding ----------------------------------------

    def _is_client_request(self, mtype):
        return mtype == T_TXN

    def _encode_entry(self, msg, src):
        return jnp.concatenate(
            [msg[wire.BODY:wire.BODY + 1 + 3 * self.txn_max],
             src[None], msg[wire.MSGID:wire.MSGID + 1]])

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg, params):
        kf, kk, kl = jax.random.split(key, 3)
        ln = jax.random.randint(kl, (), 1, self.txn_max + 1,
                                dtype=jnp.int32)
        fs = jnp.where(
            jax.random.uniform(kf, (self.txn_max,)) < self.read_prob,
            MF_R, self.write_f)
        ks = jax.random.randint(kk, (self.txn_max,), 0, self.n_keys,
                                dtype=jnp.int32)
        # unique positive write values per instance (uniq is striped
        # across clients by the runtime)
        vs = 1 + uniq * self.txn_max + jnp.arange(self.txn_max,
                                                  dtype=jnp.int32)
        op = jnp.zeros((self.op_lanes,), jnp.int32).at[0].set(ln)
        idx = 1 + 3 * jnp.arange(self.txn_max)
        op = op.at[idx].set(fs).at[idx + 1].set(ks).at[idx + 2].set(vs)
        return op

    def sample_final_op(self, key, uniq, cfg, params):
        """Post-heal phase: all-read txns over random keys, giving the
        lost-append / version-order analysis dense read coverage (the
        role of the reference's final reads in set-like workloads)."""
        kk = jax.random.split(key, 1)[0]
        ks = jax.random.randint(kk, (self.txn_max,), 0, self.n_keys,
                                dtype=jnp.int32)
        op = jnp.zeros((self.op_lanes,), jnp.int32).at[0].set(self.txn_max)
        idx = 1 + 3 * jnp.arange(self.txn_max)
        op = op.at[idx].set(MF_R).at[idx + 1].set(ks)
        return op

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        dest = jax.random.randint(key, (), 0, cfg.n_nodes, dtype=jnp.int32)
        m = wire.make_msg(src=0, dest=dest, type_=T_TXN, msg_id=msg_id,
                          body_lanes=self.body_lanes, netid=cfg.netid)
        return jax.lax.dynamic_update_slice(m, op, (wire.BODY,))

    def decode_reply_wide(self, op, msg, cfg, params):
        ok = msg[wire.TYPE] == T_TXN_OK
        etype = jnp.where(ok, EV_OK, EV_INFO)
        vals = jax.lax.dynamic_slice(msg, (wire.BODY,), (self.ev_vals,))
        return etype, vals

    # --- host-side decoding ----------------------------------------------

    def _micro_ops(self, vals):
        ln = max(0, min(int(vals[0]), self.txn_max))
        return [(int(vals[1 + 3 * i]), int(vals[2 + 3 * i]),
                 int(vals[3 + 3 * i])) for i in range(ln)]

    def invoke_record(self, *vals):
        txn = []
        for f, k, v in self._micro_ops(vals):
            if f == MF_R:
                txn.append(["r", k, None])
            else:
                txn.append([self.write_f_name, k, v])
        return {"f": "txn", "value": txn}


class TxnListAppendModel(_TxnRaftBase):
    """txn-list-append: reads return the full per-key append list."""

    name = "txn-list-append"
    checker_name = "elle-list-append"
    write_f_name = "append"
    write_f = MF_APPEND

    def _reply_width(self):
        # request echo + txn_max read-result blocks of list_cap values
        return 1 + 3 * self.txn_max + self.txn_max * self.list_cap

    def _init_kv(self):
        # [n_keys, 1 + list_cap]: lane 0 = length, 1.. = appended values
        return jnp.zeros((self.n_keys, 1 + self.list_cap), jnp.int32)

    def apply_entry(self, row: RaftRow, do, entry, cfg):
        """Fused-path apply hook: the txn_max micro-op chain as ONE
        unrolled-scan body instead of txn_max traced copies — value-
        for-value the pre-fusion legacy apply (pinned by the frozen
        goldens; reads snapshot the per-key list as of that micro-op,
        an overflowing append aborts the whole txn with error 30)."""
        T = self.txn_max
        Lc = self.list_cap
        ln, client, cmsg = entry[0], entry[-2], entry[-1]
        reply = jnp.zeros((self.ev_vals,), jnp.int32).at[0].set(ln)
        reply = lax.dynamic_update_slice(reply, entry[1:1 + 3 * T],
                                         (1,))
        rbase = 1 + 3 * T
        fkv = entry[1:1 + 3 * T].reshape(T, 3)

        def micro(carry, x):
            kv, reply, overflow = carry
            i, mop = x
            f, k, v = mop[0], mop[1], mop[2]
            z0i = i * 0
            active = i < ln
            is_rd = active & (f == MF_R)
            is_app = active & (f == MF_APPEND)
            # one clamped row read (dget == the legacy k clip) shared
            # by the read snapshot and the append path
            rk = raft_core.tget(kv, k)
            # read: snapshot k's list (sees earlier appends in this txn)
            reply = lax.dynamic_update_slice(
                reply, jnp.where(is_rd, rk[1:], 0),
                (rbase + i * Lc,))
            # append: push v
            lk = rk[0]
            fits = lk < Lc
            overflow = overflow | (is_app & ~fits)
            new_rk = lax.dynamic_update_index_in_dim(
                rk, v, 1 + iclip(lk, z0i, z0i + (Lc - 1)), axis=0)
            new_rk = new_rk.at[0].add(1)
            kv = sel(is_app & fits,
                     kv.at[k].set(new_rk, mode="drop"), kv)
            return (kv, reply, overflow), None

        (kv, reply, overflow), _ = lax.scan(
            micro, (row.kv, reply, jnp.bool_(False)),
            (jnp.arange(T, dtype=jnp.int32), fkv), unroll=True)
        ok = ~overflow
        row = row._replace(kv=sel(do & ok, kv, row.kv))

        z0 = ln * 0
        z01 = z0[None]
        body = sel(ok, reply,
                   jnp.zeros_like(reply).at[0].set(30))  # txn-conflict
        pad = cfg.lanes - wire.BODY - self.ev_vals
        return row, jnp.concatenate(
            [(do & (row.role == 2)).astype(jnp.int32)[None], z01,
             client[None], z01, sel(ok, T_TXN_OK, TYPE_ERROR)[None],
             z01, cmsg[None], z01, body]
            + ([jnp.zeros((pad,), jnp.int32)] if pad else []))

    def complete_record(self, *vals_etype):
        vals, etype = vals_etype[:-1], vals_etype[-1]
        if etype != EV_OK:
            return self.invoke_record(*vals)
        rbase = 1 + 3 * self.txn_max
        txn = []
        for i, (f, k, v) in enumerate(self._micro_ops(vals)):
            if f == MF_R:
                block = vals[rbase + i * self.list_cap:
                             rbase + (i + 1) * self.list_cap]
                lst = []
                for x in block:
                    if x == 0:
                        break
                    lst.append(int(x))
                txn.append(["r", k, lst])
            else:
                txn.append(["append", k, v])
        return {"f": "txn", "value": txn}

    def checker(self):
        from ..checkers.elle import check_list_append
        return lambda history, opts: check_list_append(
            history, (opts or {}).get("consistency_models")
            or "strict-serializable")


class TxnRwRegisterModel(_TxnRaftBase):
    """txn-rw-register: read/write register micro-ops; reads fold their
    value into the echoed ``v`` lane."""

    name = "txn-rw-register"
    checker_name = "elle-rw-register"
    write_f_name = "w"
    write_f = MF_W

    def _reply_width(self):
        return 1 + 3 * self.txn_max

    def _init_kv(self):
        return jnp.zeros((self.n_keys,), jnp.int32)   # 0 = unwritten

    def apply_entry(self, row: RaftRow, do, entry, cfg):
        """Fused-path apply hook: register micro-ops as one
        unrolled-scan body — value-for-value the pre-fusion legacy
        apply (reads fold into the echoed v lane)."""
        T = self.txn_max
        ln, client, cmsg = entry[0], entry[-2], entry[-1]
        reply = jnp.zeros((self.ev_vals,), jnp.int32).at[0].set(ln)
        reply = lax.dynamic_update_slice(reply, entry[1:1 + 3 * T],
                                         (1,))
        fkv = entry[1:1 + 3 * T].reshape(T, 3)

        def micro(carry, x):
            kv, reply = carry
            i, mop = x
            f, k, v = mop[0], mop[1], mop[2]
            active = i < ln
            is_rd = active & (f == MF_R)
            is_wr = active & (f == MF_W)
            # read result replaces the echoed v lane (dget/dset clamp
            # exactly like the legacy k clip)
            vlane = 3 + 3 * i
            reply = reply.at[vlane].set(
                jnp.where(is_rd, raft_core.tget(kv, k),
                          raft_core.tget(reply, vlane)),
                mode="drop")
            kv = sel(is_wr, kv.at[k].set(v, mode="drop"), kv)
            return (kv, reply), None

        (kv, reply), _ = lax.scan(
            micro, (row.kv, reply),
            (jnp.arange(T, dtype=jnp.int32), fkv), unroll=True)
        row = row._replace(kv=sel(do, kv, row.kv))

        z0 = ln * 0
        z01 = z0[None]
        pad = cfg.lanes - wire.BODY - self.ev_vals
        return row, jnp.concatenate(
            [(do & (row.role == 2)).astype(jnp.int32)[None], z01,
             client[None], z01, (z0 + T_TXN_OK)[None], z01, cmsg[None],
             z01, reply]
            + ([jnp.zeros((pad,), jnp.int32)] if pad else []))

    def complete_record(self, *vals_etype):
        vals, etype = vals_etype[:-1], vals_etype[-1]
        if etype != EV_OK:
            return self.invoke_record(*vals)
        txn = []
        for f, k, v in self._micro_ops(vals):
            if f == MF_R:
                txn.append(["r", k, None if v == 0 else v])
            else:
                txn.append(["w", k, v])
        return {"f": "txn", "value": txn}

    def checker(self):
        from ..checkers.elle import check_rw_register
        return lambda history, opts: check_rw_register(
            history, (opts or {}).get("consistency_models")
            or "strict-serializable")


class TxnDirtyApply(TxnListAppendModel):
    """BUG: apply + reply at append time instead of commit — a leader
    change truncates acked txns (lost appends / fractured reads)."""
    name = "txn-list-append-bug-dirty-apply"
    apply_uncommitted = True


class TxnRwDirtyApply(TxnRwRegisterModel):
    """BUG: the same dirty apply on the rw-register workload — caught
    via the checker's wfr/initial-version order inference as G-single
    cycles (stale reads of truncated acked writes)."""
    name = "txn-rw-register-bug-dirty-apply"
    apply_uncommitted = True


TXN_BUGGY_MODELS = {
    "dirty-apply": TxnDirtyApply,
    "rw-dirty-apply": TxnRwDirtyApply,
}
