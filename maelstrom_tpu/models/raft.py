"""Vectorized Raft: a linearizable KV store as a fixed-shape JAX automaton.

The TPU-runtime flagship (SURVEY §7 step 7) — the north-star config where
one chip fuzzes thousands of independent Raft clusters in parallel. The
protocol follows the reference's teaching Raft (demo/python/raft.py:
elections :274-343, log replication :391-445, commit via median
match-index :382-389) re-expressed as pure per-node step functions over
int32 lanes:

- leader election with randomized timeouts, vote bitmasks, term step-down
- log replication one entry per AppendEntries, with conflict truncation
  and next/match index backoff
- commit = median match index, guarded to current-term entries
- all client ops (read/write/cas) go through the log; the leader replies
  at apply time; non-leaders reject with error 11 (temporarily-available),
  which clients treat as a definite failure and retry as fresh ops
- fixed-capacity log (``log_cap``); a full log rejects client ops with
  error 11 (explicit, visible backpressure instead of dynamic growth)

Checked per instance by the WGL linearizability checker
(checkers/linearizable.py), the same boundary the reference's lin-kv
workload hands to Knossos.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..tpu import wire
from ..tpu.runtime import EV_INFO, EV_OK, Model, TYPE_ERROR

# message types
T_READ = 1
T_WRITE = 2
T_CAS = 3
T_READ_OK = 4
T_WRITE_OK = 5
T_CAS_OK = 6
T_REQ_VOTE = 10
T_VOTE_REPLY = 11
T_APPEND = 12
T_APPEND_REPLY = 13

F_READ = 1
F_WRITE = 2
F_CAS = 3

NIL = -1     # missing KV value

# log entry body lanes: (f, key, a, b, client, client_msg_id)
ENTRY_LANES = 6


class RaftRow(NamedTuple):
    """Per-node Raft state (the lanes of one row of the cluster tensor)."""
    term: jnp.ndarray
    voted_for: jnp.ndarray
    role: jnp.ndarray            # 0 follower / 1 candidate / 2 leader
    votes: jnp.ndarray           # bitmask of granted votes
    commit_idx: jnp.ndarray      # number of committed entries
    last_applied: jnp.ndarray
    log_term: jnp.ndarray        # [LOGN]
    log_body: jnp.ndarray        # [LOGN, ENTRY_LANES]
    log_len: jnp.ndarray
    kv: jnp.ndarray              # [KEYS]
    next_idx: jnp.ndarray        # [N] entries known replicated per peer
    match_idx: jnp.ndarray       # [N]
    election_deadline: jnp.ndarray
    last_hb: jnp.ndarray
    leader_hint: jnp.ndarray     # last known leader (for client proxying,
                                 # the role of raft.py:552-571); -1 unknown


class RaftModel(Model):
    name = "lin-kv"
    body_lanes = 12
    max_out = 1
    idempotent_fs = (F_READ,)

    def __init__(self, n_nodes_hint: int = 5, log_cap: int = 96,
                 n_keys: int = 8, n_vals: int = 8,
                 elect_min: int = 60, elect_jitter: int = 60,
                 heartbeat: int = 15, apply_max: int = 2):
        self.n_nodes_hint = n_nodes_hint
        self.log_cap = log_cap
        self.n_keys = n_keys
        self.n_vals = n_vals
        self.elect_min = elect_min
        self.elect_jitter = elect_jitter
        self.heartbeat = heartbeat
        self.apply_max = apply_max
        # tick emits: (N-1) vote-or-append sends + apply_max client replies
        self.tick_out = (n_nodes_hint - 1) + apply_max

    def _config(self):
        return (self.n_nodes_hint, self.log_cap, self.n_keys, self.n_vals,
                self.elect_min, self.elect_jitter, self.heartbeat,
                self.apply_max)

    def __hash__(self):
        return hash((type(self), self._config()))

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._config() == other._config())

    def init_row(self, n_nodes, node_idx, key, params):
        assert n_nodes == self.n_nodes_hint
        jitter = jax.random.randint(key, (), 0, self.elect_jitter)
        return RaftRow(
            term=jnp.int32(0),
            voted_for=jnp.int32(-1),
            role=jnp.int32(0),
            votes=jnp.int32(0),
            commit_idx=jnp.int32(0),
            last_applied=jnp.int32(0),
            log_term=jnp.zeros((self.log_cap,), jnp.int32),
            log_body=jnp.zeros((self.log_cap, ENTRY_LANES), jnp.int32),
            log_len=jnp.int32(0),
            kv=jnp.full((self.n_keys,), NIL, jnp.int32),
            next_idx=jnp.zeros((n_nodes,), jnp.int32),
            match_idx=jnp.zeros((n_nodes,), jnp.int32),
            election_deadline=(self.elect_min + jitter).astype(jnp.int32),
            last_hb=jnp.int32(0),
            leader_hint=jnp.int32(-1),
        )

    # --- helpers ----------------------------------------------------------

    def _last_log_term(self, row: RaftRow):
        return jnp.where(row.log_len > 0,
                         row.log_term[jnp.maximum(row.log_len - 1, 0)], 0)

    def _step_down(self, row: RaftRow, new_term, t):
        """Adopt a higher term as follower."""
        higher = new_term > row.term
        return row._replace(
            term=jnp.where(higher, new_term, row.term),
            role=jnp.where(higher, 0, row.role),
            voted_for=jnp.where(higher, -1, row.voted_for),
            votes=jnp.where(higher, 0, row.votes),
        )

    def _reset_election(self, row: RaftRow, t, key):
        jitter = jax.random.randint(key, (), 0, self.elect_jitter)
        return row._replace(
            election_deadline=(t + self.elect_min + jitter).astype(
                jnp.int32))

    def _reply(self, cfg, dest, type_, reply_to, body_vals):
        return wire.make_msg(src=0, dest=dest, type_=type_,
                             reply_to=reply_to, body=body_vals,
                             body_lanes=self.body_lanes)[None]

    # --- message handlers -------------------------------------------------

    def handle(self, row: RaftRow, node_idx, msg, t, key, cfg, params):
        mtype = msg[wire.TYPE]

        row_v, out_v = self._handle_req_vote(row, node_idx, msg, t, key,
                                             cfg)
        row_vr = self._handle_vote_reply(row, node_idx, msg, cfg)
        row_a, out_a = self._handle_append(row, node_idx, msg, t, key, cfg)
        row_ar = self._handle_append_reply(row, msg)
        row_c, out_c = self._handle_client(row, node_idx, msg, cfg)

        def pick(a, b, cond):
            return jax.tree.map(lambda x, y: jnp.where(cond, y, x), a, b)

        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        new = row
        new = pick(new, row_v, mtype == T_REQ_VOTE)
        new = pick(new, row_vr, mtype == T_VOTE_REPLY)
        new = pick(new, row_a, mtype == T_APPEND)
        new = pick(new, row_ar, mtype == T_APPEND_REPLY)
        is_client = (mtype == T_READ) | (mtype == T_WRITE) | (mtype == T_CAS)
        new = pick(new, row_c, is_client)
        out = jnp.where(mtype == T_REQ_VOTE, out_v, out)
        out = jnp.where(mtype == T_APPEND, out_a, out)
        out = jnp.where(is_client, out_c, out)
        return new, out

    def _handle_req_vote(self, row, node_idx, msg, t, key, cfg):
        c_term = msg[wire.BODY]
        c_lli = msg[wire.BODY + 1]      # candidate log length
        c_llt = msg[wire.BODY + 2]      # candidate last log term
        src = msg[wire.SRC]

        row = self._step_down(row, c_term, t)
        my_llt = self._last_log_term(row)
        log_ok = (c_llt > my_llt) | ((c_llt == my_llt)
                                     & (c_lli >= row.log_len))
        grant = ((c_term == row.term)
                 & ((row.voted_for == -1) | (row.voted_for == src))
                 & log_ok)
        row = row._replace(
            voted_for=jnp.where(grant, src, row.voted_for))
        row = jax.tree.map(
            lambda a, b: jnp.where(grant, b, a), row,
            self._reset_election(row, t, key))
        out = self._reply(cfg, src, T_VOTE_REPLY, msg[wire.MSGID],
                          [row.term, grant.astype(jnp.int32)])
        return row, out

    def _handle_vote_reply(self, row, node_idx, msg, cfg):
        r_term = msg[wire.BODY]
        granted = msg[wire.BODY + 1] == 1
        src = msg[wire.SRC]
        n = cfg.n_nodes

        row = self._step_down(row, r_term, 0)
        count_it = (row.role == 1) & (r_term == row.term) & granted
        votes = jnp.where(count_it,
                          row.votes | (1 << src).astype(jnp.int32),
                          row.votes)
        n_votes = jnp.sum((votes[None] >> jnp.arange(n)) & 1) + 1  # + self
        win = count_it & (n_votes > n // 2)
        row = row._replace(
            votes=votes,
            role=jnp.where(win, 2, row.role),
            # next_idx starts at log_len (send from the tip, back off on
            # conflict); own match is everything
            next_idx=jnp.where(win, row.log_len, row.next_idx),
            match_idx=jnp.where(
                win, jnp.zeros_like(row.match_idx), row.match_idx
            ).at[node_idx].set(jnp.where(win, row.log_len,
                                         row.match_idx[node_idx])),
            last_hb=jnp.where(win, -self.heartbeat, row.last_hb),
        )
        return row

    def _handle_append(self, row, node_idx, msg, t, key, cfg):
        l_term = msg[wire.BODY]
        prev_idx = msg[wire.BODY + 1]
        prev_term = msg[wire.BODY + 2]
        l_commit = msg[wire.BODY + 3]
        n_entries = msg[wire.BODY + 4]
        e_term = msg[wire.BODY + 5]
        e_body = msg[wire.BODY + 6:wire.BODY + 6 + ENTRY_LANES]
        src = msg[wire.SRC]

        row = self._step_down(row, l_term, t)
        current = l_term == row.term
        # a current-term AppendEntries always comes from the legitimate
        # leader: candidates step back down, election timer resets, and
        # the sender becomes the leader hint for client proxying
        row = row._replace(
            role=jnp.where(current & (row.role == 1), 0, row.role),
            leader_hint=jnp.where(current, src, row.leader_hint))
        row = jax.tree.map(
            lambda a, b: jnp.where(current, b, a), row,
            self._reset_election(row, t, key))

        prev_ok = (prev_idx == 0) | (
            (prev_idx <= row.log_len)
            & (row.log_term[jnp.maximum(prev_idx - 1, 0)] == prev_term))
        fits = prev_idx < self.log_cap
        accept = current & prev_ok & ((n_entries == 0) | fits)

        # append/overwrite the entry at prev_idx
        do_write = accept & (n_entries == 1)
        widx = jnp.clip(prev_idx, 0, self.log_cap - 1)
        same = (row.log_len > prev_idx) & (row.log_term[widx] == e_term)
        new_len = jnp.where(
            do_write,
            jnp.where(same, jnp.maximum(row.log_len, prev_idx + 1),
                      prev_idx + 1),
            row.log_len)
        log_term = jnp.where(do_write,
                             row.log_term.at[widx].set(e_term),
                             row.log_term)
        log_body = jnp.where(do_write,
                             row.log_body.at[widx].set(e_body),
                             row.log_body)
        match = jnp.where(accept, prev_idx + n_entries, 0)
        # Raft §5.3: commit = min(leaderCommit, index of last NEW entry) —
        # NOT the local log length, which may include an unverified
        # divergent tail kept past prev_idx+1
        commit = jnp.where(accept,
                           jnp.maximum(row.commit_idx,
                                       jnp.minimum(l_commit, match)),
                           row.commit_idx)
        row = row._replace(log_term=log_term, log_body=log_body,
                           log_len=new_len, commit_idx=commit)
        out = self._reply(cfg, src, T_APPEND_REPLY, msg[wire.MSGID],
                          [row.term, accept.astype(jnp.int32), match])
        return row, out

    def _handle_append_reply(self, row, msg):
        r_term = msg[wire.BODY]
        success = msg[wire.BODY + 1] == 1
        match = msg[wire.BODY + 2]
        src = msg[wire.SRC]

        row = self._step_down(row, r_term, 0)
        mine = (row.role == 2) & (r_term == row.term)
        ok = mine & success
        fail = mine & ~success
        next_idx = row.next_idx
        next_idx = jnp.where(ok, next_idx.at[src].set(
            jnp.maximum(next_idx[src], match)), next_idx)
        next_idx = jnp.where(fail, next_idx.at[src].set(
            jnp.maximum(next_idx[src] - 1, 0)), next_idx)
        match_idx = jnp.where(ok, row.match_idx.at[src].set(
            jnp.maximum(row.match_idx[src], match)), row.match_idx)
        return row._replace(next_idx=next_idx, match_idx=match_idx)

    def _handle_client(self, row, node_idx, msg, cfg):
        mtype = msg[wire.TYPE]
        src = msg[wire.SRC]
        is_leader = row.role == 2
        full = row.log_len >= self.log_cap
        accept = is_leader & ~full
        # non-leaders proxy to the last known leader, preserving the
        # client src so the leader replies straight to the client; body
        # lane 3 counts hops to stop forwarding loops
        hops = msg[wire.BODY + 3]
        forward = (~accept & (row.leader_hint >= 0)
                   & (row.leader_hint != node_idx) & (hops < 3))

        f = jnp.where(mtype == T_READ, F_READ,
                      jnp.where(mtype == T_WRITE, F_WRITE, F_CAS))
        entry = jnp.stack([f, msg[wire.BODY], msg[wire.BODY + 1],
                           msg[wire.BODY + 2], src, msg[wire.MSGID]])
        widx = jnp.clip(row.log_len, 0, self.log_cap - 1)
        row = row._replace(
            log_term=jnp.where(accept,
                               row.log_term.at[widx].set(row.term),
                               row.log_term),
            log_body=jnp.where(accept,
                               row.log_body.at[widx].set(entry),
                               row.log_body),
            log_len=jnp.where(accept, row.log_len + 1, row.log_len),
            match_idx=jnp.where(
                accept,
                row.match_idx.at[node_idx].set(row.log_len + 1),
                row.match_idx),
        )
        # forward: re-emit the request toward the leader hint; otherwise
        # reject with error 11 temporarily-unavailable (definite -> client
        # fails the op and moves on, like the reference's non-leader nodes)
        fwd = msg.at[wire.DEST].set(row.leader_hint)
        fwd = fwd.at[wire.BODY + 3].set(hops + 1)
        err = self._reply(cfg, src, TYPE_ERROR, msg[wire.MSGID], [11])[0]
        out = jnp.where(forward, fwd, err)[None]
        out = out.at[0, wire.VALID].set(jnp.where(accept, 0, 1))
        return row, out

    # --- per-tick behavior ------------------------------------------------

    def tick(self, row: RaftRow, node_idx, t, key, cfg, params):
        n = cfg.n_nodes
        k_elect, k_jit = jax.random.split(key)

        # 1) election timeout -> candidacy
        timeout = (row.role != 2) & (t >= row.election_deadline)
        row = row._replace(
            term=jnp.where(timeout, row.term + 1, row.term),
            role=jnp.where(timeout, 1, row.role),
            voted_for=jnp.where(timeout, node_idx, row.voted_for),
            votes=jnp.where(timeout, 0, row.votes),
            # make the first vote solicitation fire immediately
            last_hb=jnp.where(timeout, t - self.heartbeat, row.last_hb),
            # suspected-dead leader: stop proxying to it
            leader_hint=jnp.where(timeout, -1, row.leader_hint),
        )
        row = jax.tree.map(
            lambda a, b: jnp.where(timeout, b, a), row,
            self._reset_election(row, t, k_jit))

        # 2) leader: advance commit to the median match index (current
        # term only), then apply
        is_leader = row.role == 2
        match = row.match_idx.at[node_idx].set(row.log_len)
        sorted_match = jnp.sort(match)               # ascending
        majority_match = sorted_match[(n - 1) // 2]  # value >= on majority
        guard_idx = jnp.clip(majority_match - 1, 0, self.log_cap - 1)
        current_term_ok = row.log_term[guard_idx] == row.term
        new_commit = jnp.where(
            is_leader & (majority_match > row.commit_idx)
            & current_term_ok,
            majority_match, row.commit_idx)
        row = row._replace(commit_idx=new_commit, match_idx=match)

        # 3) apply up to apply_max committed entries; leader replies
        outs = []
        for _ in range(self.apply_max):
            row, reply = self._apply_one(row, cfg)
            outs.append(reply)

        # 4) peer sends: candidates solicit votes (re-solicit on the same
        # cadence to survive loss), leaders replicate
        is_leader = row.role == 2
        solicit = (row.role == 1) & (t - row.last_hb >= self.heartbeat)
        hb_due = is_leader & (t - row.last_hb >= self.heartbeat)
        row = row._replace(
            last_hb=jnp.where(hb_due | solicit, t, row.last_hb))
        peer_msgs = self._peer_sends(row, node_idx, t, solicit, hb_due, cfg)
        outs.append(peer_msgs)
        return row, jnp.concatenate(outs, axis=0)

    def _apply_one(self, row: RaftRow, cfg):
        do = row.last_applied < row.commit_idx
        aidx = jnp.clip(row.last_applied, 0, self.log_cap - 1)
        entry = row.log_body[aidx]
        f, k, a, b, client, cmsg = (entry[0], entry[1], entry[2], entry[3],
                                    entry[4], entry[5])
        k = jnp.clip(k, 0, self.n_keys - 1)
        cur = row.kv[k]
        cas_ok = cur == a
        new_val = jnp.where(f == F_WRITE, a,
                            jnp.where((f == F_CAS) & cas_ok, b, cur))
        kv = jnp.where(do, row.kv.at[k].set(new_val), row.kv)
        row = row._replace(
            kv=kv, last_applied=jnp.where(do, row.last_applied + 1,
                                          row.last_applied))

        # leader replies to the waiting client
        reply_type = jnp.where(
            f == F_READ, T_READ_OK,
            jnp.where(f == F_WRITE, T_WRITE_OK,
                      jnp.where(cas_ok, T_CAS_OK, TYPE_ERROR)))
        err_code = jnp.where(cur == NIL, 20, 22)
        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        out = out.at[0, wire.VALID].set(
            jnp.where(do & (row.role == 2), 1, 0))
        out = out.at[0, wire.DEST].set(client)
        out = out.at[0, wire.TYPE].set(reply_type)
        out = out.at[0, wire.REPLYTO].set(cmsg)
        # read replies carry (key, value); cas errors carry the code
        out = out.at[0, wire.BODY].set(
            jnp.where(reply_type == TYPE_ERROR, err_code, k))
        out = out.at[0, wire.BODY + 1].set(cur)
        return row, out

    def _peer_sends(self, row: RaftRow, node_idx, t, solicit, hb_due, cfg):
        """One message per peer slot (N-1 rows): RequestVote when a
        soliciting candidate, AppendEntries on the leader's heartbeat
        cadence."""
        n = cfg.n_nodes
        # peers = all nodes except self, packed into n-1 slots
        slots = jnp.arange(n - 1, dtype=jnp.int32)
        peers = jnp.where(slots >= node_idx, slots + 1, slots)

        def per_peer(peer):
            vote_body = [row.term, row.log_len, self._last_log_term(row)]
            prev_idx = row.next_idx[peer]
            has_entry = row.log_len > prev_idx
            eidx = jnp.clip(prev_idx, 0, self.log_cap - 1)
            pidx = jnp.clip(prev_idx - 1, 0, self.log_cap - 1)
            append_body = [row.term, prev_idx,
                           jnp.where(prev_idx > 0, row.log_term[pidx], 0),
                           row.commit_idx,
                           has_entry.astype(jnp.int32),
                           row.log_term[eidx]]
            out = jnp.zeros((cfg.lanes,), dtype=jnp.int32)
            send_vote = solicit
            send_append = hb_due
            out = out.at[wire.VALID].set(
                jnp.where(send_vote | send_append, 1, 0))
            out = out.at[wire.DEST].set(peer)
            out = out.at[wire.TYPE].set(
                jnp.where(send_vote, T_REQ_VOTE, T_APPEND))
            for i, v in enumerate(vote_body):
                out = out.at[wire.BODY + i].set(
                    jnp.where(send_vote, v, append_body[i]))
            for i in range(len(vote_body), len(append_body)):
                out = out.at[wire.BODY + i].set(
                    jnp.where(send_vote, 0, append_body[i]))
            entry = row.log_body[eidx] * has_entry.astype(jnp.int32)
            out = jax.lax.dynamic_update_slice(
                out, jnp.where(send_vote, 0, entry),
                (wire.BODY + 6,))
            return out

        return jax.vmap(per_peer)(peers)

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg, params):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        r = jax.random.uniform(k1)
        kk = jax.random.randint(k2, (), 0, self.n_keys, dtype=jnp.int32)
        v1 = jax.random.randint(k3, (), 0, self.n_vals, dtype=jnp.int32)
        v2 = jax.random.randint(k4, (), 0, self.n_vals, dtype=jnp.int32)
        f = jnp.where(r < 1 / 3, F_READ,
                      jnp.where(r < 2 / 3, F_WRITE, F_CAS))
        return jnp.stack([f, kk, v1, v2])

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        dest = jax.random.randint(key, (), 0, cfg.n_nodes, dtype=jnp.int32)
        mtype = jnp.where(op[0] == F_READ, T_READ,
                          jnp.where(op[0] == F_WRITE, T_WRITE, T_CAS))
        return wire.make_msg(src=0, dest=dest, type_=mtype, msg_id=msg_id,
                             body=(op[1], op[2], op[3]),
                             body_lanes=self.body_lanes)

    def decode_reply(self, op, msg, cfg, params):
        mtype = msg[wire.TYPE]
        ok = ((mtype == T_READ_OK) | (mtype == T_WRITE_OK)
              | (mtype == T_CAS_OK))
        etype = jnp.where(ok, EV_OK, EV_INFO)
        value = jnp.stack([op[1],
                           jnp.where(mtype == T_READ_OK,
                                     msg[wire.BODY + 1], op[2]),
                           op[3]])
        return etype, value

    # --- host-side decoding ----------------------------------------------

    def invoke_record(self, f, a, b, c):
        if f == F_READ:
            return {"f": "read", "value": [a, None]}
        if f == F_WRITE:
            return {"f": "write", "value": [a, b]}
        return {"f": "cas", "value": [a, [b, c]]}

    def complete_record(self, f, a, b, c, etype):
        if etype != EV_OK:
            return self.invoke_record(f, a, b, c)
        if f == F_READ:
            return {"f": "read", "value": [a, None if b == NIL else b]}
        if f == F_WRITE:
            return {"f": "write", "value": [a, b]}
        return {"f": "cas", "value": [a, [b, c]]}

    def checker(self):
        from ..checkers.linearizable import linearizable_kv_checker
        return lambda history, opts: linearizable_kv_checker(history)
