"""Vectorized Raft: a linearizable KV store as a fixed-shape JAX automaton.

The TPU-runtime flagship (SURVEY §7 step 7) — the north-star config where
one chip fuzzes thousands of independent Raft clusters in parallel. The
protocol follows the reference's teaching Raft (demo/python/raft.py:
elections :274-343, log replication :391-445, commit via median
match-index :382-389) re-expressed as pure per-node step functions over
int32 lanes:

- leader election with randomized timeouts, vote bitmasks, term step-down
- log replication one entry per AppendEntries, with conflict truncation
  and next/match index backoff
- commit = median match index, guarded to current-term entries
- all client ops (read/write/cas) go through the log; the leader replies
  at apply time; non-leaders reject with error 11 (temporarily-available),
  which clients treat as a definite failure and retry as fresh ops
- fixed-capacity log (``log_cap``); a full log rejects client ops with
  error 11 (explicit, visible backpressure instead of dynamic growth)

Checked per instance by the WGL linearizability checker
(checkers/linearizable.py), the same boundary the reference's lin-kv
workload hands to Knossos.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..tpu import wire
from ..tpu.runtime import EV_INFO, EV_OK, Model, TYPE_ERROR
from . import raft_core
# the protocol constants live with the shared fusion kernel; re-exported
# here so this module stays the raft vocabulary's import point (the
# wire-schema lint resolves T_* against the model's module)
from .raft_core import (ENTRY_LANES, F_CAS, F_READ, F_WRITE, NIL,  # noqa: F401
                        T_APPEND, T_APPEND_REPLY, T_CAS, T_CAS_OK,
                        T_READ, T_READ_OK, T_REQ_VOTE, T_VOTE_REPLY,
                        T_WRITE, T_WRITE_OK, iclip, sel)


class RaftRow(NamedTuple):
    """Per-node Raft state (the lanes of one row of the cluster tensor)."""
    term: jnp.ndarray
    voted_for: jnp.ndarray
    role: jnp.ndarray            # 0 follower / 1 candidate / 2 leader
    votes: jnp.ndarray           # bitmask of granted votes
    commit_idx: jnp.ndarray      # number of committed entries
    last_applied: jnp.ndarray
    log_term: jnp.ndarray        # [LOGN]
    log_body: jnp.ndarray        # [LOGN, ENTRY_LANES]
    log_len: jnp.ndarray
    kv: jnp.ndarray              # [KEYS]
    next_idx: jnp.ndarray        # [N] entries known replicated per peer
    match_idx: jnp.ndarray       # [N]
    election_deadline: jnp.ndarray
    last_hb: jnp.ndarray
    leader_hint: jnp.ndarray     # last known leader (for client proxying,
                                 # the role of raft.py:552-571); -1 unknown
    truncated_committed: jnp.ndarray  # sticky witness: this node once
                                      # overwrote an entry below its own
                                      # commit index (impossible in
                                      # correct Raft; the local signature
                                      # of the §5.4.2 commit bug)


class RaftModel(Model):
    name = "lin-kv"
    body_lanes = 12           # AppendEntries header (6) + entry_lanes
    entry_lanes = ENTRY_LANES  # log entry width; replicated-state-machine
                               # subclasses (txn models) widen this
    max_out = 1
    idempotent_fs = (F_READ,)

    # body lane used as the proxy-forward hop counter in client requests
    # (a lane the workload's request encoding leaves free)
    proxy_hops_lane = 3

    # correctness switches — the bug-injection corpus (models/raft_buggy)
    # flips these to produce broken-but-plausible variants; they are
    # python bools, so each variant compiles to its own specialized graph
    vote_check_voted_for = True    # False: grants multiple votes per term
    vote_check_log = True          # False: ignores log recency in votes
    vote_check_log_index = True    # False: recency compares terms only —
                                   # a shorter-log candidate can win and
                                   # overwrite committed entries
    serve_reads_locally = False    # True: reads bypass the log (stale)
    commit_term_guard = True       # False: Raft §5.4.2 commit bug
    commit_quorum = True           # False: leader commits at the MAX
                                   # match index (no majority), losing
                                   # unreplicated entries on failover
    apply_uncommitted = False      # True: apply+reply at append, not
                                   # commit (dirty apply — txn mutant)

    def __init__(self, n_nodes_hint: int = 5, log_cap: int = 96,
                 n_keys: int = 8, n_vals: int = 8,
                 elect_min: int = 60, elect_jitter: int = 60,
                 heartbeat: int = 15, apply_max: int = 2):
        self.n_nodes_hint = n_nodes_hint
        self.log_cap = log_cap
        self.n_keys = n_keys
        self.n_vals = n_vals
        self.elect_min = elect_min
        self.elect_jitter = elect_jitter
        self.heartbeat = heartbeat
        self.apply_max = apply_max
        # tick emits: (N-1) vote-or-append sends + apply_max client replies
        self.tick_out = (n_nodes_hint - 1) + apply_max

    def _config(self):
        return (self.n_nodes_hint, self.log_cap, self.n_keys, self.n_vals,
                self.elect_min, self.elect_jitter, self.heartbeat,
                self.apply_max)

    def __hash__(self):
        return hash((type(self), self._config()))

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._config() == other._config())

    def init_row(self, n_nodes, node_idx, key, params):
        assert n_nodes == self.n_nodes_hint
        jitter = jax.random.randint(key, (), 0, self.elect_jitter)
        return RaftRow(
            term=jnp.int32(0),
            voted_for=jnp.int32(-1),
            role=jnp.int32(0),
            votes=jnp.int32(0),
            commit_idx=jnp.int32(0),
            last_applied=jnp.int32(0),
            log_term=jnp.zeros((self.log_cap,), jnp.int32),
            log_body=jnp.zeros((self.log_cap, self.entry_lanes),
                               jnp.int32),
            log_len=jnp.int32(0),
            kv=self._init_kv(),
            next_idx=jnp.zeros((n_nodes,), jnp.int32),
            match_idx=jnp.zeros((n_nodes,), jnp.int32),
            election_deadline=(self.elect_min + jitter).astype(jnp.int32),
            last_hb=jnp.int32(0),
            leader_hint=jnp.int32(-1),
            truncated_committed=jnp.int32(0),
        )

    # --- replicated-state-machine hooks (overridden by txn models) -------

    def _init_kv(self):
        """The applied-state tensor living in RaftRow.kv."""
        return jnp.full((self.n_keys,), NIL, jnp.int32)

    def _is_client_request(self, mtype):
        # T_READ..T_CAS are contiguous (1..3): one range test instead
        # of three equality ors — same values on every int32
        return (mtype >= T_READ) & (mtype <= T_CAS)

    def _encode_entry(self, msg, src):
        """Client request message -> log entry row [entry_lanes]
        (lane-contiguous: f, the three op lanes, src, msg id). The f
        code IS the wire type for client requests (T_READ..T_CAS ==
        F_READ..F_CAS == 1..3); for any other message type the encoded
        row is garbage either way — both the legacy and the fused node
        step only ever commit it to the log under cli_accept, which
        implies a client request."""
        return jnp.concatenate(
            [msg[wire.TYPE:wire.TYPE + 1],
             msg[wire.BODY:wire.BODY + 3], src[None],
             msg[wire.MSGID:wire.MSGID + 1]])

    # --- helpers ----------------------------------------------------------

    def _last_log_term(self, row: RaftRow):
        return jnp.where(row.log_len > 0,
                         row.log_term[jnp.maximum(row.log_len - 1, 0)], 0)

    def _step_down(self, row: RaftRow, new_term, t):
        """Adopt a higher term as follower."""
        higher = new_term > row.term
        return row._replace(
            term=jnp.where(higher, new_term, row.term),
            role=jnp.where(higher, 0, row.role),
            voted_for=jnp.where(higher, -1, row.voted_for),
            votes=jnp.where(higher, 0, row.votes),
        )

    def _reset_election(self, row: RaftRow, t, key):
        jitter = jax.random.randint(key, (), 0, self.elect_jitter)
        return row._replace(
            election_deadline=(t + self.elect_min + jitter).astype(
                jnp.int32))

    def _reply(self, cfg, dest, type_, reply_to, body_vals):
        return wire.make_msg(src=0, dest=dest, type_=type_,
                             reply_to=reply_to, body=body_vals,
                             body_lanes=self.body_lanes,
                             netid=cfg.netid)[None]

    # --- message handlers -------------------------------------------------

    def handle(self, row: RaftRow, node_idx, msg, t, key, cfg, params):
        """Fused single-pass handler: every RaftRow field is computed once
        across all message types, and the log is touched by exactly ONE
        drop-mode scatter — no full-log selects. (The per-type pick()
        formulation cost ~5 full-state wheres per inbox slot and dominated
        the tick; this shape is ~2x faster end-to-end.) Self-gating: an
        invalid message has type 0, which matches no branch, so state is
        unchanged and the out row stays invalid."""
        mtype = msg[wire.TYPE]
        src = msg[wire.SRC]
        body0 = msg[wire.BODY]
        n = cfg.n_nodes

        is_vote = mtype == T_REQ_VOTE
        is_vrep = mtype == T_VOTE_REPLY
        is_ae = mtype == T_APPEND
        is_arep = mtype == T_APPEND_REPLY
        is_cli = self._is_client_request(mtype)
        is_proto = is_vote | is_vrep | is_ae | is_arep

        # --- term adoption / step-down (every protocol message carries
        # the sender term in body lane 0)
        higher = is_proto & (body0 > row.term)
        term = jnp.where(higher, body0, row.term)
        role = jnp.where(higher, 0, row.role)
        voted_for = jnp.where(higher, -1, row.voted_for)
        votes = jnp.where(higher, 0, row.votes)

        # --- RequestVote
        c_lli = msg[wire.BODY + 1]
        c_llt = msg[wire.BODY + 2]
        my_llt = self._last_log_term(row)
        if self.vote_check_log_index:
            log_ok = (c_llt > my_llt) | ((c_llt == my_llt)
                                         & (c_lli >= row.log_len))
        else:
            # BUG variant: recency compares terms only — a shorter-log
            # candidate at the same term can win and truncate entries
            log_ok = c_llt >= my_llt
        grant = is_vote & (body0 == term)
        if self.vote_check_voted_for:
            grant = grant & ((voted_for == -1) | (voted_for == src))
        if self.vote_check_log:
            grant = grant & log_ok
        voted_for = jnp.where(grant, src, voted_for)

        # --- VoteReply
        granted = is_vrep & (msg[wire.BODY + 1] == 1)
        count_it = (role == 1) & (body0 == term) & granted
        votes = jnp.where(count_it,
                          votes | (1 << src).astype(jnp.int32), votes)
        n_votes = jnp.sum((votes[None] >> jnp.arange(n)) & 1) + 1  # + self
        win = count_it & (n_votes > n // 2)
        role = jnp.where(win, 2, role)

        # --- AppendEntries
        prev_idx = msg[wire.BODY + 1]
        prev_term = msg[wire.BODY + 2]
        l_commit = msg[wire.BODY + 3]
        n_entries = msg[wire.BODY + 4]
        e_term = msg[wire.BODY + 5]
        e_body = msg[wire.BODY + 6:wire.BODY + 6 + self.entry_lanes]
        ae_current = is_ae & (body0 == term)
        # current-term AE: candidate steps down, sender is the leader hint
        role = jnp.where(ae_current & (role == 1), 0, role)
        leader_hint = jnp.where(ae_current, src, row.leader_hint)
        prev_ok = (prev_idx == 0) | (
            (prev_idx <= row.log_len)
            & (row.log_term[jnp.clip(prev_idx - 1, 0, self.log_cap - 1)]
               == prev_term))
        fits = prev_idx < self.log_cap
        accept = ae_current & prev_ok & ((n_entries == 0) | fits)
        ae_write = accept & (n_entries == 1)
        ae_widx = jnp.clip(prev_idx, 0, self.log_cap - 1)
        same = (row.log_len > prev_idx) & (row.log_term[ae_widx] == e_term)
        ae_len = jnp.where(
            ae_write,
            jnp.where(same, jnp.maximum(row.log_len, prev_idx + 1),
                      prev_idx + 1),
            row.log_len)
        match_ack = jnp.where(accept, prev_idx + n_entries, 0)

        # --- client request (append to own log as leader, else proxy)
        is_leader = role == 2
        cli_accept = is_cli & is_leader & (row.log_len < self.log_cap)
        stale_read = jnp.bool_(False)
        if self.serve_reads_locally:
            # BUG variant: reads bypass the log entirely
            stale_read = is_cli & (mtype == T_READ)
            cli_accept = cli_accept & ~stale_read
        cli_entry = self._encode_entry(msg, src)
        hops = msg[wire.BODY + self.proxy_hops_lane]
        forward = (is_cli & ~cli_accept & ~stale_read
                   & (row.leader_hint >= 0)
                   & (row.leader_hint != node_idx) & (hops < 3))

        # --- the single log write (AE entry or client append; exclusive)
        write = ae_write | cli_accept
        widx = jnp.where(ae_write, ae_widx, row.log_len)
        slot = jnp.where(write, jnp.clip(widx, 0, self.log_cap - 1),
                         self.log_cap)
        w_term = jnp.where(ae_write, e_term, term)
        w_body = jnp.where(ae_write, e_body, cli_entry)
        log_term = row.log_term.at[slot].set(w_term, mode="drop")
        log_body = row.log_body.at[slot].set(w_body, mode="drop")
        log_len = jnp.where(cli_accept, row.log_len + 1, ae_len)

        # Leader-Completeness witness: a conflicting AppendEntries write
        # below this node's own commit index overwrites a committed
        # entry. Correct Raft can never do this; the no-term-guard
        # mutant does, on the Figure-8 schedule.
        truncated_committed = row.truncated_committed | (
            ae_write & ~same & (ae_widx < row.commit_idx)).astype(jnp.int32)

        # --- commit advance (Raft §5.3: min(leaderCommit, last new entry))
        commit_idx = jnp.where(
            accept,
            jnp.maximum(row.commit_idx,
                        jnp.minimum(l_commit, match_ack)),
            row.commit_idx)

        # --- AppendEntriesReply bookkeeping (leader side)
        r_success = msg[wire.BODY + 1] == 1
        r_match = msg[wire.BODY + 2]
        mine = is_arep & is_leader & (body0 == term)
        src_c = jnp.clip(src, 0, n - 1)
        nxt = row.next_idx[src_c]
        nxt = jnp.where(mine & r_success, jnp.maximum(nxt, r_match),
                        jnp.where(mine & ~r_success,
                                  jnp.maximum(nxt - 1, 0), nxt))
        next_idx = row.next_idx.at[src_c].set(nxt)
        # on winning an election: reset replication state
        next_idx = jnp.where(win, row.log_len, next_idx)
        mtch = jnp.where(mine & r_success,
                         jnp.maximum(row.match_idx[src_c], r_match),
                         row.match_idx[src_c])
        match_idx = row.match_idx.at[src_c].set(mtch)
        match_idx = jnp.where(win, jnp.zeros_like(match_idx), match_idx)
        match_idx = match_idx.at[node_idx].set(
            jnp.where(win, row.log_len, match_idx[node_idx]))
        match_idx = jnp.where(
            cli_accept,
            match_idx.at[node_idx].set(row.log_len + 1), match_idx)
        last_hb = jnp.where(win, t - self.heartbeat, row.last_hb)

        # --- election timer: reset on vote grant or current-term AE
        jitter = jax.random.randint(key, (), 0, self.elect_jitter)
        election_deadline = jnp.where(
            grant | ae_current,
            (t + self.elect_min + jitter).astype(jnp.int32),
            row.election_deadline)

        row = RaftRow(term=term, voted_for=voted_for, role=role,
                      votes=votes, commit_idx=commit_idx,
                      last_applied=row.last_applied, log_term=log_term,
                      log_body=log_body, log_len=log_len, kv=row.kv,
                      next_idx=next_idx, match_idx=match_idx,
                      election_deadline=election_deadline,
                      last_hb=last_hb, leader_hint=leader_hint,
                      truncated_committed=truncated_committed)

        # --- the single out row
        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        reply_needed = is_vote | is_ae | (is_cli & ~cli_accept)
        out = out.at[0, wire.VALID].set(
            jnp.where(reply_needed, 1, 0))
        out = out.at[0, wire.DEST].set(
            jnp.where(forward, row.leader_hint, src))
        out = out.at[0, wire.TYPE].set(
            jnp.where(is_vote, T_VOTE_REPLY,
                      jnp.where(is_ae, T_APPEND_REPLY,
                                jnp.where(forward, mtype, TYPE_ERROR))))
        out = out.at[0, wire.REPLYTO].set(
            jnp.where(forward, -1, msg[wire.MSGID]))
        # body lanes: a forward echoes the full request body (hops lane
        # bumped); protocol replies use lanes 0..2; rejections carry
        # error code 11 in lane 0
        fwd_body = jax.lax.dynamic_slice(
            msg, (wire.BODY,), (self.body_lanes,)
        ).at[self.proxy_hops_lane].add(1)
        proto_body = jnp.zeros((self.body_lanes,), jnp.int32)
        proto_body = proto_body.at[0].set(
            jnp.where(is_vote | is_ae, term, 11))
        proto_body = proto_body.at[1].set(
            jnp.where(is_vote, grant.astype(jnp.int32),
                      jnp.where(is_ae, accept.astype(jnp.int32), 0)))
        proto_body = proto_body.at[2].set(
            jnp.where(is_ae, match_ack, 0))
        out = jax.lax.dynamic_update_slice(
            out, jnp.where(forward, fwd_body, proto_body)[None],
            (0, wire.BODY))
        # a forwarded request keeps the client's msg_id and logical src
        out = out.at[0, wire.MSGID].set(
            jnp.where(forward, msg[wire.MSGID], -1))
        out = out.at[0, wire.SRC].set(jnp.where(forward, src, 0))
        if self.serve_reads_locally:
            kk = jnp.clip(msg[wire.BODY], 0, self.n_keys - 1)
            out = out.at[0, wire.VALID].set(
                jnp.where(stale_read, 1, out[0, wire.VALID]))
            out = out.at[0, wire.DEST].set(
                jnp.where(stale_read, src, out[0, wire.DEST]))
            out = out.at[0, wire.TYPE].set(
                jnp.where(stale_read, T_READ_OK, out[0, wire.TYPE]))
            out = out.at[0, wire.REPLYTO].set(
                jnp.where(stale_read, msg[wire.MSGID],
                          out[0, wire.REPLYTO]))
            out = out.at[0, wire.MSGID].set(
                jnp.where(stale_read, -1, out[0, wire.MSGID]))
            out = out.at[0, wire.SRC].set(
                jnp.where(stale_read, 0, out[0, wire.SRC]))
            out = out.at[0, wire.BODY].set(
                jnp.where(stale_read, kk, out[0, wire.BODY]))
            out = out.at[0, wire.BODY + 1].set(
                jnp.where(stale_read, row.kv[kk], out[0, wire.BODY + 1]))
            out = out.at[0, wire.BODY + 3].set(
                jnp.where(stale_read, 0, out[0, wire.BODY + 3]))
        return row, out

    # --- per-tick behavior ------------------------------------------------

    def tick(self, row: RaftRow, node_idx, t, key, cfg, params):
        n = cfg.n_nodes
        k_elect, k_jit = jax.random.split(key)

        # 1) election timeout -> candidacy
        timeout = (row.role != 2) & (t >= row.election_deadline)
        row = row._replace(
            term=jnp.where(timeout, row.term + 1, row.term),
            role=jnp.where(timeout, 1, row.role),
            voted_for=jnp.where(timeout, node_idx, row.voted_for),
            votes=jnp.where(timeout, 0, row.votes),
            # make the first vote solicitation fire immediately
            last_hb=jnp.where(timeout, t - self.heartbeat, row.last_hb),
            # suspected-dead leader: stop proxying to it
            leader_hint=jnp.where(timeout, -1, row.leader_hint),
        )
        # _reset_election only moves the deadline — select just that
        # field rather than a full-pytree where (which would lean on
        # XLA's select(p, x, x) simplification to avoid copying logs)
        row = row._replace(election_deadline=jnp.where(
            timeout,
            self._reset_election(row, t, k_jit).election_deadline,
            row.election_deadline))

        # 2) leader: advance commit to the median match index (current
        # term only), then apply
        is_leader = row.role == 2
        match = row.match_idx.at[node_idx].set(row.log_len)
        if self.commit_quorum:
            sorted_match = jnp.sort(match)               # ascending
            majority_match = sorted_match[(n - 1) // 2]  # >= on majority
        else:
            # BUG variant: commit at the MAX match index — i.e. as soon
            # as ANY single node (incl. the leader itself) holds the
            # entry, no majority required; failover loses those entries
            majority_match = jnp.max(match)
        if self.commit_term_guard:
            guard_idx = jnp.clip(majority_match - 1, 0, self.log_cap - 1)
            current_term_ok = row.log_term[guard_idx] == row.term
        else:
            # BUG variant (Raft §5.4.2): commit on replication count alone
            current_term_ok = jnp.bool_(True)
        new_commit = jnp.where(
            is_leader & (majority_match > row.commit_idx)
            & current_term_ok,
            majority_match, row.commit_idx)
        row = row._replace(commit_idx=new_commit, match_idx=match)

        # 3) apply up to apply_max committed entries; leader replies
        outs = []
        for _ in range(self.apply_max):
            row, reply = self._apply_one(row, cfg)
            outs.append(reply)

        # 4) peer sends: candidates solicit votes (re-solicit on the same
        # cadence to survive loss), leaders replicate
        is_leader = row.role == 2
        solicit = (row.role == 1) & (t - row.last_hb >= self.heartbeat)
        hb_due = is_leader & (t - row.last_hb >= self.heartbeat)
        row = row._replace(
            last_hb=jnp.where(hb_due | solicit, t, row.last_hb))
        peer_msgs = self._peer_sends(row, node_idx, t, solicit, hb_due, cfg)
        outs.append(peer_msgs)
        return row, jnp.concatenate(outs, axis=0)

    def _apply_frontier(self, row: RaftRow):
        """(do, aidx, entry) for the next entry to apply; the dirty-apply
        mutant's frontier is the raw log end instead of the commit index."""
        frontier = (row.log_len if self.apply_uncommitted
                    else row.commit_idx)
        do = row.last_applied < frontier
        aidx = jnp.clip(row.last_applied, 0, self.log_cap - 1)
        return do, aidx, row.log_body[aidx]

    def _apply_one(self, row: RaftRow, cfg):
        do, aidx, entry = self._apply_frontier(row)
        f, k, a, b, client, cmsg = (entry[0], entry[1], entry[2], entry[3],
                                    entry[4], entry[5])
        k = jnp.clip(k, 0, self.n_keys - 1)
        cur = row.kv[k]
        cas_ok = cur == a
        new_val = jnp.where(f == F_WRITE, a,
                            jnp.where((f == F_CAS) & cas_ok, b, cur))
        kv = jnp.where(do, row.kv.at[k].set(new_val), row.kv)
        row = row._replace(
            kv=kv, last_applied=jnp.where(do, row.last_applied + 1,
                                          row.last_applied))

        # leader replies to the waiting client
        reply_type = jnp.where(
            f == F_READ, T_READ_OK,
            jnp.where(f == F_WRITE, T_WRITE_OK,
                      jnp.where(cas_ok, T_CAS_OK, TYPE_ERROR)))
        err_code = jnp.where(cur == NIL, 20, 22)
        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        out = out.at[0, wire.VALID].set(
            jnp.where(do & (row.role == 2), 1, 0))
        out = out.at[0, wire.DEST].set(client)
        out = out.at[0, wire.TYPE].set(reply_type)
        out = out.at[0, wire.REPLYTO].set(cmsg)
        # read replies carry (key, value); cas errors carry the code
        out = out.at[0, wire.BODY].set(
            jnp.where(reply_type == TYPE_ERROR, err_code, k))
        out = out.at[0, wire.BODY + 1].set(cur)
        return row, out

    def _peer_sends(self, row: RaftRow, node_idx, t, solicit, hb_due, cfg):
        """One message per peer slot (N-1 rows): RequestVote when a
        soliciting candidate, AppendEntries on the leader's heartbeat
        cadence."""
        n = cfg.n_nodes
        # peers = all nodes except self, packed into n-1 slots
        slots = jnp.arange(n - 1, dtype=jnp.int32)
        peers = jnp.where(slots >= node_idx, slots + 1, slots)

        def per_peer(peer):
            vote_body = [row.term, row.log_len, self._last_log_term(row)]
            prev_idx = row.next_idx[peer]
            has_entry = row.log_len > prev_idx
            eidx = jnp.clip(prev_idx, 0, self.log_cap - 1)
            pidx = jnp.clip(prev_idx - 1, 0, self.log_cap - 1)
            append_body = [row.term, prev_idx,
                           jnp.where(prev_idx > 0, row.log_term[pidx], 0),
                           row.commit_idx,
                           has_entry.astype(jnp.int32),
                           row.log_term[eidx]]
            out = jnp.zeros((cfg.lanes,), dtype=jnp.int32)
            send_vote = solicit
            send_append = hb_due
            out = out.at[wire.VALID].set(
                jnp.where(send_vote | send_append, 1, 0))
            out = out.at[wire.DEST].set(peer)
            out = out.at[wire.TYPE].set(
                jnp.where(send_vote, T_REQ_VOTE, T_APPEND))
            for i, v in enumerate(vote_body):
                out = out.at[wire.BODY + i].set(
                    jnp.where(send_vote, v, append_body[i]))
            for i in range(len(vote_body), len(append_body)):
                out = out.at[wire.BODY + i].set(
                    jnp.where(send_vote, 0, append_body[i]))
            entry = row.log_body[eidx] * has_entry.astype(jnp.int32)
            out = jax.lax.dynamic_update_slice(
                out, jnp.where(send_vote, 0, entry),
                (wire.BODY + 6,))
            return out

        return jax.vmap(per_peer)(peers)

    # --- fused node step (models/raft_core.py) ---------------------------
    #
    # The runtime drives raft-family models through the
    # compartmentalized kernel: batched inbox decode, a minimal
    # unrolled sequential core, batched reply assembly, and a
    # deduplicated apply loop. handle()/tick()/_apply_one() above stay
    # as the bit-identity reference oracle (tests/test_node_fusion.py)
    # and for host-side single-message debugging.

    fused_node = True

    def node_rng(self, mkeys):
        return raft_core.node_rng(self, mkeys)

    def inbox_step(self, row, node_idx, msg, rng, t, cfg, params):
        return raft_core.inbox_step(self, row, node_idx, msg, rng, t,
                                    cfg)

    def fused_tick(self, row, node_idx, t, rng, cfg, params):
        return raft_core.fused_tick(self, row, node_idx, t, rng, cfg)

    def apply_entry(self, row, do, entry, cfg):
        """Apply ONE committed log entry to the KV state machine and
        build the leader's client reply row — the per-model hook under
        :func:`raft_core.fused_tick`'s shared apply loop. Mirrors
        :meth:`_apply_one` value-for-value (the last_applied advance
        lives in the shared loop; SRC/ORIGIN are stamped there too)."""
        f, k = entry[0], entry[1]
        a, b = entry[2], entry[3]
        client, cmsg = entry[4], entry[5]
        z0 = f * 0
        k = iclip(k, z0, z0 + (self.n_keys - 1))  # echoed in the reply
        cur = raft_core.tget(row.kv, k)
        cas_ok = cur == a
        new_val = sel(f == F_WRITE, a, sel((f == F_CAS) & cas_ok, b,
                                           cur))
        row = row._replace(
            kv=sel(do, row.kv.at[k].set(new_val, mode="drop"),
                   row.kv))

        # leader replies to the waiting client; read replies carry
        # (key, value), cas errors the code
        reply_type = sel(f == F_READ, T_READ_OK,
                         sel(f == F_WRITE, T_WRITE_OK,
                             sel(cas_ok, T_CAS_OK, TYPE_ERROR)))
        err_code = sel(cur == NIL, 20, 22)
        z01 = z0[None]
        out = jnp.concatenate([
            (do & (row.role == 2)).astype(jnp.int32)[None], z01,
            client[None], z01, reply_type[None], z01, cmsg[None], z01,
            sel(reply_type == TYPE_ERROR, err_code, k)[None],
            cur[None],
            jnp.zeros((cfg.lanes - wire.BODY - 2,), jnp.int32)])
        return row, out

    # --- crash-restart recovery (maelstrom_tpu/faults/ crash lane) -------
    #
    # Real Raft persists term/votedFor and the log synchronously and
    # rebuilds the state machine by replaying the log on restart; the
    # applied KV + cursors are therefore equivalent-to-durable. The
    # snapshot slab holds exactly that durable subset, and restart
    # rebuilds the row as follower with every volatile field (role,
    # votes, replication cursors, leader hint, timers) reset — so
    # correct Raft stays SAFE under crash-restart with write-through
    # snapshots (snapshot_every=1), which tests/test_faults.py pins.
    # The RaftForgetsSnapshot mutant flips ``recovers_snapshot`` off:
    # an amnesiac reboot that re-votes in old terms and forgets
    # committed entries — the crash lane's planted bug.

    DURABLE_LANES = ("term", "voted_for", "log_term", "log_body",
                     "log_len", "kv", "commit_idx", "last_applied",
                     "truncated_committed")

    recovers_snapshot = True   # False: restart ignores durable storage
                               # (the forget-snapshot planted bug)

    def snapshot_row(self, row: RaftRow):
        """The durable subset (pure field selection, so it applies to
        batched rows in either carry layout)."""
        return {k: getattr(row, k) for k in self.DURABLE_LANES}

    def restart_row(self, n_nodes, node_idx, key, params, snap, t):
        fresh = self.init_row(n_nodes, node_idx, key, params)
        # init_row's timers are relative to tick 0 — re-base on the
        # restart tick (node-local clock under the skew lane)
        fresh = fresh._replace(
            election_deadline=(fresh.election_deadline
                               + t).astype(jnp.int32),
            last_hb=jnp.asarray(t, jnp.int32))
        if not self.recovers_snapshot:
            return fresh     # BUG: cold boot — total state loss
        return fresh._replace(**{k: snap[k] for k in self.DURABLE_LANES})

    # --- on-device invariants --------------------------------------------

    def invariants(self, node_state: RaftRow, cfg, params):
        """Election safety + committed-log agreement, checked every tick
        for every instance (not just the recorded sample):

        - at most one leader per term
        - any two nodes' committed log prefixes agree (terms and bodies)
        - no node ever overwrote an entry below its own commit index
          (sticky per-node witness set in :meth:`handle`)

        These catch the double-vote and no-term-guard corruptions
        on-device even in instances whose histories are never decoded.
        """
        n = cfg.n_nodes
        leaders = node_state.role == 2                     # [N]
        same_term = node_state.term[:, None] == node_state.term[None, :]
        pair = (leaders[:, None] & leaders[None, :] & same_term
                & ~jnp.eye(n, dtype=bool))
        two_leaders = jnp.any(pair)

        # Committed-prefix agreement, checked against the max-commit node
        # instead of all pairs: equivalent detection (if i and j each
        # match the reference on their own committed prefixes, they match
        # each other on the min; conversely any i/ref mismatch IS a pair
        # mismatch since ref's commit is the max) at N comparisons
        # instead of N^2 — this was the tick's single largest
        # intermediate ([N, N, log_cap, entry_lanes] per instance).
        commit = node_state.commit_idx                     # [N]
        ref = jnp.argmax(commit)
        ref_lt = node_state.log_term[ref]                  # [LOGN]
        ref_lb = node_state.log_body[ref]                  # [LOGN, E]
        in_prefix = (jnp.arange(self.log_cap)[None, :]
                     < commit[:, None])                    # [N, LOGN]
        diff = ((node_state.log_term != ref_lt[None, :])
                | jnp.any(node_state.log_body != ref_lb[None], axis=-1))
        log_mismatch = jnp.any(diff & in_prefix)
        overwrote = jnp.any(node_state.truncated_committed > 0)
        return two_leaders | log_mismatch | overwrote

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg, params):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        r = jax.random.uniform(k1)
        kk = jax.random.randint(k2, (), 0, self.n_keys, dtype=jnp.int32)
        v1 = jax.random.randint(k3, (), 0, self.n_vals, dtype=jnp.int32)
        v2 = jax.random.randint(k4, (), 0, self.n_vals, dtype=jnp.int32)
        f = jnp.where(r < 1 / 3, F_READ,
                      jnp.where(r < 2 / 3, F_WRITE, F_CAS))
        return jnp.stack([f, kk, v1, v2])

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        dest = jax.random.randint(key, (), 0, cfg.n_nodes, dtype=jnp.int32)
        mtype = jnp.where(op[0] == F_READ, T_READ,
                          jnp.where(op[0] == F_WRITE, T_WRITE, T_CAS))
        return wire.make_msg(src=0, dest=dest, type_=mtype, msg_id=msg_id,
                             body=(op[1], op[2], op[3]),
                             body_lanes=self.body_lanes,
                             netid=cfg.netid)

    def decode_reply(self, op, msg, cfg, params):
        mtype = msg[wire.TYPE]
        ok = ((mtype == T_READ_OK) | (mtype == T_WRITE_OK)
              | (mtype == T_CAS_OK))
        etype = jnp.where(ok, EV_OK, EV_INFO)
        value = jnp.stack([op[1],
                           jnp.where(mtype == T_READ_OK,
                                     msg[wire.BODY + 1], op[2]),
                           op[3]])
        return etype, value

    # --- host-side decoding ----------------------------------------------

    def invoke_record(self, f, a, b, c):
        if f == F_READ:
            return {"f": "read", "value": [a, None]}
        if f == F_WRITE:
            return {"f": "write", "value": [a, b]}
        return {"f": "cas", "value": [a, [b, c]]}

    def complete_record(self, f, a, b, c, etype):
        if etype != EV_OK:
            return self.invoke_record(f, a, b, c)
        if f == F_READ:
            return {"f": "read", "value": [a, None if b == NIL else b]}
        if f == F_WRITE:
            return {"f": "write", "value": [a, b]}
        return {"f": "cas", "value": [a, [b, c]]}

    def checker(self):
        from ..checkers.linearizable import linearizable_kv_checker
        return lambda history, opts: linearizable_kv_checker(history)
