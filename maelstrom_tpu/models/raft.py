"""Vectorized Raft: a linearizable KV store as a fixed-shape JAX automaton.

The TPU-runtime flagship (SURVEY §7 step 7) — the north-star config where
one chip fuzzes thousands of independent Raft clusters in parallel. The
protocol follows the reference's teaching Raft (demo/python/raft.py:
elections :274-343, log replication :391-445, commit via median
match-index :382-389) re-expressed as pure per-node step functions over
int32 lanes:

- leader election with randomized timeouts, vote bitmasks, term step-down
- log replication one entry per AppendEntries, with conflict truncation
  and next/match index backoff
- commit = median match index, guarded to current-term entries
- all client ops (read/write/cas) go through the log; the leader replies
  at apply time; non-leaders reject with error 11 (temporarily-available),
  which clients treat as a definite failure and retry as fresh ops
- fixed-capacity log (``log_cap``); a full log rejects client ops with
  error 11 (explicit, visible backpressure instead of dynamic growth)

Checked per instance by the WGL linearizability checker
(checkers/linearizable.py), the same boundary the reference's lin-kv
workload hands to Knossos.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..tpu import wire
from ..tpu.runtime import EV_INFO, EV_OK, Model, TYPE_ERROR
from . import raft_core
# the protocol constants live with the shared fusion kernel; re-exported
# here so this module stays the raft vocabulary's import point (the
# wire-schema lint resolves T_* against the model's module)
from .raft_core import (ENTRY_LANES, F_CAS, F_READ, F_WRITE, NIL,  # noqa: F401
                        T_APPEND, T_APPEND_REPLY, T_CAS, T_CAS_OK,
                        T_READ, T_READ_OK, T_REQ_VOTE, T_VOTE_REPLY,
                        T_WRITE, T_WRITE_OK, full_member_mask, iclip,
                        sel)


class RaftRow(NamedTuple):
    """Per-node Raft state (the lanes of one row of the cluster tensor)."""
    term: jnp.ndarray
    voted_for: jnp.ndarray
    role: jnp.ndarray            # 0 follower / 1 candidate / 2 leader
    votes: jnp.ndarray           # bitmask of granted votes
    commit_idx: jnp.ndarray      # number of committed entries
    last_applied: jnp.ndarray
    log_term: jnp.ndarray        # [LOGN]
    log_body: jnp.ndarray        # [LOGN, ENTRY_LANES]
    log_len: jnp.ndarray
    kv: jnp.ndarray              # [KEYS]
    next_idx: jnp.ndarray        # [N] entries known replicated per peer
    match_idx: jnp.ndarray       # [N]
    election_deadline: jnp.ndarray
    last_hb: jnp.ndarray
    leader_hint: jnp.ndarray     # last known leader (for client proxying,
                                 # the role of raft.py:552-571); -1 unknown
    truncated_committed: jnp.ndarray  # sticky witness: this node once
                                      # overwrote an entry below its own
                                      # commit index (impossible in
                                      # correct Raft; the local signature
                                      # of the §5.4.2 commit bug)
    cfg_boot: jnp.ndarray        # provisioning member bitmask: the
                                 # cluster config a node with NO config
                                 # entry in its log uses (the initial
                                 # membership at init; re-stamped by
                                 # join_row when a blank node is
                                 # provisioned mid-run). Full bitmask
                                 # on membership-free runs.
    caught_up: jnp.ndarray       # 0 while a joining node lacks the
                                 # committed prefix (it neither votes
                                 # nor stands, Raft §6's non-voting
                                 # learner phase); set sticky by the
                                 # first AppendEntries accept whose
                                 # leader-commit fits the local log.
                                 # 1 from init everywhere membership
                                 # never changes.


class RaftModel(Model):
    name = "lin-kv"
    checker_name = "linearizable-kv"
    body_lanes = 12           # AppendEntries header (6) + entry_lanes
    entry_lanes = ENTRY_LANES  # log entry width; replicated-state-machine
                               # subclasses (txn models) widen this
    max_out = 1
    idempotent_fs = (F_READ,)

    # body lane used as the proxy-forward hop counter in client requests
    # (a lane the workload's request encoding leaves free)
    proxy_hops_lane = 3

    # correctness switches — the bug-injection corpus (models/raft_buggy)
    # flips these to produce broken-but-plausible variants; they are
    # python bools, so each variant compiles to its own specialized graph
    vote_check_voted_for = True    # False: grants multiple votes per term
    vote_check_log = True          # False: ignores log recency in votes
    vote_check_log_index = True    # False: recency compares terms only —
                                   # a shorter-log candidate can win and
                                   # overwrite committed entries
    serve_reads_locally = False    # True: reads bypass the log (stale)
    commit_term_guard = True       # False: Raft §5.4.2 commit bug
    commit_quorum = True           # False: leader commits at the MAX
                                   # match index (no majority), losing
                                   # unreplicated entries on failover
    apply_uncommitted = False      # True: apply+reply at append, not
                                   # commit (dirty apply — txn mutant)
    joint_dual_quorum = True       # False: elections/commits during a
                                   # joint (C_old,new) phase consult
                                   # ONLY the new config — the single-
                                   # quorum reconfiguration bug
    join_requires_catchup = True   # False: a joining node votes and
                                   # stands for election before it
                                   # holds the committed prefix (an
                                   # empty-log joiner elects stale
                                   # leaders — the votes-before-
                                   # catchup reconfiguration bug)

    def __init__(self, n_nodes_hint: int = 5, log_cap: int = 96,
                 n_keys: int = 8, n_vals: int = 8,
                 elect_min: int = 60, elect_jitter: int = 60,
                 heartbeat: int = 15, apply_max: int = 2):
        self.n_nodes_hint = n_nodes_hint
        self.log_cap = log_cap
        self.n_keys = n_keys
        self.n_vals = n_vals
        self.elect_min = elect_min
        self.elect_jitter = elect_jitter
        self.heartbeat = heartbeat
        self.apply_max = apply_max
        # tick emits: (N-1) vote-or-append sends + apply_max client replies
        self.tick_out = (n_nodes_hint - 1) + apply_max

    def _config(self):
        return (self.n_nodes_hint, self.log_cap, self.n_keys, self.n_vals,
                self.elect_min, self.elect_jitter, self.heartbeat,
                self.apply_max)

    def __hash__(self):
        return hash((type(self), self._config()))

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._config() == other._config())

    def init_row(self, n_nodes, node_idx, key, params):
        assert n_nodes == self.n_nodes_hint
        jitter = jax.random.randint(key, (), 0, self.elect_jitter)
        return RaftRow(
            term=jnp.int32(0),
            voted_for=jnp.int32(-1),
            role=jnp.int32(0),
            votes=jnp.int32(0),
            commit_idx=jnp.int32(0),
            last_applied=jnp.int32(0),
            log_term=jnp.zeros((self.log_cap,), jnp.int32),
            log_body=jnp.zeros((self.log_cap, self.entry_lanes),
                               jnp.int32),
            log_len=jnp.int32(0),
            kv=self._init_kv(),
            next_idx=jnp.zeros((n_nodes,), jnp.int32),
            match_idx=jnp.zeros((n_nodes,), jnp.int32),
            election_deadline=(self.elect_min + jitter).astype(jnp.int32),
            last_hb=jnp.int32(0),
            leader_hint=jnp.int32(-1),
            truncated_committed=jnp.int32(0),
            cfg_boot=jnp.int32(full_member_mask(n_nodes)),
            caught_up=jnp.int32(1),
        )

    # --- replicated-state-machine hooks (overridden by txn models) -------

    def _init_kv(self):
        """The applied-state tensor living in RaftRow.kv."""
        return jnp.full((self.n_keys,), NIL, jnp.int32)

    def _is_client_request(self, mtype):
        # T_READ..T_CAS are contiguous (1..3): one range test instead
        # of three equality ors — same values on every int32
        return (mtype >= T_READ) & (mtype <= T_CAS)

    def _encode_entry(self, msg, src):
        """Client request message -> log entry row [entry_lanes]
        (lane-contiguous: f, the three op lanes, src, msg id). The f
        code IS the wire type for client requests (T_READ..T_CAS ==
        F_READ..F_CAS == 1..3); for any other message type the encoded
        row is garbage either way — the fused node step only ever
        commits it to the log under cli_accept, which implies a client
        request."""
        return jnp.concatenate(
            [msg[wire.TYPE:wire.TYPE + 1],
             msg[wire.BODY:wire.BODY + 3], src[None],
             msg[wire.MSGID:wire.MSGID + 1]])

    # --- fused node step (models/raft_core.py) ---------------------------
    #
    # The raft family speaks ONLY the compartmentalized fused protocol:
    # the legacy ``handle()``/``tick()`` formulation (the pre-fusion
    # runtime, PR 6's reference oracle) was deleted after its soak
    # window — bit-identity is pinned by the FROZEN pre-refactor golden
    # digests in tests/data/node_fusion_golden.json, which were
    # recorded from the legacy code and can never be regenerated from
    # this tree (tests/test_node_fusion.py).

    fused_node = True

    def node_rng(self, mkeys):
        return raft_core.node_rng(self, mkeys)

    def inbox_step(self, row, node_idx, msg, rng, t, cfg, params):
        return raft_core.inbox_step(self, row, node_idx, msg, rng, t,
                                    cfg)

    def fused_tick(self, row, node_idx, t, rng, cfg, params,
                   m_bits=None):
        return raft_core.fused_tick(self, row, node_idx, t, rng, cfg,
                                    m_bits=m_bits)

    def apply_entry(self, row, do, entry, cfg):
        """Apply ONE committed log entry to the KV state machine and
        build the leader's client reply row — the per-model hook under
        :func:`raft_core.fused_tick`'s shared apply loop. Value-for-
        value the pre-fusion legacy apply, pinned by the frozen goldens
        (the last_applied advance lives in the shared loop; SRC/ORIGIN
        are stamped there too)."""
        f, k = entry[0], entry[1]
        a, b = entry[2], entry[3]
        client, cmsg = entry[4], entry[5]
        z0 = f * 0
        k = iclip(k, z0, z0 + (self.n_keys - 1))  # echoed in the reply
        cur = raft_core.tget(row.kv, k)
        cas_ok = cur == a
        new_val = sel(f == F_WRITE, a, sel((f == F_CAS) & cas_ok, b,
                                           cur))
        row = row._replace(
            kv=sel(do, row.kv.at[k].set(new_val, mode="drop"),
                   row.kv))

        # leader replies to the waiting client; read replies carry
        # (key, value), cas errors the code
        reply_type = sel(f == F_READ, T_READ_OK,
                         sel(f == F_WRITE, T_WRITE_OK,
                             sel(cas_ok, T_CAS_OK, TYPE_ERROR)))
        err_code = sel(cur == NIL, 20, 22)
        z01 = z0[None]
        out = jnp.concatenate([
            (do & (row.role == 2)).astype(jnp.int32)[None], z01,
            client[None], z01, reply_type[None], z01, cmsg[None], z01,
            sel(reply_type == TYPE_ERROR, err_code, k)[None],
            cur[None],
            jnp.zeros((cfg.lanes - wire.BODY - 2,), jnp.int32)])
        return row, out

    # --- crash-restart recovery (maelstrom_tpu/faults/ crash lane) -------
    #
    # Real Raft persists term/votedFor and the log synchronously and
    # rebuilds the state machine by replaying the log on restart; the
    # applied KV + cursors are therefore equivalent-to-durable. The
    # snapshot slab holds exactly that durable subset, and restart
    # rebuilds the row as follower with every volatile field (role,
    # votes, replication cursors, leader hint, timers) reset — so
    # correct Raft stays SAFE under crash-restart with write-through
    # snapshots (snapshot_every=1), which tests/test_faults.py pins.
    # The RaftForgetsSnapshot mutant flips ``recovers_snapshot`` off:
    # an amnesiac reboot that re-votes in old terms and forgets
    # committed entries — the crash lane's planted bug.

    DURABLE_LANES = ("term", "voted_for", "log_term", "log_body",
                     "log_len", "kv", "commit_idx", "last_applied",
                     "truncated_committed", "cfg_boot", "caught_up")
    # caught_up is durable so the crash and membership lanes COMPOSE:
    # a joining learner that crashes before its first fitting
    # AppendEntries accept must restart with caught_up=0 — init_row's
    # fresh row says 1, and restoring everything BUT the gate would
    # let a blank joiner vote after any crash window, which is the
    # VotesBeforeCatchup anomaly in the correct model.

    recovers_snapshot = True   # False: restart ignores durable storage
                               # (the forget-snapshot planted bug)

    def snapshot_row(self, row: RaftRow):
        """The durable subset (pure field selection, so it applies to
        batched rows in either carry layout)."""
        return {k: getattr(row, k) for k in self.DURABLE_LANES}

    def restart_row(self, n_nodes, node_idx, key, params, snap, t):
        fresh = self.init_row(n_nodes, node_idx, key, params)
        # init_row's timers are relative to tick 0 — re-base on the
        # restart tick (node-local clock under the skew lane)
        fresh = fresh._replace(
            election_deadline=(fresh.election_deadline
                               + t).astype(jnp.int32),
            last_hb=jnp.asarray(t, jnp.int32))
        if not self.recovers_snapshot:
            return fresh     # BUG: cold boot — total state loss
        return fresh._replace(**{k: snap[k] for k in self.DURABLE_LANES})

    # --- membership fault lane (maelstrom_tpu/faults/ membership) --------
    #
    # A node whose administrative membership turns ON re-boots through
    # join_row: the crash-restart recovery path (durable slab state +
    # re-based timers) plus the two join-specific moves — the CURRENT
    # target bitmask becomes its provisioning config (a blank machine
    # is told the member list by the operator; a rejoiner's log-derived
    # config wins over it, see raft_core.config_view), and a joiner
    # with an EMPTY log starts as a non-voting learner (caught_up = 0)
    # until the first AppendEntries proves it holds the committed
    # prefix. The VotesBeforeCatchup mutant skips that gate.

    def boot_config(self, node_state, m_bits):
        """Stamp the initial (phase-0) membership as the provisioning
        config — pure leaf restructuring, applied to BATCHED rows at
        init in both carry layouts."""
        return node_state._replace(cfg_boot=jnp.broadcast_to(
            jnp.asarray(m_bits, jnp.int32),
            node_state.cfg_boot.shape))

    def join_row(self, n_nodes, node_idx, key, params, snap, t,
                 m_bits):
        row = self.restart_row(n_nodes, node_idx, key, params, snap, t)
        z0 = row.term * 0
        caught = sel(row.log_len > z0, z0 + 1, z0)
        if not self.join_requires_catchup:
            caught = z0 + 1   # BUG: a blank joiner votes immediately
        return row._replace(cfg_boot=m_bits + z0, caught_up=caught)

    # --- on-device invariants --------------------------------------------

    def invariants(self, node_state: RaftRow, cfg, params):
        """Election safety + committed-log agreement, checked every tick
        for every instance (not just the recorded sample):

        - at most one leader per term
        - any two nodes' committed log prefixes agree (terms and bodies)
        - no node ever overwrote an entry below its own commit index
          (sticky per-node witness set in :meth:`handle`)

        These catch the double-vote and no-term-guard corruptions
        on-device even in instances whose histories are never decoded.
        """
        n = cfg.n_nodes
        leaders = node_state.role == 2                     # [N]
        same_term = node_state.term[:, None] == node_state.term[None, :]
        pair = (leaders[:, None] & leaders[None, :] & same_term
                & ~jnp.eye(n, dtype=bool))
        two_leaders = jnp.any(pair)

        # Committed-prefix agreement, checked against the max-commit node
        # instead of all pairs: equivalent detection (if i and j each
        # match the reference on their own committed prefixes, they match
        # each other on the min; conversely any i/ref mismatch IS a pair
        # mismatch since ref's commit is the max) at N comparisons
        # instead of N^2 — this was the tick's single largest
        # intermediate ([N, N, log_cap, entry_lanes] per instance).
        commit = node_state.commit_idx                     # [N]
        ref = jnp.argmax(commit)
        ref_lt = node_state.log_term[ref]                  # [LOGN]
        ref_lb = node_state.log_body[ref]                  # [LOGN, E]
        in_prefix = (jnp.arange(self.log_cap)[None, :]
                     < commit[:, None])                    # [N, LOGN]
        diff = ((node_state.log_term != ref_lt[None, :])
                | jnp.any(node_state.log_body != ref_lb[None], axis=-1))
        log_mismatch = jnp.any(diff & in_prefix)
        overwrote = jnp.any(node_state.truncated_committed > 0)
        return two_leaders | log_mismatch | overwrote

    def summary_step(self, summ, node_state: RaftRow, events, cfg,
                     params):
        """Committed-prefix device lane: frontier = the fleet's max
        commit index (monotone — commit_idx is a DURABLE_LANE, so even
        crash-restart never rolls the max back on a correct trace);
        hash = the max-commit reference node's committed-prefix rolling
        hash; divergence = committed-prefix hash disagreement at the
        fleet MIN commit (every node has committed that far, so on a
        correct trace all N hashes agree — the O(N·LOGN) shadow of
        invariants' O(N·LOGN·E) entry diff), the sticky overwrote
        witness, or an applied-entry truncation: ``last_applied`` never
        rolls back and on a correct trace applied <= committed <= log
        end, so a log end BELOW it means an applied entry vanished —
        exactly the dirty-apply family's lost acked txns (those models
        reply at apply time), invisible to the committed-prefix lanes
        because the truncated entries were never committed."""
        from ..checkers import device_summary
        del events
        commit = node_state.commit_idx                     # [N]
        frontier = jnp.max(commit)
        ref = jnp.argmax(commit)
        pos = jnp.arange(self.log_cap, dtype=jnp.int32)
        h = device_summary.prefix_hash(
            node_state.log_term[ref], node_state.log_body[ref],
            pos < frontier)
        in_lo = pos < jnp.min(commit)                      # [LOGN]
        hs = jax.vmap(lambda lt, lb: device_summary.prefix_hash(
            lt, lb, in_lo))(node_state.log_term, node_state.log_body)
        diverged = (jnp.any(hs != hs[ref])
                    | jnp.any(node_state.truncated_committed > 0)
                    | jnp.any(node_state.log_len
                              < node_state.last_applied))
        return device_summary.fold_frontier(summ, frontier, h,
                                            diverged=diverged)

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg, params):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        r = jax.random.uniform(k1)
        kk = jax.random.randint(k2, (), 0, self.n_keys, dtype=jnp.int32)
        v1 = jax.random.randint(k3, (), 0, self.n_vals, dtype=jnp.int32)
        v2 = jax.random.randint(k4, (), 0, self.n_vals, dtype=jnp.int32)
        f = jnp.where(r < 1 / 3, F_READ,
                      jnp.where(r < 2 / 3, F_WRITE, F_CAS))
        return jnp.stack([f, kk, v1, v2])

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        dest = jax.random.randint(key, (), 0, cfg.n_nodes, dtype=jnp.int32)
        mtype = jnp.where(op[0] == F_READ, T_READ,
                          jnp.where(op[0] == F_WRITE, T_WRITE, T_CAS))
        return wire.make_msg(src=0, dest=dest, type_=mtype, msg_id=msg_id,
                             body=(op[1], op[2], op[3]),
                             body_lanes=self.body_lanes,
                             netid=cfg.netid)

    def decode_reply(self, op, msg, cfg, params):
        mtype = msg[wire.TYPE]
        ok = ((mtype == T_READ_OK) | (mtype == T_WRITE_OK)
              | (mtype == T_CAS_OK))
        etype = jnp.where(ok, EV_OK, EV_INFO)
        value = jnp.stack([op[1],
                           jnp.where(mtype == T_READ_OK,
                                     msg[wire.BODY + 1], op[2]),
                           op[3]])
        return etype, value

    # --- host-side decoding ----------------------------------------------

    def invoke_record(self, f, a, b, c):
        if f == F_READ:
            return {"f": "read", "value": [a, None]}
        if f == F_WRITE:
            return {"f": "write", "value": [a, b]}
        return {"f": "cas", "value": [a, [b, c]]}

    def complete_record(self, f, a, b, c, etype):
        if etype != EV_OK:
            return self.invoke_record(f, a, b, c)
        if f == F_READ:
            return {"f": "read", "value": [a, None if b == NIL else b]}
        if f == F_WRITE:
            return {"f": "write", "value": [a, b]}
        return {"f": "cas", "value": [a, [b, c]]}

    def checker(self):
        from ..checkers.linearizable import linearizable_kv_checker
        return lambda history, opts: linearizable_kv_checker(history)
