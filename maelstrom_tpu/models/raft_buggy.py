"""Deliberately broken Raft variants — the bug-injection corpus.

The whole point of the workbench is catching consistency bugs; these
mutants prove the TPU runtime + checkers actually do (SURVEY §7 step 8:
"bug-injection corpus (mutated Raft variants) for time-to-first-anomaly",
and the north-star requirement that checkers still find injected
linearizability bugs at scale). Each mutant flips one of
:class:`~.raft.RaftModel`'s static correctness switches, so every variant
compiles to its own specialized graph with the bug baked in.

- :class:`RaftDoubleVote` — nodes ignore ``voted_for`` and log recency
  when granting votes: two leaders per term, divergent logs, lost writes.
- :class:`RaftStaleRead` — nodes answer reads immediately from their
  local KV instead of through the log: a deposed leader (or lagging
  follower) serves stale values during partitions.
- :class:`RaftNoTermGuard` — the leader commits by match-index count
  alone, without the current-term guard (the Raft §5.4.2 trap): an entry
  replicated by an old-term leader can be committed and then overwritten.
  Tripping it needs the Figure-8 schedule (old-term entry replicated to
  a majority, leader deposed, entry overwritten after commit); the
  scripted rotating-majorities nemesis constructs exactly that across a
  fleet of seeds, and the on-device truncated-committed witness flags
  every occurrence (tests/test_tpu_raft.py::
  test_raft_no_term_guard_caught_on_figure8 — caught in ~27% of 128
  instances at 3s horizon; correct Raft stays clean).
- :class:`RaftShortLogWins` — vote recency compares last-log terms only,
  never log length: a same-term shorter-log candidate wins and truncates
  a committed suffix.
- :class:`RaftEagerCommit` — the leader commits at the MAX match index
  (no majority quorum): acknowledged writes it alone holds are lost on
  failover.
- :class:`RaftForgetsSnapshot` — crash-restart recovery ignores the
  durable snapshot slab (fault engine crash lane, maelstrom_tpu/
  faults/): the node reboots amnesiac, re-votes in old terms and loses
  committed entries.
- :class:`RaftFixedTimeout` — election timeouts are deterministic (no
  jitter): nodes time out in lockstep and livelock with no leader —
  the clock-skew lane's liveness anomaly, flagged by the availability
  checker.
- :class:`RaftSingleQuorumReconfig` — joint-consensus elections and
  commits consult only the NEW configuration: a joint-phase leader
  commits with the new minority while the old majority never heard of
  the change — under a remove-majority-then-partition plan the two
  halves commit divergent histories (committed-prefix invariant +
  linearizability trip). The membership lane's first planted bug.
- :class:`RaftVotesBeforeCatchup` — a joining node votes (and stands)
  with an empty log instead of waiting for catch-up: when a majority
  of blank joiners arrives, they elect a stale/empty leader over the
  committed history. The membership lane's second planted bug.
"""

from __future__ import annotations

import jax.numpy as jnp

from .raft import RaftModel


class RaftDoubleVote(RaftModel):
    """Election safety broken: voted_for / log recency never consulted."""
    name = "lin-kv-bug-double-vote"
    vote_check_voted_for = False
    vote_check_log = False


class RaftStaleRead(RaftModel):
    """Linearizable reads broken: any node answers reads locally."""
    name = "lin-kv-bug-stale-read"
    serve_reads_locally = True


class RaftNoTermGuard(RaftModel):
    """Commit safety broken: no current-term guard on the median commit."""
    name = "lin-kv-bug-no-term-guard"
    commit_term_guard = False


class RaftShortLogWins(RaftModel):
    """Vote recency broken: candidates are judged on last-log TERM only,
    never log length — a same-term shorter-log candidate can win an
    election and truncate a majority-replicated (committed) suffix.
    The on-device truncated-committed witness + committed-prefix
    agreement invariant catch the resulting overwrite."""
    name = "lin-kv-bug-short-log-wins"
    vote_check_log_index = False


class RaftForgetsSnapshot(RaftModel):
    """Crash-restart durability broken (the fault engine's crash-lane
    planted bug): restart ignores the snapshot slab and cold-boots with
    term 0, no vote, an empty log, and a blank KV — as if the node kept
    no durable storage at all. Under a crash-restart fault plan the
    amnesiac node re-grants votes it already cast (two leaders per
    term: the on-device election-safety invariant trips) and, when a
    crashed majority reboots together, elects a leader over an empty
    log — committed entries vanish and both the committed-prefix
    agreement invariant and WGL's lost-write detection fire. The
    correct model under the SAME plan recovers from its snapshots and
    stays valid (tests/test_faults.py anomaly matrix)."""
    name = "lin-kv-bug-forget-snapshot"
    recovers_snapshot = False


class RaftFixedTimeout(RaftModel):
    """Randomized election timeouts removed (the clock-skew lane's
    planted bug): every node draws a zero jitter, so election deadlines
    are deterministic and collide — all nodes time out in lockstep,
    vote for themselves, reject each other, and repeat forever. No
    leader is ever elected, no client op ever completes ok, and the
    availability checker flags the livelock, while correct Raft (whose
    randomized timeouts are exactly the mechanism this mutant deletes)
    elects fine under the SAME skewed-clock plan. Raft's liveness
    argument (§5.4's randomized-timeout lemma) made executable."""
    name = "lin-kv-bug-fixed-timeout"

    def __init__(self, n_nodes_hint: int = 5, **kw):
        kw["elect_jitter"] = 1   # randint(0, 1) == 0 always
        super().__init__(n_nodes_hint=n_nodes_hint, **kw)


class RaftEagerCommit(RaftModel):
    """Commit quorum broken: the leader advances commit_idx to the MAX
    match index instead of the majority median — entries are committed
    (and replied to clients) the moment the leader appends them locally.
    A failover to a node without the entry loses an acknowledged write;
    WGL flags the lost update, and committed-prefix agreement trips
    on-device."""
    name = "lin-kv-bug-eager-commit"
    commit_quorum = False


class RaftSingleQuorumReconfig(RaftModel):
    """Joint consensus broken (the membership lane's planted bug #1):
    during a C_old,new phase, elections and commits count ONLY the new
    configuration's quorum — the old majority loses its veto. Under a
    remove-majority-then-partition plan the joint-phase leader commits
    the config change (and client writes) with the tiny new quorum
    while the removed-then-restored old majority, which never saw the
    change, elects its own leader and commits a different history at
    the same indices: the on-device committed-prefix invariant trips,
    the post-heal truncation sets the sticky witness, and WGL flags
    the lost writes. Correct joint-consensus Raft under the SAME plan
    simply stalls the change until both quorums are reachable —
    unavailable for a window, never unsafe."""
    name = "lin-kv-bug-single-quorum-reconfig"
    joint_dual_quorum = False


class RaftVotesBeforeCatchup(RaftModel):
    """Join catch-up broken (the membership lane's planted bug #2): a
    joining node grants votes and stands for election with an EMPTY
    log instead of staying a non-voting learner until it holds the
    committed prefix. Add a majority of blank joiners behind a
    partition and they elect one of themselves — an empty-log leader
    that commits fresh entries over indices the old members hold
    committed (committed-prefix + WGL trip). Correct Raft's learners
    stay mute until an AppendEntries accept proves catch-up, then the
    joint-consensus happy path completes the same reconfiguration
    safely."""
    name = "lin-kv-bug-votes-before-catchup"
    join_requires_catchup = False


BUGGY_MODELS = {
    "double-vote": RaftDoubleVote,
    "stale-read": RaftStaleRead,
    "no-term-guard": RaftNoTermGuard,
    "short-log-wins": RaftShortLogWins,
    "eager-commit": RaftEagerCommit,
    "forget-snapshot": RaftForgetsSnapshot,
    "fixed-timeout": RaftFixedTimeout,
    "single-quorum-reconfig": RaftSingleQuorumReconfig,
    "votes-before-catchup": RaftVotesBeforeCatchup,
}


# --- trace-hygiene lint fixtures -------------------------------------------
#
# The mutants above are PROTOCOL bugs: shape-correct JAX that encodes a
# wrong algorithm — the checkers' prey. The class below is the OTHER bug
# family this corpus must cover: trace-hygiene violations that
# `maelstrom lint` (analysis/trace_lint.py) exists to catch before a
# device run. It is deliberately broken — python control flow on traced
# values, host syncs, hidden mutable state, bare-python RNG — and would
# crash (or silently freeze randomness into the graph) if ever traced.
# It is therefore NOT in BUGGY_MODELS and must never be registered;
# tests/test_analysis_lint.py asserts the linter flags every hazard, and
# analysis/baseline.json carries the findings as status="expected"
# (visible, never silently baselined).

_GOSSIP_LOG = []    # module state a traced fn must not touch


class RaftTracedHazards(RaftModel):
    """LINT FIXTURE (do not register): every TRC-rule hazard in one tick."""
    name = "lin-kv-lint-fixture-traced-hazards"
    # STATICALLY-LINTED-ONLY: this fixture exists for the AST trace
    # lint to read, never to execute — the raft family's legacy
    # handle()/tick() runtime path was deleted (models/raft.py), so
    # driving this class would hit Model.handle's NotImplementedError
    # before any hazard fired. fused_node=False keeps the linter's
    # traced-surface taint on the overridden tick below; super().tick
    # resolves to the abstract base's no-op default.
    fused_node = False

    def tick(self, row, node_idx, t, key, cfg, params):
        import random
        if row.term > 0:                       # TRC101 traced-branch
            row = row._replace(
                term=row.term + int(row.commit_idx))   # TRC104 host sync
        while row.log_len > 0:                 # TRC102 traced-while
            break
        assert row.commit_idx >= 0             # TRC103 traced-assert
        _GOSSIP_LOG.append(t)                  # TRC105 mutable-capture
        jitter = random.randint(0, 3)          # TRC107 bare-python-rng
        hot = jnp.nonzero(row.match_idx)[0]    # TRC106 data-dep shape
        del jitter, hot
        return super().tick(row, node_idx, t, key, cfg, params)


LINT_FIXTURE_MODELS = {"traced-hazards": RaftTracedHazards}
