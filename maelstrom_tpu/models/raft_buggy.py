"""Deliberately broken Raft variants — the bug-injection corpus.

The whole point of the workbench is catching consistency bugs; these
mutants prove the TPU runtime + checkers actually do (SURVEY §7 step 8:
"bug-injection corpus (mutated Raft variants) for time-to-first-anomaly",
and the north-star requirement that checkers still find injected
linearizability bugs at scale).

- :class:`RaftDoubleVote` — nodes ignore ``voted_for`` and grant every
  vote request: two leaders per term, divergent logs, lost writes.
- :class:`RaftStaleRead` — nodes answer reads immediately from their
  local KV instead of through the log: a deposed leader (or lagging
  follower) serves stale values during partitions.
- :class:`RaftNoTermGuard` — the leader commits by match-index count
  alone, without the current-term guard (the Raft §5.4.2 trap): an entry
  replicated by an old-term leader can be committed and then overwritten.
  NOTE: this one requires the full Figure-8 schedule (old-term entry
  replicated to a majority, leader deposed, entry overwritten after
  commit) — rare enough that 32 instances x 3s have not yet tripped it;
  it is in the corpus as a hard target for large-fleet time-to-anomaly
  runs, not in the must-catch CI test.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..tpu import wire
from .raft import RaftModel, RaftRow, T_READ, T_READ_OK, T_VOTE_REPLY


class RaftDoubleVote(RaftModel):
    """Election safety broken: voted_for is never consulted."""

    name = "lin-kv-bug-double-vote"

    def _handle_req_vote(self, row, node_idx, msg, t, key, cfg):
        c_term = msg[wire.BODY]
        src = msg[wire.SRC]
        row = self._step_down(row, c_term, t)
        # BUG: grant to anyone with a current term, regardless of
        # voted_for or log recency
        grant = c_term == row.term
        row = row._replace(voted_for=jnp.where(grant, src, row.voted_for))
        out = self._reply(cfg, src, T_VOTE_REPLY, msg[wire.MSGID],
                          [row.term, grant.astype(jnp.int32)])
        return row, out


class RaftStaleRead(RaftModel):
    """Linearizable reads broken: any node answers reads locally."""

    name = "lin-kv-bug-stale-read"

    def _handle_client(self, row: RaftRow, node_idx, msg, cfg):
        is_read = msg[wire.TYPE] == T_READ
        # BUG: serve reads from the local (possibly stale) KV immediately
        k = jnp.clip(msg[wire.BODY], 0, self.n_keys - 1)
        out_read = self._reply(cfg, msg[wire.SRC], T_READ_OK,
                               msg[wire.MSGID], [k, row.kv[k]])
        row2, out_rest = super()._handle_client(row, node_idx, msg, cfg)
        import jax
        row = jax.tree.map(lambda a, b: jnp.where(is_read, a, b), row, row2)
        out = jnp.where(is_read, out_read, out_rest)
        return row, out


class RaftNoTermGuard(RaftModel):
    """Commit safety broken: no current-term guard on the median commit."""

    name = "lin-kv-bug-no-term-guard"

    def tick(self, row: RaftRow, node_idx, t, key, cfg, params):
        # monkey-see implementation: run the correct tick but first
        # falsify the guard by rewriting log terms the leader checks.
        # Simpler and fully equivalent: pretend every entry is from the
        # current term when computing the guard, by overriding the
        # commit-advance piece. We reuse the parent tick with a patched
        # log_term view for the guard only.
        n = cfg.n_nodes
        is_leader = row.role == 2
        match = row.match_idx.at[node_idx].set(row.log_len)
        sorted_match = jnp.sort(match)
        majority_match = sorted_match[(n - 1) // 2]
        # BUG: advance commit on replication count alone
        new_commit = jnp.where(
            is_leader & (majority_match > row.commit_idx),
            majority_match, row.commit_idx)
        row = row._replace(commit_idx=new_commit)
        return super().tick(row, node_idx, t, key, cfg, params)


BUGGY_MODELS = {
    "double-vote": RaftDoubleVote,
    "stale-read": RaftStaleRead,
    "no-term-guard": RaftNoTermGuard,
}
