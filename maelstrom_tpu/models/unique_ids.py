"""Vectorized unique-ids model: flake-style ids ``node_idx <<
flake_counter_bits | counter`` — coordination-free uniqueness (the TPU
face of the unique-ids workload; reference
src/maelstrom/workload/unique_ids.clj and demo/clojure/flake_ids.clj).

The id-space split is PROVEN, not hand-waved: the range analyzer
(``maelstrom lint --ranges``, analysis/absint.py) bounds the per-node
counter's reachable ceiling at the enforced 2^20-tick horizon (a node
can handle up to ``inbox_k`` generates per tick, so the static bound
is ``inbox_k * 2^20`` — the old 20-bit split was provably thinner than
its hand analysis claimed and was widened here), and the checked-in
``analysis/range_manifest.json`` records the proof. CON204 audits the
declared split arithmetic; ABS701 re-proves it against the traced
dataflow every gate run."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tpu import wire
from ..tpu.runtime import EV_INFO, EV_OK, Model

TYPE_GEN = 1
TYPE_GEN_OK = 2

F_GENERATE = 1


class UniqueIdsModel(Model):
    name = "unique-ids"
    checker_name = "unique-ids"
    body_lanes = 1
    max_out = 1
    tick_out = 0
    idempotent_fs = ()
    # declared id-space split audited by `maelstrom lint` (CON204 and,
    # dataflow-proven, ABS701): ids are node_idx << flake_counter_bits
    # | counter. 25 bits holds the range analyzer's proven counter
    # ceiling (inbox_k * 2^20 < 2^24 under the audit config) with a
    # full doubling of margin; node ids keep 6 bits (<= 63 nodes,
    # int32-checked by CON204). The former 20-bit split was an
    # accepted-debt waiver whose margin the analyzer proved thinner
    # than the hand analysis claimed — widened and re-proven in
    # analysis/range_manifest.json.
    flake_counter_bits = 25
    # schema-conformance map (SCH305): registry RPC name -> wire TYPE
    WIRE_TYPES = {"generate": TYPE_GEN}

    def init_row(self, n_nodes, node_idx, key, params):
        return jnp.int32(0)     # per-node counter

    def handle(self, row, node_idx, msg, t, key, cfg, params):
        is_gen = msg[wire.TYPE] == TYPE_GEN
        row = jnp.where(is_gen, row + 1, row)
        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        out = out.at[0, wire.VALID].set(jnp.where(is_gen, 1, 0))
        out = out.at[0, wire.DEST].set(msg[wire.SRC])
        out = out.at[0, wire.TYPE].set(TYPE_GEN_OK)
        out = out.at[0, wire.REPLYTO].set(msg[wire.MSGID])
        out = out.at[0, wire.BODY].set(
            node_idx * (1 << self.flake_counter_bits) + row)
        return row, out

    def sample_op(self, key, uniq, cfg, params):
        return jnp.array([F_GENERATE, 0, 0, 0], jnp.int32)

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        dest = jax.random.randint(key, (), 0, cfg.n_nodes, dtype=jnp.int32)
        return wire.make_msg(src=0, dest=dest, type_=TYPE_GEN,
                             msg_id=msg_id, body_lanes=self.body_lanes,
                             netid=cfg.netid)

    def decode_reply(self, op, msg, cfg, params):
        ok = msg[wire.TYPE] == TYPE_GEN_OK
        etype = jnp.where(ok, EV_OK, EV_INFO)
        value = jnp.array([0, 0, 0], jnp.int32).at[0].set(msg[wire.BODY])
        return etype, value

    def invoke_record(self, f, a, b, c):
        return {"f": "generate", "value": None}

    def complete_record(self, f, a, b, c, etype):
        return {"f": "generate", "value": int(a) if etype == EV_OK
                else None}

    def checker(self):
        from ..checkers.unique_ids import unique_ids_checker
        return lambda history, opts: unique_ids_checker(history)
