"""Vectorized echo model: the TPU-runtime counterpart of the echo workload
(reference src/maelstrom/workload/echo.clj + demo echo nodes).

Stateless servers; an ``echo`` request is answered with an ``echo_ok``
carrying the same payload lane. This is the minimal end-to-end slice of the
device loop (SURVEY §7 step 5): it proves delivery, client op injection,
history extraction, and checker integration with near-zero protocol logic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tpu import wire
from ..tpu.runtime import (EV_FAIL, EV_INFO, EV_OK, Model, OP_LANES)

TYPE_ECHO = 1
TYPE_ECHO_OK = 2

F_ECHO = 1


class EchoModel(Model):
    name = "echo"
    checker_name = "echo"
    body_lanes = 2
    max_out = 1
    tick_out = 0
    idempotent_fs = (F_ECHO,)

    def init_row(self, n_nodes, node_idx, key, params):
        return jnp.zeros((), dtype=jnp.int32)   # stateless

    def handle(self, row, node_idx, msg, t, key, cfg, params):
        is_echo = msg[wire.TYPE] == TYPE_ECHO
        out = jnp.zeros((1, cfg.lanes), dtype=jnp.int32)
        out = out.at[0, wire.VALID].set(jnp.where(is_echo, 1, 0))
        out = out.at[0, wire.DEST].set(msg[wire.SRC])
        out = out.at[0, wire.TYPE].set(TYPE_ECHO_OK)
        out = out.at[0, wire.REPLYTO].set(msg[wire.MSGID])
        out = out.at[0, wire.BODY].set(msg[wire.BODY])
        return row, out

    # --- client side ------------------------------------------------------

    def sample_op(self, key, uniq, cfg, params):
        payload = jax.random.randint(key, (), 0, 1_000_000, dtype=jnp.int32)
        return jnp.array([F_ECHO, 0, 0, 0], jnp.int32).at[1].set(payload)

    def encode_request(self, op, msg_id, client_idx, key, cfg, params):
        dest = jax.random.randint(key, (), 0, cfg.n_nodes, dtype=jnp.int32)
        return wire.make_msg(src=0, dest=dest, type_=TYPE_ECHO,
                             msg_id=msg_id, body=(op[1],),
                             body_lanes=self.body_lanes,
                             netid=cfg.netid)

    def decode_reply(self, op, msg, cfg, params):
        ok = msg[wire.TYPE] == TYPE_ECHO_OK
        etype = jnp.where(ok, EV_OK, EV_INFO)
        # value lanes: (received payload, sent payload, -)
        value = jnp.array([0, 0, 0], jnp.int32)
        value = value.at[0].set(msg[wire.BODY])
        value = value.at[1].set(op[1])
        return etype, value

    # --- host-side history decoding --------------------------------------

    def invoke_record(self, f, a, b, c):
        return {"f": "echo", "value": int(a)}

    def complete_record(self, f, a, b, c, etype):
        if etype == EV_OK:
            return {"f": "echo", "value": int(b), "echo": int(a)}
        return {"f": "echo", "value": None}

    def checker(self):
        from ..workloads.echo import echo_checker
        return lambda history, opts: echo_checker(history, opts)
